"""Tiered plane storage: hot (HBM) / warm (host) / cold (pack file).

Serving planes were wholly device-resident, so corpus capacity per node
equaled HBM — the one hard wall between this engine and the reference's
frozen-tier / searchable-snapshots story. This module adds the missing
two tiers and the demand-promotion policy between them:

- **hot** — device-resident, today's path (``DistributedSearchPlane`` /
  ``DistributedKnnPlane`` arrays live in HBM; dispatches touch no host
  memory).
- **warm** — host-resident: the plane's packed corpus stays in numpy on
  the host and every dispatch streams it to the device fresh
  (``plane._corpus_refs``); the roofline auditor judges those dispatches
  against the host→device link (``*_streamed`` kernel families), not HBM
  bandwidth. Warm bytes are accounted against the ``host_tier`` breaker
  ledger, NOT the device-side ``accounting`` ledger.
- **cold** — an mmap'd pack file holding ``dumps_b64`` of the plane's
  warm-handoff bundle (``export_packed`` + frozen invariants +
  signature). Demotion is serialize-once + free; promotion is a chunked
  local read through the SAME resumable import path the warm handoff
  uses (``ServingPlaneCache.import_bundle``), and the file text IS the
  handoff blob — a donor offer ships it without re-serializing.

:class:`PlaneTierManager` owns the policy: per-generation access
recency/frequency (``note_dispatch`` from the serving merge, outside
every cache lock), a per-device HBM budget
(``ES_TPU_PLANE_HBM_BUDGET_BYTES``) enforced by LRU demotion, a host
budget (``ES_TPU_PLANE_HOST_BUDGET_BYTES``) that spills warm → cold, and
hit-count hysteresis (``ES_TPU_PLANE_TIER_PROMOTE_HITS``) before a warm
plane earns its HBM back. Every transition journals a ``plane_tier``
flight-recorder event — the tier history of any plane is reconstructable
from the journal alone — and bumps the
``es_plane_tier_{promotions,demotions}_total`` counters; resident bytes
per tier surface as the ``es_plane_tier_bytes{tier=...}`` gauge.

Budgets default to 0 (unlimited): a node that never opts in serves
exactly as before, every plane hot.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from typing import Dict, List, Optional

__all__ = ["ColdPackStore", "PlaneTierRecord", "PlaneTierManager"]

#: bytes per mmap read while reassembling a cold pack file — same order
#: as the warm-handoff chunk size (cluster_node.PLANE_CHUNK_BYTES), so
#: promotion exercises the same resumable chunked-read shape
COLD_READ_CHUNK = 4 << 20


class PlaneTierRecord:
    """One cold-tier plane: pack-file path + the routing metadata needed
    to match it against a segment list WITHOUT reading the file."""

    __slots__ = ("kind", "field", "signature", "path", "nbytes", "ts")

    def __init__(self, kind: str, field: str, signature, path: str,
                 nbytes: int):
        self.kind = kind
        self.field = field
        #: [(seg_id, n_docs), ...] of the bundle's base segment list
        self.signature = [(str(a), int(b)) for a, b in signature]
        self.path = path
        self.nbytes = nbytes
        self.ts = time.monotonic()


class ColdPackStore:
    """Directory of cold pack files. A pack file is the ascii
    ``datacodec.dumps_b64`` text of one warm-handoff bundle dict
    (``{"kind", "field", "avgdl", "signature", "packed"}``) — wire-exact
    with what ``export_bundles`` ships, so the file doubles as the
    recovery/handoff artifact and :meth:`read_blob` serves a donor offer
    with zero re-serialization."""

    def __init__(self, root: Optional[str] = None):
        self.root = root or os.environ.get("ES_TPU_PLANE_SPILL_DIR") or \
            os.path.join(os.environ.get("TMPDIR", "/tmp"),
                         f"es_tpu_plane_spill_{os.getpid()}")
        self._seq = 0
        self._lock = threading.Lock()

    def _next_path(self, kind: str, field: str) -> str:
        os.makedirs(self.root, exist_ok=True)
        safe = "".join(c if c.isalnum() or c in "-_" else "_"
                       for c in field)[:48]
        with self._lock:
            self._seq += 1
            seq = self._seq
        return os.path.join(self.root, f"{kind}_{safe}_{seq:06d}.espack")

    def put(self, bundle: dict) -> PlaneTierRecord:
        """Serialize one handoff bundle to a pack file (atomic: tmp +
        rename) and return its record."""
        from ..common.datacodec import dumps_b64
        blob = dumps_b64(bundle)
        path = self._next_path(str(bundle["kind"]), str(bundle["field"]))
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="ascii") as f:
            f.write(blob)
        os.replace(tmp, path)
        return PlaneTierRecord(str(bundle["kind"]), str(bundle["field"]),
                               bundle.get("signature") or (), path,
                               len(blob))

    def read_blob(self, record: PlaneTierRecord) -> str:
        """The pack file's serialized text, chunk-read through an mmap —
        exactly the blob a warm-handoff donor would ship (no
        re-serialization on donor offer)."""
        import mmap
        with open(record.path, "rb") as f:
            size = os.fstat(f.fileno()).st_size
            if size == 0:
                return ""
            with mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ) as mm:
                parts = [mm[i: i + COLD_READ_CHUNK]
                         for i in range(0, size, COLD_READ_CHUNK)]
        return b"".join(parts).decode("ascii")

    def load(self, record: PlaneTierRecord) -> dict:
        """Pack file → bundle dict (the promotion read path)."""
        from ..common.datacodec import loads_b64
        return loads_b64(self.read_blob(record))

    def remove(self, record: PlaneTierRecord) -> None:
        try:
            os.unlink(record.path)
        except OSError:
            pass

    def drop_all(self, records) -> None:
        for r in records:
            self.remove(r)


class PlaneTierManager:
    """Per-cache tier policy: access bookkeeping, budget enforcement,
    promote/demote execution, and the tier telemetry/journal surfaces.

    Locking: ``_lock`` is a LEAF lock guarding only the manager's own
    bookkeeping (access stats, cold records, in-flight markers). Tier
    transitions call back into the cache (registry eviction under
    ``_gen_lock``, breaker moves, plane array shuffles) and journal to
    the flight recorder — all of that runs OUTSIDE ``_lock`` (ESTP-L02:
    no telemetry under a serving lock; ESTP-R01: no nested
    manager-inside-cache lock order)."""

    #: warm dispatches before a plane earns promotion back to HBM
    PROMOTE_HITS = int(os.environ.get(
        "ES_TPU_PLANE_TIER_PROMOTE_HITS", "2"))
    #: seconds a freshly installed/promoted plane is immune to demotion
    #: (anti-thrash: the budget sweep must not evict what the current
    #: request just paid to promote)
    MIN_RESIDENCY_S = float(os.environ.get(
        "ES_TPU_PLANE_TIER_MIN_RESIDENCY_S", "0.0"))

    def __init__(self, cache):
        self._cache_ref = weakref.ref(cache)
        self.hbm_budget = int(os.environ.get(
            "ES_TPU_PLANE_HBM_BUDGET_BYTES", "0") or 0)
        self.host_budget = int(os.environ.get(
            "ES_TPU_PLANE_HOST_BUDGET_BYTES", "0") or 0)
        self.promote_hits = self.PROMOTE_HITS
        self.min_residency_s = self.MIN_RESIDENCY_S
        self.cold_store = ColdPackStore()
        self._lock = threading.Lock()
        #: gen -> [warm_hit_count, last_access_monotonic]
        self._access: "weakref.WeakKeyDictionary" = \
            weakref.WeakKeyDictionary()
        #: generations with an in-flight background promotion
        self._promoting: set = set()
        self._cold: List[PlaneTierRecord] = []
        self.promotions = 0
        self.demotions = 0
        from ..common import telemetry as _tm
        _tm.DEFAULT.register_object_collector(
            f"plane_tiers_{id(self):x}", self,
            PlaneTierManager._metrics_doc)

    # -- helpers -------------------------------------------------------------

    @staticmethod
    def _base(gen):
        return gen.__dict__.get("base", gen) \
            if hasattr(gen, "__dict__") else gen

    @staticmethod
    def _tier(gen) -> str:
        return getattr(PlaneTierManager._base(gen), "storage_tier", "hot")

    def _cache(self):
        return self._cache_ref()

    def enabled(self) -> bool:
        return self.hbm_budget > 0 or self.host_budget > 0

    def _last_access(self, gen) -> float:
        with self._lock:
            st = self._access.get(gen)
        return st[1] if st is not None else 0.0

    # -- telemetry -----------------------------------------------------------

    def _metrics_doc(self):
        hot = warm = 0
        cache = self._cache()
        if cache is not None:
            for gen in cache.generations():
                base = self._base(gen)
                try:
                    if getattr(base, "storage_tier", "hot") == "hot":
                        hot += int(base.device_corpus_bytes())
                    else:
                        warm += int(base.host_tier_bytes())
                except Exception:   # noqa: BLE001 — foreign planes
                    continue
        with self._lock:
            cold = sum(r.nbytes for r in self._cold)
        return {
            "es_plane_tier_bytes": {
                "type": "gauge",
                "help": "serving-plane bytes resident per storage tier "
                        "(hot: per-device HBM share; warm: host copies; "
                        "cold: pack-file bytes)",
                "samples": [({"tier": "hot"}, hot),
                            ({"tier": "warm"}, warm),
                            ({"tier": "cold"}, cold)],
            },
        }

    def _journal(self, op: str, gen_or_rec, from_tier: str, to_tier: str,
                 nbytes: int, reason: str) -> None:
        """One transition: flight-recorder event + telemetry counters —
        called outside every lock. The event carries (kind, field,
        from/to, bytes, reason): the tier history of any plane is
        reconstructable from the journal alone."""
        if isinstance(gen_or_rec, PlaneTierRecord):
            kind, field = gen_or_rec.kind, gen_or_rec.field
        else:
            kind = getattr(gen_or_rec, "kind", "plane")
            field = getattr(gen_or_rec, "field", "?")
        from ..common import flightrec as _fr
        from ..common import telemetry as _tm
        _fr.record("plane_tier", op=op, kind=kind, field=field,
                   from_tier=from_tier, to_tier=to_tier,
                   bytes=int(nbytes), reason=reason)
        _tm.record_tier_transition(op, to_tier)
        with self._lock:
            if op == "promote":
                self.promotions += 1
            else:
                self.demotions += 1

    def stats(self) -> dict:
        """Rollup for benches/tests."""
        cache = self._cache()
        hot_b = warm_b = n_hot = n_warm = 0
        for gen in (cache.generations() if cache is not None else ()):
            base = self._base(gen)
            try:
                if getattr(base, "storage_tier", "hot") == "hot":
                    n_hot += 1
                    hot_b += int(base.device_corpus_bytes())
                else:
                    n_warm += 1
                    warm_b += int(base.host_tier_bytes())
            except Exception:   # noqa: BLE001
                continue
        with self._lock:
            return {"promotions": self.promotions,
                    "demotions": self.demotions,
                    "hot_planes": n_hot, "warm_planes": n_warm,
                    "cold_planes": len(self._cold),
                    "hot_bytes": hot_b, "warm_bytes": warm_b,
                    "cold_bytes": sum(r.nbytes for r in self._cold)}

    # -- access bookkeeping (serving hot path) -------------------------------

    def note_dispatch(self, gen) -> None:
        """Serving-merge hook (outside every cache lock): refresh the
        generation's recency, and after ``promote_hits`` consecutive
        warm dispatches schedule its promotion OFF the request thread
        (``repack_mode == "sync"`` runs it inline for deterministic
        tests, same convention as the repack scheduler)."""
        if not self.enabled():
            return
        tier = self._tier(gen)
        promote = False
        with self._lock:
            st = self._access.get(gen)
            if st is None:
                st = self._access[gen] = [0, 0.0]
            st[1] = time.monotonic()
            if tier == "warm":
                st[0] += 1
                if st[0] >= self.promote_hits \
                        and id(gen) not in self._promoting:
                    self._promoting.add(id(gen))
                    promote = True
            else:
                st[0] = 0
        if not promote:
            return
        cache = self._cache()
        if cache is not None and cache.repack_mode == "sync":
            self._promote(gen)
            return
        threading.Thread(target=self._promote, args=(gen,), daemon=True,
                         name="es-recovery-tier-promote").start()

    def touch(self, gen) -> None:
        """Mark a generation as just-accessed (install/import paths) so
        the budget sweep sees it as MRU, not never-used."""
        with self._lock:
            st = self._access.get(gen)
            if st is None:
                st = self._access[gen] = [0, 0.0]
            st[1] = time.monotonic()

    # -- transitions ---------------------------------------------------------

    def _hot_share(self, gen) -> int:
        """The per-device HBM bytes this generation holds (hot) or would
        re-claim on promotion (warm — snapshotted at demote time)."""
        base = self._base(gen)
        if getattr(base, "storage_tier", "hot") == "hot":
            try:
                return int(base.device_corpus_bytes())
            except Exception:   # noqa: BLE001
                return 0
        return int(getattr(base, "_tier_dev_bytes", 0))

    def demote_to_warm(self, gen, reason: str = "hbm_budget") -> bool:
        """Hot → warm: pull the corpus to host, free the device arrays,
        and MOVE the breaker estimate from the device-side ``accounting``
        ledger to ``host_tier``. A host-ledger trip means the node has no
        room for another warm plane either — the demotion continues
        straight to cold instead."""
        from ..common.breakers import DEFAULT as _breakers
        from ..common.errors import CircuitBreakingError
        base = self._base(gen)
        if getattr(base, "storage_tier", "hot") != "hot":
            return False
        dev_share = self._hot_share(gen)
        acct_bytes = int(getattr(base, "_acct_bytes", 0))
        try:
            host_bytes = int(base.demote_to_warm())
        except Exception:   # noqa: BLE001 — foreign/legacy plane
            return False
        base._tier_dev_bytes = dev_share
        base._hot_acct_bytes = acct_bytes
        host = _breakers.breaker("host_tier")
        field = getattr(gen, "field", "?")
        try:
            host.add_estimate(
                host_bytes, f"<warm plane tier [{field}], "
                            f"{host_bytes} B host>")
        except CircuitBreakingError:
            # no host headroom: release the device ledger (the HBM is
            # already freed) and spill the rest of the way to cold
            _breakers.breaker("accounting").release(acct_bytes)
            base._acct_bytes = 0
            base._host_acct_bytes = 0
            self._journal("demote", gen, "hot", "warm", host_bytes,
                          reason)
            self.demote_to_cold(gen, reason="host_breaker")
            return True
        _breakers.breaker("accounting").release(acct_bytes)
        base._acct_bytes = 0
        base._host_acct_bytes = host_bytes
        self._journal("demote", gen, "hot", "warm", host_bytes, reason)
        return True

    def demote_to_cold(self, gen, reason: str = "host_budget") -> bool:
        """Warm (or hot) → cold: serialize the generation's handoff
        bundle ONCE into a pack file, drop it from the serving registry,
        and release every breaker reservation. The next signature-
        matching probe promotes it back through ``import_bundle`` — the
        same path warm handoff uses."""
        cache = self._cache()
        if cache is None:
            return False
        from_tier = self._tier(gen)
        bundle = cache._bundle_for(gen)
        if bundle is None:
            return False
        try:
            with self._lock:
                record = self.cold_store.put(bundle)
        except Exception:   # noqa: BLE001 — spill dir unwritable: the
            return False    # plane simply stays resident
        if not cache._evict_generation(gen):
            # lost a race with a repack swap/release: the generation is
            # no longer registered — don't keep a cold copy of it either
            with self._lock:
                self.cold_store.remove(record)
            return False
        with self._lock:
            self._cold.append(record)
        self._journal("demote", gen, from_tier, "cold", record.nbytes,
                      reason)
        return True

    def _promote(self, gen) -> None:
        """Warm → hot (background): re-reserve the device-side
        ``accounting`` estimate (a trip leaves the plane warm — streamed
        serving still works), make HBM headroom by demoting colder
        planes, then re-upload."""
        from ..common.breakers import DEFAULT as _breakers
        from ..common.errors import CircuitBreakingError
        try:
            base = self._base(gen)
            if getattr(base, "storage_tier", "hot") != "warm":
                return
            need = int(getattr(base, "_tier_dev_bytes", 0))
            self._make_hot_room(need, keep=gen)
            cache = self._cache()
            if cache is None:
                return
            # anti-thrash: if the sweep could NOT make room (residency-
            # protected hot planes — the actively-serving head), the
            # promotion aborts and the plane keeps serving warm rather
            # than evicting a hotter plane into a demote/promote loop.
            # When nothing else is hot the budget is moot (serving
            # floor): the working plane always gets HBM.
            still_hot = sum(
                self._hot_share(g) for g in cache.generations()
                if self._tier(g) == "hot" and g is not gen)
            if self.hbm_budget > 0 and still_hot > 0 \
                    and still_hot + need > self.hbm_budget:
                return
            acct_bytes = int(getattr(base, "_hot_acct_bytes", 0))
            acct = _breakers.breaker("accounting")
            try:
                field = getattr(gen, "field", "?")
                acct.add_estimate(
                    acct_bytes, f"<plane tier promote [{field}], "
                                f"{acct_bytes} B>")
            except CircuitBreakingError:
                return          # stays warm; hysteresis retries later
            try:
                host_bytes = int(base.promote_to_hot())
            except Exception:   # noqa: BLE001
                acct.release(acct_bytes)
                return
            base._acct_bytes = acct_bytes
            _breakers.breaker("host_tier").release(
                int(getattr(base, "_host_acct_bytes", host_bytes)))
            base._host_acct_bytes = 0
            self._journal("promote", gen, "warm", "hot",
                          int(getattr(base, "_tier_dev_bytes", 0)),
                          "access")
        finally:
            with self._lock:
                self._promoting.discard(id(gen))
                st = self._access.get(gen)
                if st is not None:
                    st[0] = 0
                    st[1] = time.monotonic()

    def on_cold_promoted(self, record: PlaneTierRecord, gen) -> None:
        """Bookkeeping after ``import_bundle`` installed a cold bundle
        as a live (hot) generation: drop the pack file and journal the
        promotion."""
        with self._lock:
            try:
                self._cold.remove(record)
            except ValueError:
                pass
            self.cold_store.remove(record)
        self._journal("promote", record, "cold", "hot", record.nbytes,
                      "access")
        if gen is not None:
            self.touch(gen)

    # -- cold lookup ---------------------------------------------------------

    def cold_blob(self, record: PlaneTierRecord) -> str:
        """Pack-file text for a donor offer (locked accessor — the
        store's record set is shared with the budget sweeps)."""
        with self._lock:
            return self.cold_store.read_blob(record)

    def cold_bundle(self, record: PlaneTierRecord) -> dict:
        """Deserialized bundle for the promotion path (locked
        accessor)."""
        with self._lock:
            return self.cold_store.load(record)

    def cold_records(self, kind: Optional[str] = None,
                     field: Optional[str] = None
                     ) -> List[PlaneTierRecord]:
        with self._lock:
            return [r for r in self._cold
                    if (kind is None or r.kind == kind)
                    and (field is None or r.field == field)]

    # -- budget enforcement --------------------------------------------------

    def _lru_order(self, gens) -> list:
        return sorted(gens, key=self._last_access)

    def _make_hot_room(self, need: int, keep=None) -> None:
        """Demote LRU hot generations until ``need`` extra per-device
        bytes fit under the HBM budget (no-op when unlimited)."""
        if self.hbm_budget <= 0:
            return
        cache = self._cache()
        if cache is None:
            return
        now = time.monotonic()
        hot = [g for g in cache.generations()
               if self._tier(g) == "hot" and g is not keep]
        used = sum(self._hot_share(g) for g in hot) + \
            (self._hot_share(keep) if keep is not None
             and self._tier(keep) == "hot" else 0)
        order = self._lru_order(hot)
        if keep is None and order:
            # serving floor: the MRU generation stays resident even when
            # the budget is smaller than one plane — demoting the plane
            # the current request just installed/used would churn every
            # probe into a demote→re-import loop
            order = order[:-1]
        for g in order:
            if used + need <= self.hbm_budget:
                return
            if now - self._last_access(g) < self.min_residency_s:
                continue
            share = self._hot_share(g)
            if self.demote_to_warm(g):
                used -= share

    def enforce_budget(self) -> None:
        """Post-install / post-promotion sweep: spill LRU hot planes to
        warm past the HBM budget, then LRU warm planes to cold past the
        host budget. Safe to call from any thread, outside every cache
        lock."""
        if not self.enabled():
            return
        cache = self._cache()
        if cache is None:
            return
        self._make_hot_room(0)
        if self.host_budget <= 0:
            return
        warm = [g for g in cache.generations()
                if self._tier(g) == "warm"]
        used = 0
        for g in warm:
            try:
                used += int(self._base(g).host_tier_bytes())
            except Exception:   # noqa: BLE001
                continue
        # same MRU serving floor as the hot sweep: an actively-serving
        # warm plane must not cold-spill out from under its own requests
        for g in self._lru_order(warm)[:-1]:
            if used <= self.host_budget:
                return
            try:
                share = int(self._base(g).host_tier_bytes())
            except Exception:   # noqa: BLE001
                continue
            if self.demote_to_cold(g):
                used -= share

    # -- lifecycle -----------------------------------------------------------

    def release(self) -> None:
        """Owning cache is closing: drop every cold pack file (the
        records are meaningless once the registry is gone — recovery
        re-imports from a donor, not from a dead node's spill dir)."""
        with self._lock:
            records, self._cold = self._cold, []
            self.cold_store.drop_all(records)
