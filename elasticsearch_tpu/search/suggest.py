"""Suggesters: term, phrase, and completion suggestions.

Re-design of the reference's suggest module (``search/suggest/``):

- **term** (``TermSuggester.java`` / Lucene ``DirectSpellChecker``):
  candidate corrections from the term dictionary within a bounded edit
  distance, ranked by (similarity desc, doc frequency desc) — the same
  ordering contract, computed with a banded Levenshtein over the
  dictionary instead of an FST intersection (vocabularies here are
  host-side dicts; the banded scan is vectorizable later if needed).
- **phrase** (``PhraseSuggester.java``): whole-input corrections composed
  from per-term candidates, scored by a unigram language model with
  Stupid Backoff-style smoothing over corpus term frequencies (the
  reference defaults to a bigram Laplace model; unigram is the documented
  simplification — scores order candidates the same way for the common
  single-error case).
- **completion** (``CompletionSuggester.java``): prefix matches over a
  ``completion`` field's input weights (see ``index/mapping.py``),
  returned weight-descending — the reference's FST is replaced by a
  sorted-prefix scan of the field's suggestion table.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..common.errors import IllegalArgumentError, ParsingError


def levenshtein_within(a: str, b: str, max_edits: int) -> Optional[int]:
    """Banded edit distance; None if > max_edits (early-exit rows)."""
    la, lb = len(a), len(b)
    if abs(la - lb) > max_edits:
        return None
    prev = list(range(lb + 1))
    for i in range(1, la + 1):
        cur = [i] + [0] * lb
        best = cur[0]
        for j in range(1, lb + 1):
            cur[j] = min(prev[j] + 1, cur[j - 1] + 1,
                         prev[j - 1] + (a[i - 1] != b[j - 1]))
            best = min(best, cur[j])
        if best > max_edits:
            return None
        prev = cur
    return prev[lb] if prev[lb] <= max_edits else None


class TermSuggester:
    def __init__(self, body: dict):
        self.field = body.get("field")
        if self.field is None:
            raise ParsingError("the required field option is missing")
        self.size = int(body.get("size", 5))
        self.max_edits = int(body.get("max_edits", 2))
        if not 1 <= self.max_edits <= 2:
            raise IllegalArgumentError(
                f"max_edits must be 1 or 2, got [{self.max_edits}]")
        self.prefix_length = int(body.get("prefix_length", 1))
        self.min_word_length = int(body.get("min_word_length", 4))
        self.suggest_mode = body.get("suggest_mode", "missing")
        if self.suggest_mode not in ("missing", "popular", "always"):
            raise IllegalArgumentError(
                f"suggest_mode [{self.suggest_mode}] not supported")

    def suggest_token(self, ctx, token: str) -> List[dict]:
        """Candidate corrections for one input token."""
        df_self = ctx.term_df(self.field, token)
        if self.suggest_mode == "missing" and df_self > 0:
            return []
        if len(token) < self.min_word_length:
            return []
        cands: List[Tuple[float, int, str]] = []
        seen = set()
        for seg in ctx.segments:
            f = seg.text_fields.get(self.field)
            if f is None:
                continue
            for term in f.term_ids:
                if term == token or term in seen:
                    continue
                if self.prefix_length and \
                        term[: self.prefix_length] != \
                        token[: self.prefix_length]:
                    continue
                d = levenshtein_within(term, token, self.max_edits)
                if d is None or d == 0:
                    continue
                seen.add(term)
                df = ctx.term_df(self.field, term)
                if self.suggest_mode == "popular" and df <= df_self:
                    continue
                sim = 1.0 - d / max(len(term), len(token))
                cands.append((sim, df, term))
        cands.sort(key=lambda c: (-c[0], -c[1], c[2]))
        return [{"text": t, "score": round(sim, 6), "freq": df}
                for sim, df, t in cands[: self.size]]

    def run(self, ctx, text: str) -> List[dict]:
        out = []
        offset = 0
        for token in text.split():
            start = text.index(token, offset)
            offset = start + len(token)
            norm = token.lower()
            out.append({"text": norm, "offset": start,
                        "length": len(token),
                        "options": self.suggest_token(ctx, norm)})
        return out


class PhraseSuggester:
    def __init__(self, body: dict):
        self.field = body.get("field")
        if self.field is None:
            raise ParsingError("the required field option is missing")
        self.size = int(body.get("size", 5))
        self.max_errors = float(body.get("max_errors", 1.0))
        gen = (body.get("direct_generator") or [{}])[0]
        self.term = TermSuggester(dict(gen, field=gen.get(
            "field", self.field), size=5,
            suggest_mode=gen.get("suggest_mode", "always")))
        hl = body.get("highlight") or {}
        self.pre_tag = hl.get("pre_tag", "")
        self.post_tag = hl.get("post_tag", "")

    def _corpus_total(self, ctx) -> int:
        total = 0
        for seg in ctx.segments:
            f = seg.text_fields.get(self.field)
            if f is not None and len(f.total_term_freq):
                total += int(f.total_term_freq.sum())
        return total

    def _unigram_logp(self, ctx, term: str, total: int) -> float:
        ttf = 0
        for seg in ctx.segments:
            f = seg.text_fields.get(self.field)
            if f is None:
                continue
            tid = f.term_ids.get(term)
            if tid is not None:
                ttf += int(f.total_term_freq[tid])
        return float(np.log((ttf + 0.5) / (total + 1.0)))

    def run(self, ctx, text: str) -> List[dict]:
        tokens = [t.lower() for t in text.split()]
        per_token: List[List[str]] = []
        corrections = 0
        max_errs = self.max_errors if self.max_errors > 1 else \
            max(1, int(self.max_errors * len(tokens)))
        for tok in tokens:
            options = [tok]
            if ctx.term_df(self.field, tok) == 0 and \
                    corrections < max_errs:
                cands = self.term.suggest_token(ctx, tok)
                if cands:
                    options = [cands[0]["text"], tok]
                    corrections += 1
            per_token.append(options)
        # compose: original + single-best corrected variant(s)
        variants = {tuple(tokens)}
        best = [opts[0] for opts in per_token]
        variants.add(tuple(best))
        # one-substitution variants for scoring diversity
        for i, opts in enumerate(per_token):
            if opts[0] != tokens[i]:
                v = list(tokens)
                v[i] = opts[0]
                variants.add(tuple(v))
        total = self._corpus_total(ctx)    # constant for the request
        logp_cache: Dict[str, float] = {}

        def lp(t: str) -> float:
            v = logp_cache.get(t)
            if v is None:
                v = logp_cache[t] = self._unigram_logp(ctx, t, total)
            return v

        scored = []
        for v in variants:
            logp = sum(lp(t) for t in v)
            scored.append((logp, v))
        scored.sort(key=lambda s: -s[0])
        out = []
        for logp, v in scored[: self.size]:
            if list(v) == tokens:
                text_out = " ".join(v)
                hl = None
            else:
                text_out = " ".join(v)
                hl = " ".join(
                    f"{self.pre_tag}{t}{self.post_tag}"
                    if t != tokens[i] else t
                    for i, t in enumerate(v)) \
                    if (self.pre_tag or self.post_tag) else None
            entry = {"text": text_out, "score": float(np.exp(logp))}
            if hl is not None:
                entry["highlighted"] = hl
            out.append(entry)
        return [{"text": text, "offset": 0, "length": len(text),
                 "options": out}]


class CompletionSuggester:
    def __init__(self, body: dict):
        self.field = body.get("field")
        if self.field is None:
            raise ParsingError("the required field option is missing")
        self.size = int(body.get("size", 5))
        self.skip_duplicates = bool(body.get("skip_duplicates", False))
        self.contexts = body.get("contexts") or {}

    def _context_filter(self, ctx, seg):
        """bool[n_docs] of docs matching every requested context, or None
        when the query has no context clauses."""
        if not self.contexts:
            return None
        from ..index.mapping import (CompletionFieldType,
                                     GeoPointFieldType, geohash_encode_12)
        ft = ctx.mapper.field_type(self.field) if ctx.mapper else None
        cdefs = {c.get("name"): c for c in
                 getattr(ft, "contexts", [])} if ft is not None else {}
        keep = np.ones(seg.n_docs, bool)
        for cname, clauses in self.contexts.items():
            kf = seg.keyword_fields.get(f"{self.field}._ctx_{cname}")
            any_match = np.zeros(seg.n_docs, bool)
            if not isinstance(clauses, list):
                clauses = [clauses]
            ctype = (cdefs.get(cname) or {}).get("type", "category")
            for cl in clauses:
                if ctype == "geo":
                    spec = cl if isinstance(cl, dict) else {"context": cl}
                    point = spec["context"] if "context" in spec else spec
                    precision = _geohash_level(
                        spec.get("precision",
                                 (cdefs.get(cname) or {}).get(
                                     "precision", 6)))
                    lat, lon = GeoPointFieldType(cname).parse_value(point)
                    # the reference matches the query cell AND its 8
                    # neighbors (GeoContextMapping.toInternalQueryContexts)
                    bits = 5 * precision
                    dlon = 360.0 / (1 << ((bits + 1) // 2))
                    dlat = 180.0 / (1 << (bits // 2))
                    prefixes = set()
                    for di in (-1, 0, 1):
                        for dj in (-1, 0, 1):
                            la = min(max(lat + di * dlat, -90.0), 90.0)
                            lo_ = ((lon + dj * dlon + 180.0) % 360.0) - 180.0
                            prefixes.add(
                                geohash_encode_12(la, lo_)[:precision])
                    if kf is not None:
                        for term, o in kf.term_ords.items():
                            if any(term.startswith(p_) for p_ in prefixes):
                                st, ln, _ = kf.term_run(term)
                                any_match[kf.docs_host[st: st + ln]] = True
                else:
                    val = cl.get("context") if isinstance(cl, dict) else cl
                    if kf is not None:
                        st, ln, _ = kf.term_run(str(val))
                        any_match[kf.docs_host[st: st + ln]] = True
            keep &= any_match
        return keep

    def run(self, ctx, prefix: str) -> List[dict]:
        ft = ctx.mapper.field_type(self.field) if ctx.mapper else None
        defined = {c.get("name") for c in getattr(ft, "contexts", [])}
        if defined and (not self.contexts or
                        all(not v for v in self.contexts.values())):
            raise IllegalArgumentError(
                "Missing mandatory contexts in context query")
        prefix = prefix.lower()
        options: List[Tuple[float, str, str, dict]] = []
        for seg in ctx.segments:
            kf = seg.keyword_fields.get(self.field)
            if kf is None:
                continue
            ctx_keep = self._context_filter(ctx, seg)
            weights = seg.numeric_first_value_column(
                f"{self.field}._weight")
            # inputs keep their original case; matching is lowercase
            # (the completion "simple" analyzer) over a cached
            # case-folded sorted table (segments are immutable)
            import bisect
            lowered = getattr(kf, "_lowered_sorted", None)
            if lowered is None:
                lowered = sorted((t.lower(), t) for t in kf.ord_terms)
                kf._lowered_sorted = lowered
            lo_i = bisect.bisect_left(lowered, (prefix,))
            for li in range(lo_i, len(lowered)):
                low, inp = lowered[li]
                if not low.startswith(prefix):
                    break
                st, ln, _ = kf.term_run(inp)
                for doc in kf.docs_host[st: st + ln]:
                    if not seg.live[doc]:
                        continue
                    if ctx_keep is not None and not ctx_keep[int(doc)]:
                        continue
                    w = weights[doc]
                    w = 1.0 if np.isnan(w) else float(w)
                    options.append((w, inp, seg.doc_uids[int(doc)],
                                    seg.sources[int(doc)]))
        options.sort(key=lambda o: (-o[0], o[1]))
        out = []
        seen = set()
        for weight, inp, doc_id, src in options:
            if self.skip_duplicates and inp in seen:
                continue
            seen.add(inp)
            out.append({"text": inp, "_id": doc_id,
                        "_score": float(weight), "_source": src})
            if len(out) >= self.size:
                break
        return [{"text": prefix, "offset": 0, "length": len(prefix),
                 "options": out}]


def run_suggest(ctx, spec: dict) -> Dict[str, list]:
    """Execute a ``suggest`` section (``RestSearchAction`` suggest part)."""
    if not isinstance(spec, dict):
        raise ParsingError("suggest must be an object")
    global_text = spec.get("text")
    out: Dict[str, list] = {}
    for name, body in spec.items():
        if name == "text":
            continue
        if not isinstance(body, dict):
            raise ParsingError(f"suggestion [{name}] must be an object")
        text = body.get("text", body.get("prefix", global_text))
        if text is None:
            raise ParsingError(
                f"suggestion [{name}] requires [text] or [prefix]")
        if "term" in body:
            out[name] = TermSuggester(body["term"]).run(ctx, text)
        elif "phrase" in body:
            out[name] = PhraseSuggester(body["phrase"]).run(ctx, text)
        elif "completion" in body:
            out[name] = CompletionSuggester(body["completion"]).run(ctx, text)
        else:
            raise ParsingError(
                f"suggestion [{name}] requires one of [term, phrase, "
                f"completion]")
    return out


#: geohash cell heights per precision level (meters) — the mapping from
#: a distance precision ("5km") to the coarsest level at least that fine
_GEOHASH_LEVEL_M = [5009400.0, 1252300.0, 156500.0, 39100.0, 4900.0,
                    1200.0, 152.9, 38.2, 4.78, 1.19, 0.149, 0.037]


def _geohash_level(precision) -> int:
    if isinstance(precision, int):
        return max(1, min(precision, 12))
    if isinstance(precision, str) and precision.isdigit():
        return max(1, min(int(precision), 12))
    from .positional import parse_distance_meters
    meters = parse_distance_meters(precision)
    for level, size in enumerate(_GEOHASH_LEVEL_M, start=1):
        if size <= meters:
            return level
    return 12
