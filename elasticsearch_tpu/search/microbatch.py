"""Micro-batching queue for the serving plane: concurrent plane-eligible
queries coalesce into ONE device dispatch, driven by a dedicated
dispatcher thread per plane.

The reference amortizes per-query overhead through its search thread pool
(``threadpool/ThreadPool.java`` SEARCH lane) and batched partial reduction
(``action/search/QueryPhaseResultConsumer.java``); on a TPU the analogous
lever is the batch dimension of the dispatch itself — one ``plane.search``
over B queries costs barely more than B=1 (the kernel is bandwidth-bound
over the postings table, which every query in the batch shares).

Design (dispatcher pipeline): client threads only enqueue a slot and
block on its result; a small pool of dispatcher threads (PIPELINE_DEPTH,
spawned on demand, exiting after IDLE_EXIT_S of quiet) drains the queue.
While one dispatcher waits on a device result, the other accumulates the
next batch and runs its host-side prep (term→id lookup, padding,
``np.stack``), so host prep pipelines with device execution. No client
thread ever "leads" a dispatch — the old leader-promotion scheme let a
promoted leader's k-bucket filter starve waiters in other buckets (the
convoy this rebuild kills). Under load the batch size converges to
arrival-rate × dispatch-time with no tuning knob and no timed wait.

Batch selection: the dispatcher picks the k-bucket with the most ready
slots; when the queue runs deeper than one full batch it coalesces
across buckets at the max-k shape instead (one bigger dispatch beats two
half-empty ones); and any slot skipped STARVATION_ROUNDS times forces
its own bucket next, so no bucket waits unboundedly behind a popular one.
Under multi-tenant contention the pick is **priority-weighted**
(``common/qos.py`` classes: interactive / bulk / analytics): each
queued class accrues deficit by its weight every round and the
highest-deficit class seeds the bucket choice, so interactive point
queries win most rounds while bulk/analytics still drain — and co-batch
into interactive dispatches whenever they share the dispatch shape. The
class is a SELECTION key only, never part of the bucket/jit shape key,
so the compile lattice is untouched; the per-slot STARVATION_ROUNDS
bound applies to every slot regardless of class, which bounds each
class's wait independently.

Observability: every request is stamped with per-stage timings — queue
wait, host prep, device dispatch, result fetch — aggregated per batcher
(totals for nodes stats, bounded sample rings for bench percentiles), so
a serving regression is attributable to a stage instead of one opaque
p99. :meth:`PlaneMicroBatcher.warmup` pre-compiles the serving shape
lattice (B-pow2 × k-bucket × L-rung) off the serving path at plane-build
time — a first-hit XLA compile landing mid-traffic is the classic
multi-second p99 signature.

One batcher per serving GENERATION (``plane_route`` hands the batcher a
generation object — packed base plane + append-only delta tier — whose
``serve`` merges delta hits into the base dispatch; an append-only
refresh swaps the delta inside the same generation, so the batcher and
its warmed shapes survive, and only a background repack retires it);
distinct generations dispatch concurrently.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..common import qos as _qos
from ..common import racedep

#: upper bound on queries per dispatch — past this the dispatch itself is
#: long enough that splitting reduces tail latency
MAX_BATCH = 64

#: per-request stage names, in pipeline order
STAGES = ("queue", "prep", "dispatch", "fetch")

#: per-stage sample ring size (bench percentiles read these)
STAGE_SAMPLE_CAP = 4096


def empty_serving_stats() -> Dict[str, int]:
    """Zero-valued serving-stats doc — the shape :meth:`stats_doc`
    returns and nodes stats aggregate (``plane_serving`` section)."""
    return {
        "dispatches": 0, "queries": 0, "max_batch": 0,
        "starved_dispatches": 0, "coalesced_dispatches": 0,
        "deduped_queries": 0,
        "delta_queries": 0, "delta_time_in_millis": 0,
        "warmed_shapes": 0, "warmup_time_in_millis": 0,
        "queue_time_in_millis": 0, "prep_time_in_millis": 0,
        "dispatch_time_in_millis": 0, "fetch_time_in_millis": 0,
        # serving-mesh topology (max-merged across batchers — every
        # generation of one cache shares the cache's mesh)
        "mesh_shard_devices": 0, "mesh_replica_devices": 0,
    }


class _Slot:
    __slots__ = ("terms", "k", "done", "vals", "hits", "total", "aggs",
                 "error", "t_enq", "rounds_skipped", "stage_ms", "info",
                 "view_segments", "view_key", "params", "trace_id",
                 "node", "shape", "priority", "tenant")

    def __init__(self, terms, k: int, view=None, params=None):
        self.terms = terms
        self.k = k
        #: the enqueuing request's trace id + ambient node (captured
        #: HERE, on the request thread — dispatcher threads carry no
        #: request context): the dispatch profiler's record and the
        #: roofline efficiency exemplar both link back through them,
        #: and the node stamp keeps the cluster fan-in's per-node
        #: dedup exact (in-process nodes share the ring)
        from ..common import flightrec as _fr
        from ..common import tracing as _tracing
        self.trace_id = _tracing.current_trace_id()
        self.node = _fr.ambient_node()
        #: the request's query shape id (dispatch-profile records join
        #: /_insights/top_queries by it) — captured here for the same
        #: reason as trace_id
        self.shape = _fr.current_shape()
        #: the request's tenant (X-Opaque-Id) — captured on the request
        #: thread so the dispatcher can stamp the batch's dominant
        #: (tenant, shape) into the continuous profiler's attribution
        #: map around each dispatch (common/contprof.py)
        self.tenant = _tracing.current_opaque_id()
        #: the request's QoS priority class (interactive/bulk/analytics)
        #: — bound by the REST edge, captured on the request thread; a
        #: SELECTION key for the weighted-deficit pick, never part of
        #: the dispatch/jit shape
        self.priority = _qos.current_priority()
        #: extra dispatch parameters that shape the kernel (kNN IVF:
        #: bucketed (nprobe, rerank)) — co-batching only within one
        #: params tuple, so the compile-shape lattice stays warm
        self.params = params
        #: the caller's segment-list snapshot (NRT view). Hit coordinates
        #: must decode against THIS list, so slots only co-batch within
        #: one view and the dispatch resolves the delta tier for exactly
        #: this list (plane_route serve_view) — a refresh landing between
        #: enqueue and dispatch must not shift coordinates under the
        #: caller. None = viewless (legacy planes / tests).
        self.view_segments = view
        self.view_key = tuple(id(s) for s in view) \
            if view is not None else None
        self.done = False
        self.vals = None
        self.hits: Optional[List[Tuple[int, int]]] = None
        self.total: Optional[int] = None
        #: fused agg-stage result for THIS slot (dict), or None — set
        #: only by dispatches whose plane returned a 4th output list
        self.aggs = None
        self.error: Optional[BaseException] = None
        self.t_enq = time.perf_counter()
        #: dispatch rounds that passed this slot over (starvation bound)
        self.rounds_skipped = 0
        #: per-stage ms for THIS request, filled at fan-out
        self.stage_ms: Optional[Dict[str, float]] = None
        #: dispatch metadata for THIS request (compile-cache hit/miss,
        #: batch size) — the Profile API's ``serving`` section
        self.info: Optional[Dict[str, object]] = None


class PlaneMicroBatcher:
    """Batches ``plane.search`` dispatches for one plane behind a
    dedicated dispatcher thread."""

    #: batcher kind label (timeline tracks, es_batcher_queue_depth)
    kind = "text"

    #: concurrent dispatcher threads: 2 pipelines host prep of batch N+1
    #: with the device execution / result sync of batch N
    PIPELINE_DEPTH = 2
    #: dispatcher threads exit after this long with an empty queue (a
    #: rebuilt plane's orphaned batcher must not leak a thread forever)
    IDLE_EXIT_S = 5.0
    #: a queued slot skipped this many rounds forces its bucket next
    STARVATION_ROUNDS = 4

    def __init__(self, plane, max_batch: int = MAX_BATCH):
        self.plane = plane
        self.max_batch = max_batch
        # one lock, two wait-sets: clients wait on _cond for their slot,
        # dispatchers wait on _work for queue items — an enqueue then
        # wakes ONE dispatcher instead of every blocked client
        _lock = threading.Lock()
        self._cond = threading.Condition(_lock)
        self._work = threading.Condition(_lock)
        self._queue: List[_Slot] = []
        #: priority class -> accrued weighted deficit (mutated only
        #: under the lock inside _take_batch_locked)
        self._deficit: Dict[str, float] = {}
        self._dispatchers: List[threading.Thread] = []
        self._warmup_thread: Optional[threading.Thread] = None
        # observability (nodes stats / serving bench) — mutated ONLY under
        # self._cond
        self.n_dispatches = 0
        self.n_queries = 0
        self.max_seen_batch = 0
        self.n_starved_dispatches = 0
        self.n_coalesced_dispatches = 0
        self.n_deduped = 0
        # delta-tier observability: queries whose dispatch merged a
        # base+delta result (live indexing appended segments since the
        # base pack) and the eager delta-scan time they paid
        self.n_delta_queries = 0
        self.delta_ms = 0.0
        self.warmed_shapes = 0
        self.warmup_ms = 0.0
        self._retired = False
        self.stage_totals_ms: Dict[str, float] = {s: 0.0 for s in STAGES}
        self.stage_samples: Dict[str, deque] = {
            s: deque(maxlen=STAGE_SAMPLE_CAP) for s in STAGES}
        # serving-mesh fan-out, resolved once (the plane's mesh never
        # changes under a batcher — a repack swaps the whole generation
        # AND its batcher): replica axis sizes the co-batched block's
        # pad, shard axis splits docs-scanned attribution per device
        mesh = getattr(plane, "mesh", None)
        self.mesh_shard_devices = 1
        self.mesh_replica_devices = 1
        if mesh is not None:
            try:
                from ..parallel.mesh import AXIS_REPLICA, AXIS_SHARD
                self.mesh_shard_devices = int(mesh.shape[AXIS_SHARD])
                self.mesh_replica_devices = int(mesh.shape[AXIS_REPLICA])
            except Exception:   # noqa: BLE001 — foreign mesh-less plane
                pass

    # -- client entry -------------------------------------------------------

    def search(self, terms: Sequence[str], k: int,
               stages: Optional[dict] = None,
               info: Optional[dict] = None, view=None, params=None):
        """One query through the batched dispatch. Returns
        (scores[k], hits[(shard, doc)...], exact total). Blocks until the
        dispatch that carries this query completes. ``stages``, when a
        dict, receives this request's per-stage ms timings; ``info``
        receives dispatch metadata (compile-cache hit/miss, batch size)
        for the Profile API's serving section. ``view`` is the caller's
        segment-list snapshot (see ``_Slot.view_segments``); ``params``
        are kernel-shaping dispatch parameters (see ``_Slot.params``)."""
        slot = _Slot(terms, k, view=view, params=params)
        with self._cond:
            self._queue.append(slot)
            self._ensure_dispatcher_locked()
            self._work.notify()
            while not slot.done:
                self._cond.wait()
        if stages is not None and slot.stage_ms is not None:
            stages.update(slot.stage_ms)
        if info is not None and slot.info is not None:
            info.update(slot.info)
        return self._result(slot)

    @staticmethod
    def _result(slot: _Slot):
        if slot.error is not None:
            raise slot.error
        return slot.vals, slot.hits, slot.total

    @staticmethod
    def _k_bucket(k: int) -> int:
        """Dispatch k rounded up to a power of two: co-batched queries only
        share a dispatch within the same bucket, so one size=10000 request
        neither inflates every size=10 neighbor's kernel nor churns the
        per-k compile cache (``dist_search._get_step`` caches per k)."""
        return 1 << max(0, (k - 1).bit_length())

    # -- dispatcher ---------------------------------------------------------

    def _ensure_dispatcher_locked(self) -> None:
        self._dispatchers = [t for t in self._dispatchers if t.is_alive()]
        if self._queue and len(self._dispatchers) < self.PIPELINE_DEPTH:
            t = threading.Thread(
                target=self._dispatch_loop,
                name=f"es-dispatcher-{id(self):x}", daemon=True)
            self._dispatchers.append(t)
            t.start()

    def _dispatch_loop(self) -> None:
        me = threading.current_thread()
        while True:
            with self._cond:
                deadline = time.monotonic() + self.IDLE_EXIT_S
                while not self._queue:
                    rem = deadline - time.monotonic()
                    if rem <= 0:
                        if me in self._dispatchers:
                            self._dispatchers.remove(me)
                        return
                    self._work.wait(rem)
                batch = self._take_batch_locked()
            # stamp this dispatcher with the batch's dominant
            # (tenant, shape) — captured per-slot on the request thread
            # at enqueue — so the continuous profiler attributes the
            # host-prep + dispatch CPU burned here. OUTSIDE the batcher
            # lock: contprof is telemetry-side (ESTP-L02)
            from ..common import contprof as _contprof
            counts: Dict = {}
            for s in batch:
                key = (s.tenant, s.shape)
                counts[key] = counts.get(key, 0) + 1
            dom = max(counts.items(), key=lambda kv: kv[1])[0]
            _cp_token = _contprof.bind_dispatch(dom[0], dom[1])
            try:
                self._run_batch(batch)
            except BaseException as e:   # noqa: BLE001 — the loop must
                # survive anything so queued slots never hang a client
                with self._cond:
                    for s in batch:
                        if not s.done:
                            s.error = e
                            s.done = True
                    self._cond.notify_all()
            finally:
                _contprof.unbind_dispatch(_cp_token)

    def _bucket_key(self, s: _Slot):
        """One dispatch = one (k shape, segment view, params): k and
        params decide the compile shape, the view decides the hit
        coordinate space."""
        return (self._k_bucket(s.k), s.view_key, s.params)

    def _pick_class_locked(self, q: List[_Slot]) -> List[_Slot]:
        """Weighted-deficit class selection (caller holds the lock):
        every class with queued slots accrues deficit by its QoS weight
        each round; the highest-deficit class's slots seed the bucket
        choice and its deficit resets. The batch itself still takes
        EVERY queued slot sharing the chosen dispatch shape — bulk /
        analytics co-batch behind interactive for free — and the class
        never enters the bucket key, so the compile lattice is
        untouched. Classes with nothing queued drop their banked
        deficit (no unbounded credit)."""
        by_class: Dict[str, List[_Slot]] = {}
        for s in q:
            by_class.setdefault(s.priority, []).append(s)
        if len(by_class) == 1:
            return q
        for c in by_class:
            self._deficit[c] = self._deficit.get(c, 0.0) \
                + _qos.priority_weight(c)
        for c in list(self._deficit):
            if c not in by_class:
                self._deficit.pop(c)
        win = max(by_class, key=lambda c: (self._deficit.get(c, 0.0), c))
        self._deficit[win] = 0.0
        return by_class[win]

    def _take_batch_locked(self) -> List[_Slot]:
        """Pick the next batch (caller holds the lock; queue non-empty).

        Priority: (1) any slot skipped STARVATION_ROUNDS times gets its
        bucket dispatched now — a queued slot whose bucket never matches
        the popular one is still served within a bounded number of
        rounds, whatever its class; otherwise the weighted-deficit
        class pick (:meth:`_pick_class_locked`) chooses whose slots
        seed the shape, then (2) a queue deeper than one full batch
        coalesces across k-buckets (within one view) at the max-k
        shape; (3) otherwise the largest ready bucket goes (ties
        resolve to the oldest slot's bucket). Steps 2–3 take matching
        slots from the WHOLE queue, not just the winning class."""
        q = self._queue
        starved = next((s for s in q
                        if s.rounds_skipped >= self.STARVATION_ROUNDS), None)
        if starved is not None:
            bk = self._bucket_key(starved)
            batch = [s for s in q
                     if self._bucket_key(s) == bk][: self.max_batch]
            self.n_starved_dispatches += 1
        else:
            pool = self._pick_class_locked(q)
            if len(q) > self.max_batch:
                # coalesce across k-buckets but never across views (a
                # view boundary is a refresh boundary — coordinates
                # differ) or params (different kernel knobs = different
                # compile shape)
                vcounts: Dict = {}
                for s in pool:
                    vp = (s.view_key, s.params)
                    vcounts[vp] = vcounts.get(vp, 0) + 1
                vbest = max(vcounts.values())
                vk = next((s.view_key, s.params) for s in pool
                          if vcounts[(s.view_key, s.params)] == vbest)
                batch = [s for s in q
                         if (s.view_key, s.params) == vk][: self.max_batch]
                if len({self._k_bucket(s.k) for s in batch}) > 1:
                    self.n_coalesced_dispatches += 1
            else:
                counts: Dict = {}
                for s in pool:
                    bk = self._bucket_key(s)
                    counts[bk] = counts.get(bk, 0) + 1
                best = max(counts.values())
                bk = next(self._bucket_key(s) for s in pool
                          if counts[self._bucket_key(s)] == best)
                batch = [s for s in q
                         if self._bucket_key(s) == bk][: self.max_batch]
        taken = set(map(id, batch))
        self._queue = [s for s in q if id(s) not in taken]
        for s in self._queue:
            s.rounds_skipped += 1
        return batch

    def _run_batch(self, batch: List[_Slot]) -> None:
        t_pick = time.perf_counter()
        # dispatch at the bucket's rounded-up k so the compile shape is
        # stable within a bucket (slots trim to their own k on fan-out);
        # a coalesced cross-bucket batch runs at the max-k shape
        k = self._k_bucket(max(s.k for s in batch))
        # in-flight dedup: identical queries that queued concurrently
        # (the same hot body from many clients) share ONE dispatch slot —
        # each client still gets its own result copy on fan-out
        slot_of: Dict = {}
        lane: List[int] = []
        for s in batch:
            qk = self._query_key(s.terms)
            idx = slot_of.setdefault(qk, len(slot_of))
            lane.append(idx)
        n_deduped = len(batch) - len(slot_of)
        uniq: List = [None] * len(slot_of)
        for s, idx in zip(batch, lane):
            if uniq[idx] is None:
                uniq[idx] = s.terms
        # pad the batch to a power of two: every distinct traced B shape is
        # a fresh XLA compile — ragged arrival sizes would otherwise
        # compile dozens of programs (padding slots score as no-op
        # queries). Then pad on to a REPLICA-axis multiple: the mesh
        # partitions the batch dim over replica groups (the pad at
        # dist_search.search would add it anyway), and filling the
        # per-replica sub-batches here keeps the batcher's co-batched
        # block equal to the traced block — warm-lattice shapes ARE the
        # serving shapes at every mesh.
        b_pad = 1 << max(0, (len(uniq) - 1).bit_length())
        rm = self.mesh_replica_devices
        if rm > 1:
            b_pad = -(-b_pad // rm) * rm
        queries = uniq + [self._pad_slot()
                          for _ in range(b_pad - len(uniq))]
        plane_stages: Dict[str, float] = {}
        t_call = time.perf_counter()
        err: Optional[BaseException] = None
        try:
            out = self._dispatch(
                queries, k, plane_stages,
                view=batch[0].view_segments, params=batch[0].params)
            vals, hits, totals = out[:3]
            # fused agg stages: a plane that served analytics stages
            # returns a 4th per-slot list of aggregations dicts
            aggs_list = out[3] if len(out) > 3 else None
        except BaseException as e:          # noqa: BLE001 — fan the error
            err = e                         # out to every query in the batch
        t_done = time.perf_counter()
        if err is not None:
            for s in batch:
                s.error = err
        else:
            for s, idx in zip(batch, lane):
                s.vals = vals[idx][:s.k]
                s.hits = hits[idx][:s.k]
                s.total = totals[idx]
                if aggs_list is not None:
                    s.aggs = aggs_list[idx]
        # stage attribution: queue wait is per-slot; prep / dispatch /
        # fetch are shared by the whole batch (one dispatch). The plane
        # refines its own call into prep/dispatch/fetch when it can;
        # otherwise the whole call counts as dispatch.
        prep_ms = (t_call - t_pick) * 1e3 + plane_stages.get("prep_ms", 0.0)
        dispatch_ms = plane_stages.get(
            "dispatch_ms", (t_done - t_call) * 1e3)
        fetch_base_ms = plane_stages.get("fetch_ms", 0.0)
        batch_info = {"batch_size": len(batch), "k_bucket": k,
                      "compile_cache": plane_stages.get("compile_cache",
                                                        "hit"),
                      # the dispatch's mesh topology, so profile:true
                      # responses name the device fan-out next to the
                      # per-device docs share below
                      "mesh": {"shard_devices": self.mesh_shard_devices,
                               "replica_devices":
                                   self.mesh_replica_devices}}
        # task resource attribution (node/task_manager.TaskResources):
        # the dispatch's transfer bytes split across the batch's slots
        # (so per-task sums reconcile with es_device_transfer_bytes_total)
        # while docs scanned is per QUERY — every query's score covers
        # the full base corpus plus the delta tier
        share = 1.0 / max(len(batch), 1)
        h2d = plane_stages.get("h2d_bytes")
        d2h = plane_stages.get("d2h_bytes")
        if h2d or d2h:
            batch_info["h2d_bytes"] = int((h2d or 0) * share)
            batch_info["d2h_bytes"] = int((d2h or 0) * share)
        base_docs = getattr(self.plane, "base_docs", None)
        if base_docs is None:
            base_docs = getattr(self.plane, "n_docs_total", 0)
        # a cluster-pruned (IVF) dispatch scans only the probed rows —
        # the plane reports them; full scans cover the whole base corpus
        scanned = plane_stages.get("docs_scanned")
        batch_info["docs_scanned"] = int(
            (base_docs if scanned is None else scanned)
            + plane_stages.get("delta_docs", 0))
        # per-DEVICE share of the scan: the shard axis partitions the
        # corpus, so each chip streams ~1/s_dev of the scanned rows (the
        # delta tier is host-side and excluded) — task attribution and
        # plane_serving report both views
        sdev = max(self.mesh_shard_devices, 1)
        base_scan = int(base_docs if scanned is None else scanned)
        batch_info["docs_scanned_per_device"] = -(-base_scan // sdev)
        tier = plane_stages.get("tier")
        if tier is not None:
            # streamed-tier dispatch (warm plane): surface the storage
            # tier + per-dispatch host→device stream bytes next to the
            # transfer counters, so profile:true and the stats rollup
            # show WHY this dispatch's byte model moved to the host link
            batch_info["tier"] = tier
            batch_info["stream_bytes"] = int(
                plane_stages.get("stream_bytes", 0))
        delta_ms = plane_stages.get("delta_ms")
        if delta_ms is not None:
            # this dispatch merged the base plane with a live delta tier:
            # surface the scan cost + delta size in the Profile API's
            # serving section and the batcher's stats rollup
            batch_info["delta_ms"] = round(delta_ms, 3)
            batch_info["delta_docs"] = int(
                plane_stages.get("delta_docs", 0))
        with self._cond:
            racedep.note_write("microbatch.stats", self)
            fetch_ms = fetch_base_ms + \
                (time.perf_counter() - t_done) * 1e3
            for s in batch:
                s.info = batch_info
                s.stage_ms = {
                    "queue": (t_pick - s.t_enq) * 1e3, "prep": prep_ms,
                    "dispatch": dispatch_ms, "fetch": fetch_ms}
                if "agg_ms" in plane_stages:
                    # fused analytics stages ran inside this dispatch:
                    # break their share out next to the pipeline stages
                    # (profile:true serving section)
                    s.stage_ms["agg"] = plane_stages["agg_ms"]
                for name in STAGES:
                    self.stage_totals_ms[name] += s.stage_ms[name]
                    self.stage_samples[name].append(s.stage_ms[name])
                s.done = True
            self.n_dispatches += 1
            self.n_queries += len(batch)
            self.n_deduped += n_deduped
            if delta_ms is not None:
                self.n_delta_queries += len(batch)
                self.delta_ms += delta_ms
            self.max_seen_batch = max(self.max_seen_batch, len(batch))
            self._cond.notify_all()
        t_end = time.perf_counter()
        # dispatch-timeline record + roofline audit, then the
        # flight-recorder slow-dispatch journal — ALL outside the
        # batcher lock (ESTP-L02: no profiler/telemetry/recorder write
        # under a serving lock). The slow event carries the profile
        # record's seq so the two journals cross-link.
        rec = self._profile_dispatch(
            batch, n_uniq=len(slot_of), k=k, b_pad=b_pad,
            t_pick=t_pick, t_call=t_call, t_done=t_done, t_end=t_end,
            plane_stages=plane_stages, batch_info=batch_info, err=err)
        from ..common import flightrec as _fr
        slow_ms = prep_ms + dispatch_ms + fetch_base_ms
        if err is None and slow_ms > _fr.slow_dispatch_threshold_ms():
            _fr.record(
                "slow_dispatch", plane=type(self.plane).__name__,
                batch_size=len(batch), k_bucket=k,
                prep_ms=round(prep_ms, 3),
                dispatch_ms=round(dispatch_ms, 3),
                fetch_ms=round(fetch_base_ms, 3),
                compile_cache=batch_info.get("compile_cache"),
                profile_rec=rec.get("seq"))

    def _kernel_family(self, params, plane_stages: dict) -> str:
        """ROOFLINE.md kernel family of one dispatch (the serving path
        stamps ``stages['kernel']`` when it knows better — e.g. a prune
        request that routed eager past the θ-window cap)."""
        k = plane_stages.get("kernel") if plane_stages else None
        if k:
            return str(k)
        if params is not None and params[0] == "prune" and params[1] \
                and getattr(self.plane, "blockmax", None) is not None:
            return "bm25_pruned"
        return "bm25_eager"

    def _profile_dispatch(self, batch, *, n_uniq: int, k: int,
                          b_pad: int, t_pick: float, t_call: float,
                          t_done: float, t_end: float,
                          plane_stages: dict, batch_info: dict,
                          err) -> dict:
        """Append this dispatch's timeline record (bounded ring,
        ``search/dispatch_profile.py``) and audit it against the
        ROOFLINE bytes model. Runs on the dispatcher thread, never
        under a lock; O(1) and never raises."""
        try:
            from ..common import roofline as _rf
            from . import dispatch_profile as _dp
            mono_end = time.perf_counter()
            wall_end = time.time()

            def wall(t: float) -> float:
                return (wall_end - (mono_end - t)) * 1e3

            q_start = min(s.t_enq for s in batch)
            stages = [
                {"name": name,
                 "start_ms": round(wall(a), 3),
                 "end_ms": round(wall(b), 3),
                 "mono_start_ms": round(a * 1e3, 3),
                 "mono_end_ms": round(b * 1e3, 3)}
                for name, a, b in (
                    ("queue", q_start, t_pick), ("prep", t_pick, t_call),
                    ("execute", t_call, t_done), ("fetch", t_done, t_end))]
            kernel = self._kernel_family(batch[0].params, plane_stages)
            model_b = plane_stages.get("model_bytes")
            if model_b is None:
                model_b = _rf.fallback_model_bytes(
                    kernel, self.plane, n_uniq, k)
            audit = None
            if err is None:
                exemplar = next(
                    (s.trace_id for s in batch if s.trace_id), None)
                # the plane's own refined device-execute wall when it
                # reports one (the whole-call wall includes plane-side
                # host prep + fetch decode — charging those as
                # "bandwidth" would misattribute a host regression)
                exec_ms = plane_stages.get(
                    "dispatch_ms", (t_done - t_call) * 1e3)
                audit = _rf.audit(kernel, model_b, exec_ms,
                                  exemplar=exemplar)
            me = threading.current_thread()
            return _dp.record(
                ts_ms=round(wall(q_start), 3),
                mono_ms=round(q_start * 1e3, 3),
                end_ms=round(wall(t_end), 3),
                node=next((s.node for s in batch if s.node), None),
                shape=next((s.shape for s in batch if s.shape), None),
                batcher=f"{self.kind}:{id(self):x}", kind=self.kind,
                kernel=kernel, thread=me.ident, thread_name=me.name,
                bucket={"k": k,
                        "params": repr(batch[0].params)
                        if batch[0].params is not None else None,
                        "view": len(batch[0].view_segments)
                        if batch[0].view_segments is not None else None},
                batch={"requests": len(batch), "unique": n_uniq,
                       "b_pad": b_pad,
                       "mesh": batch_info.get("mesh")},
                # dispatch TOTALS (batch_info carries the per-slot
                # share for task attribution)
                bytes={"h2d": int(plane_stages.get("h2d_bytes") or 0),
                       "d2h": int(plane_stages.get("d2h_bytes") or 0),
                       "model": int(model_b or 0)},
                compile_cache=batch_info.get("compile_cache"),
                docs_scanned=batch_info.get("docs_scanned"),
                error=type(err).__name__ if err is not None else None,
                stages=stages, audit=audit)
        except Exception:   # noqa: BLE001 — the profiler must never
            return {}       # take down the dispatch it observes

    # -- warmup (shape-lattice pre-compile) ---------------------------------

    def warmup(self, ks: Sequence[int] = (10,),
               max_b: Optional[int] = None, sync: bool = False):
        """Pre-compile the serving shape lattice (B-pow2 × k-bucket ×
        L-rung) so no first-hit XLA compile lands mid-traffic. Runs in a
        background thread by default (plane build must not block on
        minutes of compiles); ``sync=True`` blocks (tests). Host-serving
        planes (CPU backend → eager/BLAS paths) compile nothing and
        return immediately."""
        from ..common import telemetry as _tm
        # n=0 up front: the cumulative family's presence is
        # deterministic even when nothing compiles (host planes below)
        _tm.record_warmed_shapes(0)
        if self._serves_host():
            return None
        shapes = list(self._warm_lattice(ks, max_b or self.max_batch))

        def _run():
            t0 = time.perf_counter()
            n = 0
            for fn in shapes:
                if self._retired:
                    # the plane was superseded (refresh rebuilt it):
                    # stop compiling shapes nobody will ever serve and
                    # release the thread's reference to the old corpus
                    break
                try:
                    fn()
                    n += 1
                except Exception:   # noqa: BLE001 — warmup must never
                    break           # take down serving
            with self._cond:
                racedep.note_write("microbatch.stats", self)
                self.warmed_shapes += n
                self.warmup_ms += (time.perf_counter() - t0) * 1e3
            # process-cumulative credit: survives this batcher's
            # retirement, so compile_churn windows stay honest across
            # generation swaps (see telemetry.record_warmed_shapes)
            _tm.record_warmed_shapes(n)

        if sync:
            _run()
            return None
        t = threading.Thread(target=_run,
                             name=f"es-warmup-{id(self):x}", daemon=True)
        with self._cond:
            # the handle is written by whichever thread triggers warmup
            # (request-thread cold build or the repack thread) and read
            # by stats/tests — same lock as the other batcher state
            self._warmup_thread = t
        t.start()
        return t

    def retire(self) -> None:
        """The owning plane was superseded or evicted: stop any in-flight
        warmup at the next shape boundary (in-flight dispatches complete
        normally; late arrivals through a stale reference still serve)."""
        self._retired = True

    def _serves_host(self) -> bool:
        """True when the plane serves through a host-native path (CPU
        backend) — nothing to pre-compile."""
        return getattr(self.plane, "_host_csr", None) is not None

    def _warm_lattice(self, ks, max_b):
        """Thunks, one per (B, k-bucket, L-rung) serving shape."""
        plane = self.plane
        rungs = plane.ladder_rungs() if hasattr(plane, "ladder_rungs") \
            else [None]
        kbs = sorted({self._k_bucket(k) for k in ks})
        # serving dispatches run at the plane's Q floor (serve() collapses
        # the Q shape axis there) — warm that exact shape
        qkw = {"Q": plane.SERVING_Q_MIN} \
            if getattr(plane, "SERVING_Q_MIN", 0) else {}
        b = 1
        while b <= min(max_b, self.max_batch):
            for kb in kbs:
                for L in rungs:
                    yield lambda B=b, kb=kb, L=L: plane.search(
                        [self._pad_slot()] * B, k=kb, L=L,
                        tiered=getattr(plane, "T_pad", 0) > 0 or None,
                        with_totals=True, **qkw)
            b <<= 1

    # -- stats --------------------------------------------------------------

    def queue_depth(self) -> int:
        """Slots waiting for a dispatch right now (watchdog captures
        snapshot this per batcher — a deep queue at capture time names
        the convoy)."""
        with self._cond:
            return len(self._queue)

    def queue_depth_by_class(self) -> Dict[str, int]:
        """Queued slots per QoS priority class — the watchdog samples
        this into ``es_batcher_queue_depth{index,kind,class}`` so a
        convoy is attributable to the class causing it."""
        with self._cond:
            out: Dict[str, int] = {}
            for s in self._queue:
                out[s.priority] = out.get(s.priority, 0) + 1
            return out

    def stats_doc(self) -> Dict[str, int]:
        """Aggregate serving stats (nodes stats ``plane_serving``)."""
        with self._cond:
            racedep.note_read("microbatch.stats", self)
            out = empty_serving_stats()
            out.update(
                dispatches=self.n_dispatches, queries=self.n_queries,
                max_batch=self.max_seen_batch,
                starved_dispatches=self.n_starved_dispatches,
                coalesced_dispatches=self.n_coalesced_dispatches,
                deduped_queries=self.n_deduped,
                delta_queries=self.n_delta_queries,
                delta_time_in_millis=int(self.delta_ms),
                warmed_shapes=self.warmed_shapes,
                warmup_time_in_millis=int(self.warmup_ms),
                mesh_shard_devices=self.mesh_shard_devices,
                mesh_replica_devices=self.mesh_replica_devices)
            for name in STAGES:
                out[f"{name}_time_in_millis"] = int(
                    self.stage_totals_ms[name])
            return out

    def stage_percentiles(self, skip: int = 0) -> Dict[str, dict]:
        """Per-stage p50/p99 over the retained per-request samples,
        skipping the first ``skip`` samples of each ring (bench: exclude
        a warmup window). Empty stages are omitted."""
        with self._cond:
            snap = {s: list(d)[skip:] for s, d in
                    self.stage_samples.items()}
        out = {}
        for name, vals in snap.items():
            if vals:
                a = np.asarray(vals)
                out[name] = {"p50_ms": round(float(np.percentile(a, 50)), 3),
                             "p99_ms": round(float(np.percentile(a, 99)), 3),
                             "n": len(vals)}
        return out

    # -- dispatch hooks (overridden by the kNN batcher) ---------------------

    def _pad_slot(self):
        """Inert query filling a pow2 padding slot."""
        return []

    @staticmethod
    def _query_key(terms):
        """Hashable identity of one query (in-flight dedup)."""
        return tuple(terms)

    def _dispatch(self, queries, k: int,
                  stages: Optional[dict] = None, view=None, params=None):
        """One device dispatch over the coalesced batch → (vals, hits,
        totals) aligned with ``queries``. Runs on a dispatcher thread,
        never under the queue lock. ``params`` on the text plane is the
        bucketed block-max ``("prune", bool)`` knob — co-batching
        already split on it, so the whole batch shares one value."""
        kw = {}
        if params is not None and params[0] == "prune":
            kw["prune"] = params[1]
        if view is not None:
            sv = getattr(self.plane, "serve_view", None)
            if sv is not None:
                # serving generation: resolve the delta tier for EXACTLY
                # the batch's segment view, so hit coordinates match the
                # callers' snapshot even if a refresh landed meanwhile
                return sv(queries, k=k, view=view, with_totals=True,
                          stages=stages, **kw)
        serve = getattr(self.plane, "serve", None)
        if serve is not None:
            # the plane's serving entry picks the backend path (eager
            # CSR scorer on CPU, ladder-shaped jitted step on TPU) and
            # refines the stage timings
            return serve(queries, k=k, with_totals=True, stages=stages,
                         **kw)
        # legacy/raw planes: size L through the ladder here
        L = None
        if hasattr(self.plane, "max_run_len"):
            L = self.plane.ladder_L(self.plane.max_run_len(queries))
        tiered = getattr(self.plane, "T_pad", 0) > 0 or None
        return self.plane.search(queries, k=k, L=L, tiered=tiered,
                                 with_totals=True)


class KnnPlaneMicroBatcher(PlaneMicroBatcher):
    """Micro-batcher over a ``DistributedKnnPlane``: concurrent REST kNN
    requests coalesce their query_vector batches into ONE blocked einsum
    dispatch, exactly like lexical queries coalesce through the text
    plane — the corpus streams through the MXU once per batch regardless
    of how many requests share it. Slots carry query vectors instead of
    term bags; there is no totals concept (kNN always matches its k)."""

    kind = "knn"

    def _kernel_family(self, params, plane_stages: dict) -> str:
        k = plane_stages.get("kernel") if plane_stages else None
        if k:
            return str(k)
        if params is not None and params[0] > 0:
            return "knn_ivf"
        return "knn_exact"

    def _pad_slot(self):
        # zero vector: scores 0.0 everywhere (or -‖v‖² under l2), results
        # discarded with the slot
        return np.zeros(max(self.plane.dim, 1), np.float32)

    @staticmethod
    def _query_key(terms):
        v = np.asarray(terms)
        return (v.shape, v.tobytes())

    def _serves_host(self) -> bool:
        return getattr(self.plane, "_host_pack", None) is not None

    def _warm_lattice(self, ks, max_b):
        plane = self.plane
        kbs = sorted({self._k_bucket(k) for k in ks})
        has_ivf = getattr(plane, "ivf", None) is not None
        b = 1
        while b <= min(max_b, self.max_batch):
            for kb in kbs:
                yield lambda B=b, kb=kb: plane.search(
                    np.zeros((B, max(plane.dim, 1)), np.float32), k=kb)
                if has_ivf:
                    # the IVF serving default is its own compile family
                    # ((nprobe, rerank, union-width) shapes); warm the
                    # default knobs so the first pruned dispatch of each
                    # B×k shape doesn't compile mid-traffic
                    yield lambda B=b, kb=kb: plane.serve(
                        np.zeros((B, max(plane.dim, 1)), np.float32),
                        k=kb)
            b <<= 1

    def _dispatch(self, queries, k: int,
                  stages: Optional[dict] = None, view=None, params=None):
        # plane.serve picks the backend-appropriate path (numpy blocked
        # scorer on CPU — the search_eager analogue — jitted step on
        # TPU); params carries the batch's bucketed IVF (nprobe, rerank)
        kw = {}
        if params is not None:
            kw = {"nprobe": params[0], "rerank": params[1]}
        if view is not None:
            sv = getattr(self.plane, "serve_view", None)
            if sv is not None:
                vals, hits = sv(np.stack(queries), k=k, view=view,
                                stages=stages, **kw)
                return vals, hits, [None] * len(queries)
        vals, hits = self.plane.serve(np.stack(queries), k=k,
                                      stages=stages, **kw)
        return vals, hits, [None] * len(queries)


class FusedPlaneMicroBatcher(PlaneMicroBatcher):
    """Micro-batcher over a ``query_planner.FusedPlanRunner``: planned
    hybrid/bool requests coalesce into ONE fused dispatch (lexical scan
    + kNN scan + fusion + rescore), exactly like bag queries coalesce
    through the per-plane batchers. Slots carry plan items
    (``query_planner.make_item``); co-batching splits on the plan's
    SHAPE via ``params`` (fusion kind, rescore mode, windows,
    bag-vs-bool route, knn knobs), so one dispatch always runs one
    compiled program."""

    kind = "fused"

    def _kernel_family(self, params, plane_stages: dict) -> str:
        return "fused"

    def _pad_slot(self):
        return {"bag": [], "clauses": [], "msm": 0, "qv": None,
                "kboost": 1.0, "knn_k": 0, "knn_nc": 0,
                "nprobe": None, "rerank": None, "fusion": None,
                "rc": 60, "wt": 0, "k": 0, "rescore": None,
                "aggs": None, "n_stages": 1, "key": ("pad",)}

    @staticmethod
    def _query_key(item):
        return item["key"]

    @staticmethod
    def _result(slot):
        if slot.error is not None:
            raise slot.error
        if slot.aggs is not None:
            # agg-carrying dispatch: the caller gets the 4-tuple form
            return slot.vals, slot.hits, slot.total, slot.aggs
        return slot.vals, slot.hits, slot.total

    def _serves_host(self) -> bool:
        return self.plane.serves_host()

    def _warm_lattice(self, ks, max_b):
        # fused shapes warm on first dispatch per shape; the lattice is
        # bounded by (B-pow2 × plan shape) and the bench asserts zero
        # steady-state compiles after that first window
        return iter(())

    def _dispatch(self, queries, k: int, stages: Optional[dict] = None,
                  view=None, params=None):
        prune = None
        if params is not None:
            for p in params:
                if isinstance(p, tuple) and p and p[0] == "prune":
                    prune = p[1]
        return self.plane.serve_view(queries, view=view, stages=stages,
                                     prune=prune)


def knn_dispatch_params(plane, nprobe: Optional[int],
                        rerank: Optional[int]):
    """Bucketed IVF (nprobe, rerank) dispatch params for one kNN plane
    — pow2-rounded UP (extra probes only improve recall) so co-batched
    queries share one compile shape. None when the plane has no IVF
    tier (the knobs are inert there)."""
    ivf = getattr(plane, "ivf", None)
    if ivf is None:
        return None
    if nprobe == 0:
        return (0, 0)             # exact scan explicitly requested
    from ..utils.shapes import round_up_pow2
    from ..parallel.dist_search import IVF_DEFAULT_RERANK
    want = ivf.default_nprobe if nprobe is None else max(1, int(nprobe))
    rr = IVF_DEFAULT_RERANK if not rerank else max(1, int(rerank))
    return (min(round_up_pow2(want, 1), ivf.nlist),
            round_up_pow2(rr, 1))


def batched_fused_search(runner, item: dict, *, view=None,
                         stages: Optional[dict] = None,
                         info: Optional[dict] = None,
                         prune: Optional[bool] = None):
    """Route one PLANNED request through the fused runner's
    micro-batcher. ``item`` is ``query_planner.make_item`` output;
    ``prune`` rides the lexical stage exactly like the text plane's
    knob. Returns (scores np.f32[k], hits [(shard, doc)...], total)."""
    from ..utils.shapes import round_up_pow2
    kbase = runner._knn_base()
    knn_params = knn_dispatch_params(kbase, item.get("nprobe"),
                                     item.get("rerank")) \
        if kbase is not None else None
    tbase = runner._text_base()
    prune_param = None
    if item.get("bag") is not None and \
            getattr(tbase, "blockmax", None) is not None:
        prune_param = ("prune", prune is not False)
    params = ("fused",
              item["bag"] is not None,
              item["fusion"],
              item["rescore"]["mode"] if item.get("rescore") else None,
              round_up_pow2(max(item["wt"], 1)),
              round_up_pow2(max(item["knn_nc"], 1)),
              knn_params, prune_param,
              # agg-plan tree shape: agg-carrying requests co-batch only
              # with the same tree structure (and never with agg-free
              # ones — the dispatch output arity differs)
              item["aggs"].shape if item.get("aggs") is not None
              else None)
    batcher = getattr(runner, "_microbatcher", None)
    if batcher is None:
        with _CREATE_LOCK:
            batcher = getattr(runner, "_microbatcher", None)
            if batcher is None:
                batcher = FusedPlaneMicroBatcher(runner)
                runner._microbatcher = batcher
    return batcher.search(item, item["k"], stages=stages, info=info,
                          view=view, params=params)


def batched_search(plane, terms: Sequence[str], k: int,
                   stages: Optional[dict] = None,
                   info: Optional[dict] = None, view=None,
                   prune: Optional[bool] = None):
    """Module entry: route one query through the plane's micro-batcher
    (created lazily on first use; plane rebuilds get a fresh one).
    ``view`` is the caller's segment-list snapshot — hit coordinates
    come back in that list's space.

    ``prune`` (block-max pruned scan, rank-safe): bucketed into the
    compile-shape lattice via the slot's ``params`` — co-batching splits
    on it, so a prune=off straggler never forces a whole batch eager.
    On a plane without a block-max tier the knob is inert and every
    request shares the knob-less dispatch; ``None`` resolves to the
    tier default (pruned when the tier exists)."""
    params = None
    if getattr(plane, "blockmax", None) is not None:
        params = ("prune", prune is not False)
    batcher = getattr(plane, "_microbatcher", None)
    if batcher is None:
        with _CREATE_LOCK:
            batcher = getattr(plane, "_microbatcher", None)
            if batcher is None:
                batcher = PlaneMicroBatcher(plane)
                plane._microbatcher = batcher
    return batcher.search(terms, k, stages=stages, info=info, view=view,
                          params=params)


def batched_knn_search(plane, query_vector, k: int, view=None,
                       stages: Optional[dict] = None,
                       info: Optional[dict] = None,
                       nprobe: Optional[int] = None,
                       rerank: Optional[int] = None):
    """Route one kNN query through the knn plane's micro-batcher.
    Returns (raw_scores[k'], hits [(shard, doc), ...]).

    ``nprobe``/``rerank`` (the ANN accuracy knobs) ride the k-bucket
    lattice: they are ROUNDED UP to a power of two here (never down —
    extra probes only improve recall), so co-batched queries share one
    compile shape and the warmup lattice covers live traffic. On a plane
    without an IVF tier the knobs are inert (exact brute force) and
    every request shares the knob-less dispatch."""
    params = knn_dispatch_params(plane, nprobe, rerank)
    batcher = getattr(plane, "_microbatcher", None)
    if batcher is None:
        with _CREATE_LOCK:
            batcher = getattr(plane, "_microbatcher", None)
            if batcher is None:
                batcher = KnnPlaneMicroBatcher(plane)
                plane._microbatcher = batcher
    vals, hits, _total = batcher.search(
        np.asarray(query_vector, np.float32), k, view=view,
        stages=stages, info=info, params=params)
    return vals, hits


_CREATE_LOCK = threading.Lock()
