"""Micro-batching queue for the serving plane: concurrent plane-eligible
queries coalesce into ONE device dispatch.

The reference amortizes per-query overhead through its search thread pool
(``threadpool/ThreadPool.java`` SEARCH lane) and batched partial reduction
(``action/search/QueryPhaseResultConsumer.java``); on a TPU the analogous
lever is the batch dimension of the dispatch itself — one ``plane.search``
over B queries costs barely more than B=1 (the kernel is bandwidth-bound
over the postings table, which every query in the batch shares).

Design ("batch whatever queued during the previous dispatch"): the first
arrival becomes the *leader* and dispatches immediately — zero added
latency at low load. Requests that arrive while the device is busy queue
up; when the leader finishes it promotes one waiter to leader for the
accumulated batch. Under load the batch size converges to
arrival-rate × dispatch-time with no tuning knob and no timed wait.

One batcher per plane (planes are per-(shard, field) and rebuilt on
refresh); dispatches on one plane are serialized by construction, distinct
planes dispatch concurrently.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

#: upper bound on queries per dispatch — past this the dispatch itself is
#: long enough that splitting reduces tail latency
MAX_BATCH = 64


class _Slot:
    __slots__ = ("terms", "k", "done", "is_leader", "vals", "hits",
                 "total", "error")

    def __init__(self, terms: Sequence[str], k: int):
        self.terms = terms
        self.k = k
        self.done = False
        self.is_leader = False
        self.vals = None
        self.hits: Optional[List[Tuple[int, int]]] = None
        self.total: Optional[int] = None
        self.error: Optional[BaseException] = None


class PlaneMicroBatcher:
    """Serializes and batches ``plane.search`` dispatches for one plane."""

    def __init__(self, plane, max_batch: int = MAX_BATCH):
        self.plane = plane
        self.max_batch = max_batch
        self._cond = threading.Condition()
        self._queue: List[_Slot] = []
        self._leader_active = False
        # observability (nodes stats / ROOFLINE measurements)
        self.n_dispatches = 0
        self.n_queries = 0
        self.max_seen_batch = 0

    def search(self, terms: Sequence[str], k: int):
        """One query through the batched dispatch. Returns
        (scores[k], hits[(shard, doc)...], exact total). Blocks until the
        dispatch that carries this query completes."""
        slot = _Slot(terms, k)
        with self._cond:
            self._queue.append(slot)
            if self._leader_active:
                while not (slot.done or slot.is_leader):
                    self._cond.wait()
                if slot.done:
                    return self._result(slot)
                # promoted: fall through to lead the accumulated batch
            else:
                self._leader_active = True
        self._lead()
        return self._result(slot)

    @staticmethod
    def _result(slot: _Slot):
        if slot.error is not None:
            raise slot.error
        return slot.vals, slot.hits, slot.total

    @staticmethod
    def _k_bucket(k: int) -> int:
        """Dispatch k rounded up to a power of two: co-batched queries only
        share a dispatch within the same bucket, so one size=10000 request
        neither inflates every size=10 neighbor's kernel nor churns the
        per-k compile cache (``dist_search._get_step`` caches per k)."""
        return 1 << max(0, (k - 1).bit_length())

    def _lead(self) -> None:
        """Dispatch the queued batch (which includes the caller's slot),
        then hand leadership to a waiter if more queued meanwhile. Only
        slots in the head slot's k-bucket join; others stay queued for the
        next leader."""
        with self._cond:
            kb = self._k_bucket(self._queue[0].k)
            batch = [s for s in self._queue[:self.max_batch]
                     if self._k_bucket(s.k) == kb]
            taken = set(map(id, batch))
            self._queue = [s for s in self._queue
                           if id(s) not in taken]
        # dispatch at the bucket's rounded-up k so the compile shape is
        # stable within a bucket (slots trim to their own k on fan-out)
        k = self._k_bucket(max(s.k for s in batch))
        # pad the batch to a power of two: every distinct traced B shape is
        # a fresh XLA compile — ragged arrival sizes would otherwise
        # compile dozens of programs (padding slots score as no-op
        # queries, same as the plane's own replica padding)
        b_pad = 1 << max(0, (len(batch) - 1).bit_length())
        queries = [s.terms for s in batch] + \
            [self._pad_slot() for _ in range(b_pad - len(batch))]
        try:
            vals, hits, totals = self._dispatch(queries, k)
        except BaseException as e:          # noqa: BLE001 — fan the error
            for s in batch:                 # out to every query in the batch
                s.error = e
        else:
            for i, s in enumerate(batch):
                s.vals = vals[i][:s.k]
                s.hits = hits[i][:s.k]
                s.total = totals[i]
        self.n_dispatches += 1
        self.n_queries += len(batch)
        self.max_seen_batch = max(self.max_seen_batch, len(batch))
        with self._cond:
            for s in batch:
                s.done = True
            if self._queue:
                self._queue[0].is_leader = True
            else:
                self._leader_active = False
            self._cond.notify_all()

    # -- dispatch hooks (overridden by the kNN batcher) ---------------------

    def _pad_slot(self):
        """Inert query filling a pow2 padding slot."""
        return []

    def _dispatch(self, queries, k: int):
        """One device dispatch over the coalesced batch → (vals, hits,
        totals) aligned with ``queries``. Runs outside the queue lock."""
        # size L to the batch through the plane's 4-rung ladder: ordinary
        # short-run batches skip the worst-case sparse-merge cost
        # (pinning L_cap made every dispatch pay it — the difference
        # between ~10ms and multi-second dispatches on the full corpus),
        # while the rung count bounds serving-time compiles to at most 4
        # shapes per (B, Q, k) family
        L = None
        if hasattr(self.plane, "max_run_len"):
            L = self.plane.ladder_L(self.plane.max_run_len(queries))
        tiered = getattr(self.plane, "T_pad", 0) > 0 or None
        return self.plane.search(queries, k=k, L=L, tiered=tiered,
                                 with_totals=True)


class KnnPlaneMicroBatcher(PlaneMicroBatcher):
    """Micro-batcher over a ``DistributedKnnPlane``: concurrent REST kNN
    requests coalesce their query_vector batches into ONE blocked einsum
    dispatch, exactly like lexical queries coalesce through the text
    plane — the corpus streams through the MXU once per batch regardless
    of how many requests share it. Slots carry query vectors instead of
    term bags; there is no totals concept (kNN always matches its k)."""

    def _pad_slot(self):
        # zero vector: scores 0.0 everywhere (or -‖v‖² under l2), results
        # discarded with the slot
        return np.zeros(max(self.plane.dim, 1), np.float32)

    def _dispatch(self, queries, k: int):
        # plane.serve picks the backend-appropriate path (numpy blocked
        # scorer on CPU — the search_eager analogue — jitted step on TPU)
        vals, hits = self.plane.serve(np.stack(queries), k=k)
        return vals, hits, [None] * len(queries)


def batched_search(plane, terms: Sequence[str], k: int):
    """Module entry: route one query through the plane's micro-batcher
    (created lazily on first use; plane rebuilds get a fresh one)."""
    batcher = getattr(plane, "_microbatcher", None)
    if batcher is None:
        with _CREATE_LOCK:
            batcher = getattr(plane, "_microbatcher", None)
            if batcher is None:
                batcher = PlaneMicroBatcher(plane)
                plane._microbatcher = batcher
    return batcher.search(terms, k)


def batched_knn_search(plane, query_vector, k: int):
    """Route one kNN query through the knn plane's micro-batcher.
    Returns (raw_scores[k'], hits [(shard, doc), ...])."""
    batcher = getattr(plane, "_microbatcher", None)
    if batcher is None:
        with _CREATE_LOCK:
            batcher = getattr(plane, "_microbatcher", None)
            if batcher is None:
                batcher = KnnPlaneMicroBatcher(plane)
                plane._microbatcher = batcher
    vals, hits, _total = batcher.search(
        np.asarray(query_vector, np.float32), k)
    return vals, hits


_CREATE_LOCK = threading.Lock()
