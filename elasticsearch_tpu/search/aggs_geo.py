"""Geo aggregations + adaptive histograms + adjacency matrix +
significant_text.

References: ``bucket/geogrid/GeoHashGridAggregator.java`` /
``GeoTileGridAggregator.java``, ``bucket/range/GeoDistanceAggregationBuilder
.java``, ``bucket/histogram/AutoDateHistogramAggregator.java``,
``bucket/histogram/VariableWidthHistogramAggregator.java``,
``bucket/adjacency/AdjacencyMatrixAggregator.java``,
``bucket/terms/SignificantTextAggregator.java``.

Geo points live as paired ``field._lat`` / ``field._lon`` doc-value
columns (lockstep order, see ``mapping.py``). The adaptive histograms
(auto_date / variable_width) must see ALL values before choosing their
buckets, so their ``collect`` stages the per-segment inputs (including
the (ctx, seg, mask) triple for sub-agg collection) and the global
bucketing happens in ``reduce`` — the same single-global-reduce shape the
coordinator already guarantees (``dist_query.py`` reduces once,
cross-shard, in process)."""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..common.errors import ParsingError
from ..index.mapping import GeoPointFieldType, format_date_millis
from .aggregations import (Aggregator, BucketAggregator, _bucket_payload,
                           _sub_results,
                           _numeric_pairs, _reduce_subs)
from .aggs_extra import SignificantTermsAgg, _live_parents
from .positional import haversine_meters, parse_distance_meters

# ---------------------------------------------------------------------------
# geo keys
# ---------------------------------------------------------------------------

from ..index.mapping import geohash_encode  # noqa: F401 (re-export)


#: web-mercator latitude bound (GeoTileUtils.LATITUDE_MASK)
_MERCATOR_LAT_MAX = 85.0511287798066


def geotile_key(lat: float, lon: float, zoom: int) -> str:
    """Web-mercator tile ``z/x/y`` (``GeoTileUtils.java``)."""
    tiles = 1 << zoom
    x = int(math.floor((lon + 180.0) / 360.0 * tiles))
    lat_rad = math.radians(
        min(max(lat, -_MERCATOR_LAT_MAX), _MERCATOR_LAT_MAX))
    y = int(math.floor(
        (1.0 - math.log(math.tan(lat_rad) + 1.0 / math.cos(lat_rad))
         / math.pi) / 2.0 * tiles))
    x = min(max(x, 0), tiles - 1)
    y = min(max(y, 0), tiles - 1)
    return f"{zoom}/{x}/{y}"


def _geo_pairs(seg, field: str, mapper=None):
    """(docs int32[M], lat f64[M], lon f64[M]) or None."""
    if mapper is not None:
        ft = mapper.field_type(field)
        if ft is not None and ft.name != field:
            field = ft.name
    la = seg.numeric_fields.get(f"{field}._lat")
    lo = seg.numeric_fields.get(f"{field}._lon")
    if la is None or lo is None or la.vals_host.size == 0:
        return None
    return la.docs_host, la.vals_host, lo.vals_host


# ---------------------------------------------------------------------------
# geo grid aggs
# ---------------------------------------------------------------------------


class _GeoGridAgg(BucketAggregator):
    default_precision = 5
    min_precision = 1
    max_precision = 12

    def __init__(self, body: dict):
        self.field = body.get("field")
        if self.field is None:
            raise ParsingError("geo grid aggregation requires [field]")
        self.precision = int(body.get("precision", self.default_precision))
        if not (self.min_precision <= self.precision
                <= self.max_precision):
            raise ParsingError(
                f"Invalid geo grid precision of {self.precision}. Must be "
                f"between {self.min_precision} and {self.max_precision}.")
        self.size = int(body.get("size", 10000))
        self.shard_size = int(body.get("shard_size", max(self.size, 10000)))

    def _cell(self, lat: float, lon: float) -> str:
        raise NotImplementedError

    def collect(self, ctx, seg, mask):
        geo = _geo_pairs(seg, self.field, ctx.mapper)
        if geo is None:
            return {}
        docs, lat, lon = geo
        pm = mask[docs]
        cell_docs: Dict[str, set] = {}
        for d, la, lo in zip(docs[pm], lat[pm], lon[pm]):
            cell_docs.setdefault(self._cell(la, lo), set()).add(int(d))
        out = {}
        for cell, ds in cell_docs.items():
            if self.subs:
                bm = np.zeros(mask.shape[0], bool)
                bm[list(ds)] = True
                out[cell] = _bucket_payload(self, ctx, seg, bm)
            else:
                out[cell] = (len(ds), {})
        return out

    def reduce(self, partials):
        merged: Dict[str, List] = {}
        for p in partials:
            for cell, item in p.items():
                merged.setdefault(cell, []).append(item)
        rows = []
        for cell, items in merged.items():
            count = sum(c for c, _ in items)
            subs = _reduce_subs(self, [s for _, s in items]) \
                if self.subs else {}
            rows.append((cell, count, subs))
        rows.sort(key=lambda r: (-r[1], r[0]))
        buckets = []
        for cell, count, subs in rows[: self.size]:
            b = {"key": cell, "doc_count": count}
            b.update(subs)
            buckets.append(b)
        return {"buckets": buckets}


class GeoHashGridAgg(_GeoGridAgg):
    default_precision = 5
    max_precision = 12

    def _cell(self, lat, lon):
        return geohash_encode(lat, lon, self.precision)


class GeoTileGridAgg(_GeoGridAgg):
    default_precision = 7
    min_precision = 0
    max_precision = 29

    def _cell(self, lat, lon):
        return geotile_key(lat, lon, self.precision)


# ---------------------------------------------------------------------------
# geo_distance range agg
# ---------------------------------------------------------------------------


class GeoDistanceAgg(BucketAggregator):
    def __init__(self, body: dict):
        self.field = body.get("field")
        self.origin = body.get("origin")
        self.ranges = body.get("ranges")
        if self.field is None or self.origin is None or not self.ranges:
            raise ParsingError(
                "geo_distance requires [field], [origin] and [ranges]")
        self.olat, self.olon = GeoPointFieldType("origin").parse_value(
            self.origin)
        self.unit = body.get("unit", "m")
        self.unit_m = parse_distance_meters(f"1{self.unit}")
        self.keyed = bool(body.get("keyed", False))

    def _range_key(self, r) -> str:
        if "key" in r:
            return r["key"]
        f = "*" if r.get("from") is None else f"{float(r['from'])}"
        t = "*" if r.get("to") is None else f"{float(r['to'])}"
        return f"{f}-{t}"

    def _doc_distances(self, ctx, seg, mask):
        """float64[n_pad] min distance per doc (inf where absent)."""
        geo = _geo_pairs(seg, self.field, ctx.mapper)
        dist = np.full(mask.shape[0], np.inf)
        if geo is None:
            return dist
        docs, lat, lon = geo
        d = haversine_meters(lat, lon, self.olat, self.olon) / self.unit_m
        np.minimum.at(dist, docs, d)
        return dist

    def collect(self, ctx, seg, mask):
        dist = self._doc_distances(ctx, seg, mask)
        out = {}
        for r in self.ranges:
            key = self._range_key(r)
            sel = mask.copy()
            if r.get("from") is not None:
                sel &= dist >= float(r["from"])
            if r.get("to") is not None:
                sel &= dist < float(r["to"])
            sel &= np.isfinite(dist)
            if self.subs:
                out[key] = _bucket_payload(self, ctx, seg, sel)
            else:
                out[key] = (int(sel.sum()), {})
        return out

    def reduce(self, partials):
        buckets = []
        for r in self.ranges:
            key = self._range_key(r)
            items = [p[key] for p in partials if key in p]
            count = sum(c for c, _ in items)
            subs = _reduce_subs(self, [s for _, s in items]) \
                if self.subs else {}
            b = {"key": key, "doc_count": count}
            if r.get("from") is not None:
                b["from"] = float(r["from"])
            if r.get("to") is not None:
                b["to"] = float(r["to"])
            b.update(subs)
            buckets.append(b)
        if self.keyed:
            return {"buckets": {b.pop("key"): b for b in buckets}}
        return {"buckets": buckets}


# ---------------------------------------------------------------------------
# geo metric aggs
# ---------------------------------------------------------------------------


class GeoBoundsAgg(Aggregator):
    def __init__(self, body: dict):
        self.field = body.get("field")
        if self.field is None:
            raise ParsingError("geo_bounds requires [field]")

    def collect(self, ctx, seg, mask):
        geo = _geo_pairs(seg, self.field, ctx.mapper)
        if geo is None:
            return None
        docs, lat, lon = geo
        pm = mask[docs]
        if not pm.any():
            return None
        return (float(lat[pm].max()), float(lat[pm].min()),
                float(lon[pm].min()), float(lon[pm].max()))

    def reduce(self, partials):
        parts = [p for p in partials if p is not None]
        if not parts:
            return {}
        top = max(p[0] for p in parts)
        bottom = min(p[1] for p in parts)
        left = min(p[2] for p in parts)
        right = max(p[3] for p in parts)
        return {"bounds": {"top_left": {"lat": top, "lon": left},
                           "bottom_right": {"lat": bottom, "lon": right}}}


class GeoCentroidAgg(Aggregator):
    def __init__(self, body: dict):
        self.field = body.get("field")
        if self.field is None:
            raise ParsingError("geo_centroid requires [field]")

    def collect(self, ctx, seg, mask):
        geo = _geo_pairs(seg, self.field, ctx.mapper)
        if geo is None:
            return (0.0, 0.0, 0)
        docs, lat, lon = geo
        pm = mask[docs]
        return (float(lat[pm].sum()), float(lon[pm].sum()), int(pm.sum()))

    def reduce(self, partials):
        slat = sum(p[0] for p in partials)
        slon = sum(p[1] for p in partials)
        n = sum(p[2] for p in partials)
        if n == 0:
            return {"count": 0}
        return {"location": {"lat": slat / n, "lon": slon / n}, "count": n}


# ---------------------------------------------------------------------------
# auto_date_histogram
# ---------------------------------------------------------------------------

_MS_S, _MS_M, _MS_H, _MS_D = 1000, 60_000, 3_600_000, 86_400_000

#: (unit suffix, to-unit-index fn, from-unit-index fn, inner multiples)
#: mirrors AutoDateHistogramAggregationBuilder.buildRoundings
def _dt_from_ms(ms: float):
    import datetime
    return datetime.datetime.fromtimestamp(ms / 1000.0,
                                           tz=datetime.timezone.utc)


def _month_idx(ms: float) -> int:
    dt = _dt_from_ms(ms)
    return dt.year * 12 + (dt.month - 1)


def _month_ms(idx: int) -> float:
    import datetime
    y, m = divmod(idx, 12)
    return datetime.datetime(y, m + 1, 1,
                             tzinfo=datetime.timezone.utc).timestamp() * 1000


def _year_idx(ms: float) -> int:
    return _dt_from_ms(ms).year


def _year_ms(idx: int) -> float:
    import datetime
    return datetime.datetime(idx, 1, 1,
                             tzinfo=datetime.timezone.utc).timestamp() * 1000


_ROUNDINGS = [
    ("s", lambda ms: int(ms // _MS_S), lambda i: i * _MS_S,
     (1, 5, 10, 30)),
    ("m", lambda ms: int(ms // _MS_M), lambda i: i * _MS_M,
     (1, 5, 10, 30)),
    ("h", lambda ms: int(ms // _MS_H), lambda i: i * _MS_H, (1, 3, 12)),
    ("d", lambda ms: int(ms // _MS_D), lambda i: i * _MS_D, (1, 7)),
    ("M", _month_idx, _month_ms, (1, 3)),
    ("y", _year_idx, _year_ms, (1, 5, 10, 20, 50, 100)),
]

#: fixed-width unit sizes in ms for the vectorized index computation
_UNIT_MS = [_MS_S, _MS_M, _MS_H, _MS_D]


def _unit_indices(vals: np.ndarray, ri: int) -> np.ndarray:
    """Vectorized ``_ROUNDINGS[ri]`` index computation: one numpy pass
    for the fixed-width units; months/years fall back to the scalar
    calendar functions (rare at realistic bucket caps)."""
    if ri < len(_UNIT_MS):
        return (vals // _UNIT_MS[ri]).astype(np.int64)
    to_idx = _ROUNDINGS[ri][1]
    return np.array([to_idx(x) for x in vals], np.int64)


class AutoDateHistogramAgg(BucketAggregator):
    """Picks the smallest rounding from the reference's ladder whose bucket
    count (anchored at the FIRST bucket, merged in groups of ``k`` inner
    units) fits the target. Global choice → collection is staged and the
    bucketing happens at reduce (see module docstring)."""

    def __init__(self, body: dict):
        self.field = body.get("field")
        if self.field is None:
            raise ParsingError("auto_date_histogram requires [field]")
        self.buckets = int(body.get("buckets", 10))
        if self.buckets <= 0:
            raise ParsingError("[buckets] must be a positive integer")

    def collect(self, ctx, seg, mask):
        pairs = _numeric_pairs(seg, self.field, ctx.mapper)
        vals = np.empty(0, np.float64)
        if pairs is not None:
            docs, v = pairs
            vals = v[mask[docs]]
        return {"vals": vals, "triple": (ctx, seg, mask)}

    def collect_wire(self, ctx, seg, mask):
        """Data-only partial for cross-node shipping (no live segment
        refs). Bucket counts come from value histograms — exact for
        single-valued date fields. Sub-aggregations are pre-collected at
        the finest k=1 rounding unit whose local bucket count stays
        bounded; the reduce re-bins those unit buckets into the globally
        chosen interval (units nest exactly in UTC: s→m→h→d→M→y)."""
        pairs = _numeric_pairs(seg, self.field, ctx.mapper)
        out = {"vals": np.empty(0, np.float64)}
        if pairs is None:
            return out
        docs, v = pairs
        sel = mask[docs]
        vals = v[sel]
        out["vals"] = vals
        if not self.subs or vals.size == 0:
            return out
        cap = max(self.buckets, 1) * 50
        ri = len(_ROUNDINGS) - 1
        idxs = None
        for r in range(len(_ROUNDINGS)):
            cand = _unit_indices(vals, r)
            if np.unique(cand).size <= cap:
                ri, idxs = r, cand
                break
        if idxs is None:
            idxs = _unit_indices(vals, ri)
        sub_by_idx = {}
        sel_docs = docs[sel]
        for idx in np.unique(idxs):
            bm = np.zeros(mask.shape[0], bool)
            bm[sel_docs[idxs == idx]] = True
            bm &= mask
            sub_by_idx[int(idx)] = _sub_results(self, ctx, seg, bm)
        out["subs_unit"] = ri
        out["b"] = sub_by_idx
        return out

    def reduce(self, partials):
        all_vals = np.concatenate([p["vals"] for p in partials]) \
            if partials else np.empty(0)
        if all_vals.size == 0:
            return {"buckets": [], "interval": "1s"}
        self._debug = {"surviving_buckets": int(
            np.unique(all_vals // 86_400_000).size)}
        vmin, vmax = float(all_vals.min()), float(all_vals.max())
        chosen = None
        for suffix, to_idx, from_idx, inners in _ROUNDINGS:
            lo, hi = to_idx(vmin), to_idx(vmax)
            for k in inners:
                if (hi - lo) // k + 1 <= self.buckets:
                    chosen = (suffix, to_idx, from_idx, k, lo, hi)
                    break
            if chosen:
                break
        if chosen is None:      # fall back to the coarsest rounding
            suffix, to_idx, from_idx, inners = _ROUNDINGS[-1]
            k = inners[-1]
            lo, hi = to_idx(vmin), to_idx(vmax)
            chosen = (suffix, to_idx, from_idx, k, lo, hi)
        suffix, to_idx, from_idx, k, lo, hi = chosen
        nbuckets = (hi - lo) // k + 1
        buckets = []
        for i in range(nbuckets):
            start_idx = lo + i * k
            key_ms = float(from_idx(start_idx))
            end_ms = float(from_idx(start_idx + k))
            count = 0
            sub_partials = []
            for p in partials:
                if "triple" not in p:
                    # wire partial: value-histogram count (exact for
                    # single-valued fields); subs re-bin by unit bucket
                    count += int(((p["vals"] >= key_ms)
                                  & (p["vals"] < end_ms)).sum())
                    if self.subs and p.get("b"):
                        from_local = _ROUNDINGS[p["subs_unit"]][2]
                        for uidx, sub in p["b"].items():
                            ms = float(from_local(uidx))
                            if key_ms <= ms < end_ms:
                                sub_partials.append(sub)
                    continue
                ctx, seg, mask = p["triple"]
                pairs = _numeric_pairs(seg, self.field, ctx.mapper)
                if pairs is None:
                    continue
                docs, v = pairs
                sel = mask[docs] & (v >= key_ms) & (v < end_ms)
                bm = np.zeros(mask.shape[0], bool)
                bm[docs[sel]] = True
                bm &= mask
                count += int(bm.sum())
                if self.subs:
                    sub_partials.append(
                        _bucket_payload(self, ctx, seg, bm)[1])
            b = {"key": key_ms, "key_as_string": format_date_millis(key_ms),
                 "doc_count": count}
            if isinstance(b["key"], float) and b["key"].is_integer():
                b["key"] = int(b["key"])
            if self.subs:
                b.update(_reduce_subs(self, sub_partials))
            buckets.append(b)
        return {"buckets": buckets, "interval": f"{k}{suffix}"}


# ---------------------------------------------------------------------------
# variable_width_histogram
# ---------------------------------------------------------------------------


class VariableWidthHistogramAgg(BucketAggregator):
    """1-D agglomerative clustering: start from distinct values, repeatedly
    merge the closest adjacent clusters until the target count is reached.
    Cluster key = mean of member values."""

    def __init__(self, body: dict):
        self.field = body.get("field")
        if self.field is None:
            raise ParsingError("variable_width_histogram requires [field]")
        self.buckets = int(body.get("buckets", 10))
        if self.buckets <= 0:
            raise ParsingError(
                "[buckets] must be a positive, non-zero integer")

    def collect(self, ctx, seg, mask):
        pairs = _numeric_pairs(seg, self.field, ctx.mapper)
        vals = np.empty(0, np.float64)
        if pairs is not None:
            docs, v = pairs
            vals = v[mask[docs]]
        return {"vals": vals, "triple": (ctx, seg, mask)}

    #: distinct-value bound for per-value sub-partials on the wire
    WIRE_SUB_VALUE_CAP = 2048

    def collect_wire(self, ctx, seg, mask):
        """Data-only partial for cross-node shipping. Sub-aggregations
        pre-collect per DISTINCT VALUE (clusters are decided globally at
        reduce, so the finest shippable granularity is the value itself);
        bounded by WIRE_SUB_VALUE_CAP distinct values."""
        pairs = _numeric_pairs(seg, self.field, ctx.mapper)
        out = {"vals": np.empty(0, np.float64)}
        if pairs is None:
            return out
        docs, v = pairs
        sel = mask[docs]
        vals = v[sel]
        out["vals"] = vals
        if not self.subs or vals.size == 0:
            return out
        uniq = np.unique(vals)
        if uniq.size > self.WIRE_SUB_VALUE_CAP:
            return out                   # counts stay exact; subs degrade
        sel_docs = docs[sel]
        vb = {}
        for uv in uniq:
            bm = np.zeros(mask.shape[0], bool)
            bm[sel_docs[vals == uv]] = True
            bm &= mask
            vb[float(uv)] = _sub_results(self, ctx, seg, bm)
        out["vb"] = vb
        return out

    def reduce(self, partials):
        all_vals = np.sort(np.concatenate([p["vals"] for p in partials])) \
            if partials else np.empty(0)
        if all_vals.size == 0:
            return {"buckets": []}
        uniq, counts = np.unique(all_vals, return_counts=True)
        # merging the smallest adjacent gap until k clusters remain is
        # equivalent to cutting at the k-1 LARGEST gaps (gaps never change
        # as clusters merge) — O(n log n), no iterative merge loop
        k = min(self.buckets, uniq.size)
        gaps = np.diff(uniq)
        cut_after = np.sort(np.argsort(gaps)[::-1][: k - 1]) \
            if k > 1 else np.empty(0, np.int64)
        starts = np.concatenate(([0], cut_after + 1))
        ends = np.concatenate((cut_after, [uniq.size - 1]))
        clusters = list(zip(starts.tolist(), ends.tolist()))
        buckets = []
        for c0, c1 in clusters:
            lo_v, hi_v = float(uniq[c0]), float(uniq[c1])
            n_vals = int(counts[c0:c1 + 1].sum())
            member_sum = float((uniq[c0:c1 + 1] * counts[c0:c1 + 1]).sum())
            key = member_sum / n_vals
            # doc_count is DOC-based (a multi-valued doc counts once per
            # cluster), so recount through per-segment doc masks
            n_docs = 0
            sub_partials = []
            for p in partials:
                if "triple" not in p:
                    n_docs += int(((p["vals"] >= lo_v)
                                   & (p["vals"] <= hi_v)).sum())
                    if self.subs and p.get("vb"):
                        sub_partials.extend(
                            sub for uv, sub in p["vb"].items()
                            if lo_v <= uv <= hi_v)
                    continue
                ctx, seg, mask = p["triple"]
                pairs = _numeric_pairs(seg, self.field, ctx.mapper)
                if pairs is None:
                    continue
                docs, v = pairs
                sel = mask[docs] & (v >= lo_v) & (v <= hi_v)
                bm = np.zeros(mask.shape[0], bool)
                bm[docs[sel]] = True
                bm &= mask
                n_docs += int(bm.sum())
                if self.subs:
                    sub_partials.append(
                        _bucket_payload(self, ctx, seg, bm)[1])
            b = {"min": lo_v, "key": key, "max": hi_v, "doc_count": n_docs}
            if self.subs:
                b.update(_reduce_subs(self, sub_partials))
            buckets.append(b)
        return {"buckets": buckets}


# ---------------------------------------------------------------------------
# adjacency_matrix
# ---------------------------------------------------------------------------


class AdjacencyMatrixAgg(BucketAggregator):
    def __init__(self, body: dict):
        filters = body.get("filters")
        if not isinstance(filters, dict) or not filters:
            raise ParsingError("adjacency_matrix requires [filters]")
        from .query_dsl import parse_query
        self.names = sorted(filters)
        self.queries = {n: parse_query(filters[n]) for n in self.names}
        self.separator = str(body.get("separator", "&"))

    def collect(self, ctx, seg, mask):
        fmasks = {}
        for n, q in self.queries.items():
            _, m = q.execute(ctx.shard_ctx, seg)
            fmasks[n] = mask & np.asarray(m)[: mask.shape[0]]
        out = {}
        keys = []
        for i, a in enumerate(self.names):
            keys.append((a, fmasks[a]))
            for b in self.names[i + 1:]:
                keys.append((f"{a}{self.separator}{b}",
                             fmasks[a] & fmasks[b]))
        for key, bm in keys:
            if self.subs:
                out[key] = _bucket_payload(self, ctx, seg, bm)
            else:
                out[key] = (int(bm.sum()), {})
        return out

    def reduce(self, partials):
        merged: Dict[str, List] = {}
        for p in partials:
            for key, item in p.items():
                merged.setdefault(key, []).append(item)
        buckets = []
        for key in sorted(merged):
            items = merged[key]
            count = sum(c for c, _ in items)
            if count == 0:
                continue
            b = {"key": key, "doc_count": count}
            if self.subs:
                b.update(_reduce_subs(self, [s for _, s in items]))
            buckets.append(b)
        return {"buckets": buckets}


# ---------------------------------------------------------------------------
# significant_text
# ---------------------------------------------------------------------------


class SignificantTextAgg(SignificantTermsAgg):
    """significant_terms over a TEXT field's postings: per-term foreground
    doc counts come from the postings CSR restricted to the bucket mask
    (vectorized bincount over posting term-ids). ``filter_duplicate_text``
    reconstructs matched docs' token streams from the position CSR and
    strips 6-gram runs already seen in earlier matched docs — the
    ``DeDuplicatingTokenFilter`` behavior."""

    DUP_SEQ = 6

    def __init__(self, body: dict):
        super().__init__(body)
        self.filter_duplicate_text = bool(
            body.get("filter_duplicate_text", False))

    def _dedup_fg_counts(self, f, fg_docs: np.ndarray) -> Dict[int, int]:
        """term-id → fg doc count, counting only tokens outside duplicated
        6-gram runs. Token streams are rebuilt per doc from positions."""
        terms_sorted = list(f.term_ids)
        seqs: Dict[int, Dict[int, int]] = {int(d): {} for d in fg_docs}
        fg_set = set(seqs)
        for tid in range(len(terms_sorted)):
            s, e = int(f.offsets[tid]), int(f.offsets[tid + 1])
            for p in range(s, e):
                d = int(f.docs_host[p])
                if d in fg_set:
                    for pos in f.pos_flat[
                            f.pos_offsets[p]:f.pos_offsets[p + 1]]:
                        seqs[d][int(pos)] = tid
        seen_grams = set()
        counts: Dict[int, int] = {}
        w = self.DUP_SEQ
        for d in sorted(fg_set):
            positions = sorted(seqs[d])
            seq = [seqs[d][p] for p in positions]
            dup = [False] * len(seq)
            new_grams = []
            for i in range(len(seq) - w + 1):
                gram = tuple(seq[i:i + w])
                if gram in seen_grams:
                    for j in range(i, i + w):
                        dup[j] = True
                else:
                    new_grams.append(gram)
            seen_grams.update(new_grams)
            for tid in {t for t, isdup in zip(seq, dup) if not isdup}:
                counts[tid] = counts.get(tid, 0) + 1
        return counts

    def collect(self, ctx, seg, mask):
        field = self.field
        ft = ctx.mapper.field_type(field) if ctx.mapper else None
        if ft is not None and ft.name != field:
            field = ft.name
        f = seg.text_fields.get(field)
        if f is None:
            tok = self._bg_token(seg)
            if tok not in self._seg_bg:
                self._seg_bg[tok] = (
                    int(_live_parents(
                        seg, mask.shape[0])[: seg.n_docs].sum()), {})
            return {"fg_total": int(mask[: seg.n_docs].sum()),
                    "terms": {}, "seg_bg": self._seg_bg}
        if not self.filter_duplicate_text:
            return self._collect_text(ctx, seg, mask, f)
        v = len(f.term_ids)
        tid = np.repeat(np.arange(v, dtype=np.int64),
                        np.diff(f.offsets).astype(np.int64))
        terms_sorted = list(f.term_ids)
        tok = self._bg_token(seg)
        if tok not in self._seg_bg:
            bg_mask = self._bg_mask(ctx, seg, mask)
            bg = np.bincount(tid[bg_mask[f.docs_host]], minlength=v)
            self._seg_bg[tok] = (
                int(bg_mask[: seg.n_docs].sum()),
                {terms_sorted[i]: int(bg[i]) for i in np.flatnonzero(bg)})
        fg_docs = np.unique(f.docs_host[mask[f.docs_host]])
        fg_of = self._dedup_fg_counts(f, fg_docs)
        t = {terms_sorted[t_id]: c for t_id, c in fg_of.items() if c}
        return {"fg_total": int(mask[: seg.n_docs].sum()), "terms": t,
                "seg_bg": self._seg_bg}


# ---------------------------------------------------------------------------
# registration
# ---------------------------------------------------------------------------

from .aggregations import _AGG_PARSERS      # noqa: E402

_AGG_PARSERS.update({
    "geohash_grid": GeoHashGridAgg,
    "geotile_grid": GeoTileGridAgg,
    "geo_distance": GeoDistanceAgg,
    "geo_bounds": GeoBoundsAgg,
    "geo_centroid": GeoCentroidAgg,
    "auto_date_histogram": AutoDateHistogramAgg,
    "variable_width_histogram": VariableWidthHistogramAgg,
    "adjacency_matrix": AdjacencyMatrixAgg,
    "significant_text": SignificantTextAgg,
})
