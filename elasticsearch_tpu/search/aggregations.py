"""Aggregations: bucket/metric/pipeline analytics over search results.

Re-design of the reference's aggregation framework
(``search/aggregations/`` — 498 files; two-pass model: per-segment
``Aggregator.collect(doc)`` into BigArrays buckets, then coordinator
``InternalAggregation.reduce`` — ``search/aggregations/AggregatorBase.java``,
``InternalAggregations.java``).

TPU-first execution model: there is no per-doc collect loop. The query tree
already produced a dense ``(scores, mask)`` pair per segment on device; each
aggregation is a *masked columnar reduction* over the segment's doc-values
pair columns ``(docs, values)``:

1. the per-pair mask is one device gather: ``pair_mask = mask[docs]``;
2. bucket assignment and reductions are vectorized array ops — ordinal
   ``segment_sum`` for terms, ``floor((v-offset)/interval)`` for histograms,
   masked sum/min/max for metrics (see ``ops/aggs.py`` for the device
   kernels used on the hot paths; exact float64 reductions run host-side
   where TPU f32 would lose precision, e.g. epoch-millis histograms);
3. per-segment partials are plain dicts merged by ``Aggregator.reduce`` —
   the same merge runs across shards on the coordinating side.

Sub-aggregations refine the parent's mask per bucket (array AND), which maps
the reference's bucket-ordinal machinery onto plain mask algebra.
"""

from __future__ import annotations

import math
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..common.errors import (ElasticsearchError,
                             IllegalArgumentError, ParsingError)
from ..index.mapping import (
    BooleanFieldType, DateFieldType, KeywordFieldType, MapperService,
    NumberFieldType, RangeFieldType, RuntimeFieldType, format_date_millis,
    parse_date_millis)
from ..index.segment import Segment
from ..ops import aggs as ops_aggs

INT_TYPES = {"long", "integer", "short", "byte"}


def _mix64(v: int) -> int:
    """hppc ``BitMixer.mix64`` (Stafford mix13 variant, NOT murmur
    fmix64) — the hash behind numeric terms partitioning
    (``IncludeExclude.PartitionedLongFilter``)."""
    m = (1 << 64) - 1
    v &= m
    v = ((v ^ (v >> 32)) * 0x4CD6944C5CC20B6D) & m
    v = ((v ^ (v >> 29)) * 0xFC12C5B19D3259E9) & m
    return v ^ (v >> 32)


def _device_mask(seg, mask: np.ndarray):
    """Upload a host doc mask padded to the segment's n_pad (pair-doc
    sentinels gather False via OOB-fill)."""
    import jax.numpy as jnp
    if mask.shape[0] == seg.n_pad:
        return jnp.asarray(mask)
    padded = np.zeros(seg.n_pad, bool)
    padded[: mask.shape[0]] = mask
    return jnp.asarray(padded)


# ---------------------------------------------------------------------------
# value sources
# ---------------------------------------------------------------------------


def _concrete(mapper, field: str) -> str:
    """Field alias → target path (FieldAliasMapper)."""
    if mapper is None:
        return field
    ft = mapper.field_type(field)
    return ft.name if ft is not None and ft.name != field else field


def _numeric_pairs(seg: Segment, field: str, mapper=None):
    """(docs int32[M], vals float64[M]) host-side exact values, or None.
    Runtime fields materialize their computed column as pairs."""
    field = _concrete(mapper, field)
    f = seg.numeric_fields.get(field)
    if f is not None and f.docs_host.shape[0] > 0:
        return f.docs_host, f.vals_host
    if mapper is not None:
        ft = mapper.field_type(field)
        if isinstance(ft, RuntimeFieldType):
            col = ft.column(seg)[: seg.n_docs]
            docs = np.flatnonzero(~np.isnan(col)).astype(np.int32)
            if docs.size:
                return docs, col[docs]
    return None


def _doc_weights(seg: Segment):
    """float64[n_docs] per-doc count weights from the _doc_count meta
    field (DocCountFieldMapper), or None when absent."""
    dc = seg.numeric_fields.get("_doc_count")
    if dc is None or dc.docs_host.size == 0:
        return None
    w = np.ones(seg.n_docs, np.float64)
    w[dc.docs_host] = dc.vals_host
    return w


def _keyword_pairs(seg: Segment, field: str, mapper=None):
    """(docs int32[M], ords int32[M], ord_terms list) or None."""
    field = _concrete(mapper, field)
    f = seg.keyword_fields.get(field)
    if f is None or f.dv_docs_host.shape[0] == 0:
        return None
    return f.dv_docs_host, f.dv_ords_host, f.ord_terms


def _field_type(mapper: MapperService, field: str):
    return mapper.field_type(field)


def _is_date(mapper, field) -> bool:
    return isinstance(_field_type(mapper, field), DateFieldType)


def _is_int(mapper, field) -> bool:
    ft = _field_type(mapper, field)
    return isinstance(ft, NumberFieldType) and ft.type_name in INT_TYPES


def _format_key(mapper, field, v: float):
    if _is_date(mapper, field):
        return v, format_date_millis(v)
    if _is_int(mapper, field):
        return int(v), None
    return v, None


# ---------------------------------------------------------------------------
# framework
# ---------------------------------------------------------------------------


class Aggregator:
    """One node of the aggregation tree. ``collect`` runs per segment with
    the query's host-side doc mask; ``reduce`` merges partials from all
    segments of a shard — and, unchanged, partials from all shards."""

    name: str

    def collect(self, ctx, seg: Segment, mask: np.ndarray) -> Any:
        raise NotImplementedError

    def reduce(self, partials: List[Any]) -> dict:
        raise NotImplementedError


class AggregationContext:
    """Carries the mapper, the shard query context (for filter sub-queries)
    and per-segment scores (for top_hits) through the tree."""

    def __init__(self, mapper: MapperService, shard_ctx=None,
                 seg_scores: Optional[Dict[str, np.ndarray]] = None,
                 wire: bool = False):
        self.mapper = mapper
        self.shard_ctx = shard_ctx
        self.seg_scores = seg_scores or {}
        #: partials will cross the transport: aggregators that stage live
        #: segment refs must use their data-only collect_wire form
        self.wire = wire


def parse_aggs(spec: dict) -> Dict[str, Aggregator]:
    if not isinstance(spec, dict):
        raise ParsingError("aggregations must be an object")
    out: Dict[str, Aggregator] = {}
    for name, body in spec.items():
        if not isinstance(body, dict):
            raise ParsingError(f"aggregation [{name}] must be an object")
        sub_spec = body.get("aggs") or body.get("aggregations") or {}
        kinds = [k for k in body if k not in ("aggs", "aggregations", "meta")]
        if len(kinds) != 1:
            raise ParsingError(
                f"aggregation [{name}] must define exactly one type, "
                f"got {kinds}")
        kind = kinds[0]
        factory = _AGG_PARSERS.get(kind)
        if factory is None:
            raise ParsingError(f"unknown aggregation type [{kind}]")
        agg = factory(body[kind])
        agg.name = name
        agg.kind = kind
        agg._raw = body[kind] if isinstance(body[kind], dict) else {}
        agg.meta = body.get("meta")
        subs = parse_aggs(sub_spec) if sub_spec else {}
        if subs and not isinstance(agg, BucketAggregator):
            raise ParsingError(
                f"aggregation [{name}] of type [{kind}] cannot have "
                f"sub-aggregations")
        if isinstance(agg, BucketAggregator):
            # rate descendants resolve their per-unit factor from the
            # CLOSEST enclosing date_histogram (RateAggregator's parent
            # Rounding); subtrees build before parents, so walking all
            # descendants here stamps any not yet claimed by a nearer one
            if isinstance(agg, DateHistogramAgg):
                if agg.fixed_ms is not None:
                    interval_ms = agg.fixed_ms
                else:
                    from .aggs_analytics import _UNIT_MS
                    unit_names = {
                        "s": "second", "m": "minute", "h": "hour",
                        "d": "day", "w": "week", "M": "month",
                        "q": "quarter", "y": "year"}
                    interval_ms = _UNIT_MS[unit_names[agg.calendar_unit]]

                def _stamp(tree):
                    for sa in tree.values():
                        if getattr(sa, "_needs_parent_interval", False) \
                                and sa._parent_interval_ms is None:
                            sa._parent_interval_ms = interval_ms
                        if getattr(sa, "subs", None):
                            _stamp(sa.subs)
                _stamp(subs)
            # composite may only nest under SINGLE-bucket parents
            single_bucket = {"FilterAgg", "NestedAgg", "ReverseNestedAgg",
                             "GlobalAgg", "MissingAgg", "SamplerAgg"}
            for sn, sa in subs.items():
                if type(sa).__name__ == "CompositeAgg" and \
                        type(agg).__name__ not in single_bucket:
                    raise IllegalArgumentError(
                        f"[composite] aggregation cannot be used with a "
                        f"parent aggregation of type: "
                        f"[{type(agg).__name__}]")
            agg.subs = subs
        if isinstance(agg, PipelineAggregator) and subs:
            raise ParsingError(
                f"pipeline aggregation [{name}] cannot have sub-aggregations")
        out[name] = agg
    return out


def run_aggregations(aggs: Dict[str, Aggregator], ctx: AggregationContext,
                     seg_masks: List[Tuple[Segment, np.ndarray]]) -> dict:
    """Collect every segment then reduce — shard-level entry point.
    Pipeline aggs run last, over their sibling's reduced output."""
    return run_aggregations_multi(
        aggs, [(ctx, seg, mask) for seg, mask in seg_masks])


#: search.max_buckets cluster setting (mutable; REST layer updates it)
MAX_BUCKETS = [65536]


def _count_buckets(node) -> int:
    total = 0
    if isinstance(node, dict):
        b = node.get("buckets")
        if isinstance(b, list):
            total += len(b)
            for item in b:
                total += _count_buckets(item)
        elif isinstance(b, dict):
            total += len(b)
            for item in b.values():
                total += _count_buckets(item)
        else:
            for v in node.values():
                if isinstance(v, dict):
                    total += _count_buckets(v)
    return total


def _check_max_buckets(result: dict) -> None:
    limit = MAX_BUCKETS[0]
    n = sum(_count_buckets(v) for v in result.values()
            if isinstance(v, dict))
    if n > limit:
        raise IllegalArgumentError(
            f"Trying to create too many buckets. Must be less than or "
            f"equal to: [{limit}] but was [{n}]. This limit can be set "
            f"by changing the [search.max_buckets] cluster level "
            f"setting.")


def run_aggregations_multi(
        aggs: Dict[str, Aggregator],
        ctx_seg_masks: List[Tuple[AggregationContext, Segment, np.ndarray]],
        extra_partials: Optional[Dict[str, list]] = None,
) -> dict:
    """Cross-index entry point: each segment collects under its *own*
    index's context (mapper + term stats), then one shared reduce — the
    reference reduces per-shard trees the same way
    (``SearchPhaseController.java:211-219``). ``extra_partials`` carries
    already-collected partials from REMOTE shards (the cluster tier) into
    the same reduce."""
    from ..common.breakers import DEFAULT as _breakers
    from ..common.breakers import estimate_partial_bytes
    request_breaker = _breakers.breaker("request")
    result: Dict[str, dict] = {}
    pipelines: Dict[str, PipelineAggregator] = {}
    for name, agg in aggs.items():
        if isinstance(agg, PipelineAggregator):
            pipelines[name] = agg
            continue
        # collection-time accounting (the reference's BigArrays accounts
        # DURING bucket growth, ``AggregatorBase.addRequestCircuitBreaker-
        # Bytes``): reserve each segment's partial AS it is produced, so
        # a pathological high-cardinality agg trips BEFORE the next
        # segment's partial is even materialized — not after everything
        # is already resident
        partials = []
        reserved = 0
        try:
            for ctx, seg, mask in ctx_seg_masks:
                p = agg.collect(ctx, seg, mask)
                step = estimate_partial_bytes(p)
                request_breaker.add_estimate(step, f"<agg [{name}]>")
                reserved += step
                partials.append(p)
            for p in (extra_partials or {}).get(name, ()):
                step = estimate_partial_bytes(p)
                request_breaker.add_estimate(step, f"<agg [{name}]>")
                reserved += step
                partials.append(p)
            result[name] = agg.reduce(partials)
        finally:
            request_breaker.release(reserved)
        _apply_parent_pipes(agg, result[name])
        if getattr(agg, "meta", None) is not None:
            result[name]["meta"] = agg.meta
    for name, p in pipelines.items():
        result[name] = p.apply(result)
        if getattr(p, "meta", None) is not None:
            result[name]["meta"] = p.meta
    _check_max_buckets(result)
    return result


def inject_mapper(aggs: Dict[str, "Aggregator"], mapper) -> None:
    """Give every aggregator (recursively) the mapper its reduce-side
    rendering needs (key_as_string, date formats). Locally this happens
    as a side effect of ``collect`` (``self._mapper = ctx.mapper``); a
    coordinator reducing REMOTE partials never ran collect, so the
    cluster tier injects the mapper explicitly before the shared reduce
    (the reference ships formatters inside serialized
    ``InternalAggregation`` trees instead)."""
    for agg in aggs.values():
        agg._mapper = mapper
        subs = getattr(agg, "subs", None)
        if subs:
            inject_mapper(subs, mapper)


def _collect_fn(agg, ctx):
    """collect, or collect_wire when the partial will cross the wire."""
    if getattr(ctx, "wire", False):
        return getattr(agg, "collect_wire", agg.collect)
    return agg.collect


def _sub_results(agg: "BucketAggregator", ctx, seg, bucket_mask) -> dict:
    return {n: _collect_fn(a, ctx)(ctx, seg, bucket_mask)
            for n, a in agg.subs.items()}


def _reduce_subs(agg: "BucketAggregator", partial_lists: List[dict]) -> dict:
    out = {}
    pipelines = {}
    for n, a in agg.subs.items():
        if isinstance(a, PipelineAggregator):
            if not a.parent_pipeline:
                pipelines[n] = a
            continue
        out[n] = a.reduce([x for x in (p.get(n) for p in partial_lists)
                           if x is not None])
        _apply_parent_pipes(a, out[n])
    for n, p in pipelines.items():
        out[n] = p.apply(out)
    return out


class BucketAggregator(Aggregator):
    subs: Dict[str, Aggregator] = {}


class PipelineAggregator(Aggregator):
    """Computed from sibling reduced output, no per-doc collection
    (reference: ``search/aggregations/pipeline/``)."""

    #: parent pipelines (derivative, cumulative_sum, moving_fn, …) run
    #: over their PARENT bucket agg's reduced bucket list, not a sibling
    parent_pipeline = False

    def collect(self, ctx, seg, mask):
        return None

    def reduce(self, partials):
        return {}

    def apply(self, sibling_results: dict) -> dict:
        raise NotImplementedError

    def apply_parent(self, name: str, parent_node: dict) -> None:
        raise NotImplementedError


def _bucket_series(blist: List[dict], path: str) -> List[Any]:
    """Per-bucket metric series for parent pipelines (BucketHelpers with
    gap policy skip on empty buckets)."""
    parts = path.replace(">", ".").split(".")
    out = []
    for b in blist:
        if parts[0] == "_count":
            out.append(b.get("doc_count"))
            continue
        v: Any = b
        for p in parts:
            v = v.get(p) if isinstance(v, dict) else None
        if isinstance(v, dict):
            v = v.get("value")
        out.append(v)
    return out


def _apply_parent_pipes(agg: "Aggregator", node: dict) -> None:
    subs = getattr(agg, "subs", None)
    if not subs or not isinstance(node, dict):
        return
    if "buckets" not in node:
        return
    for pname, p in subs.items():
        if isinstance(p, PipelineAggregator) and p.parent_pipeline:
            p.apply_parent(pname, node)


# ---------------------------------------------------------------------------
# metric aggregations
# ---------------------------------------------------------------------------


class _NumericMetricAgg(Aggregator):
    def __init__(self, body: dict):
        self.field = body.get("field")
        self.missing = body.get("missing")
        if self.field is None:
            raise ParsingError("metric aggregation requires [field]")

    def _with_value_string(self, out: dict) -> dict:
        """Metric values over date fields also serialize formatted
        (value_as_string, like the reference's DocValueFormat)."""
        mapper = getattr(self, "_mapper", None)
        ft = _field_type(mapper, self.field) if mapper else None
        if isinstance(ft, DateFieldType) and out.get("value") is not None:
            out["value_as_string"] = format_date_millis(out["value"])
        return out

    def _matched_values(self, ctx, seg, mask: np.ndarray) -> np.ndarray:
        self._mapper = ctx.mapper
        from ..index.mapping import KeywordFieldType, TextFieldType
        ft = ctx.mapper.field_type(self.field) if ctx.mapper else None
        if isinstance(ft, (TextFieldType, KeywordFieldType)):
            raise IllegalArgumentError(
                f"Field [{self.field}] of type "
                f"[{getattr(ft, 'type_name', 'text')}] is not supported "
                f"for aggregation [{getattr(self, 'name', '?')}]")
        pairs = _numeric_pairs(seg, self.field, ctx.mapper)
        vals_list = []
        if pairs is not None:
            docs, vals = pairs
            pm = mask[docs]
            vals_list.append(vals[pm])
        if self.missing is not None:
            # docs matched by the query but without the field
            has = np.zeros(mask.shape[0], bool)
            if pairs is not None:
                has[pairs[0]] = True
            n_missing = int((mask & ~has).sum())
            if n_missing:
                vals_list.append(np.full(n_missing, float(self.missing)))
        if not vals_list:
            return np.empty(0, np.float64)
        return np.concatenate(vals_list)


class AvgAgg(_NumericMetricAgg):
    def collect(self, ctx, seg, mask):
        v = self._matched_values(ctx, seg, mask)
        return {"sum": float(v.sum()), "count": int(v.size)}

    def reduce(self, partials):
        s = sum(p["sum"] for p in partials)
        c = sum(p["count"] for p in partials)
        return self._with_value_string({"value": (s / c) if c else None})


class SumAgg(_NumericMetricAgg):
    def collect(self, ctx, seg, mask):
        v = self._matched_values(ctx, seg, mask)
        return {"sum": float(v.sum())}

    def reduce(self, partials):
        return {"value": sum(p["sum"] for p in partials)}


class MinAgg(_NumericMetricAgg):
    def collect(self, ctx, seg, mask):
        v = self._matched_values(ctx, seg, mask)
        return {"min": float(v.min()) if v.size else None}

    def reduce(self, partials):
        vals = [p["min"] for p in partials if p["min"] is not None]
        return self._with_value_string(
            {"value": min(vals) if vals else None})


class MaxAgg(_NumericMetricAgg):
    def collect(self, ctx, seg, mask):
        v = self._matched_values(ctx, seg, mask)
        return {"max": float(v.max()) if v.size else None}

    def reduce(self, partials):
        vals = [p["max"] for p in partials if p["max"] is not None]
        return self._with_value_string(
            {"value": max(vals) if vals else None})


class ValueCountAgg(_NumericMetricAgg):
    def __init__(self, body):
        self.field = body.get("field")
        self.missing = body.get("missing")
        if self.field is None:
            raise ParsingError("metric aggregation requires [field]")

    def collect(self, ctx, seg, mask):
        # counts values of any doc-values type
        kw = _keyword_pairs(seg, self.field, ctx.mapper)
        if kw is not None:
            docs, _, _ = kw[0], kw[1], kw[2]
            return {"count": int(mask[kw[0]].sum())}
        v = self._matched_values(ctx, seg, mask)
        return {"count": int(v.size)}

    def reduce(self, partials):
        return {"value": sum(p["count"] for p in partials)}


class StatsAgg(_NumericMetricAgg):
    def collect(self, ctx, seg, mask):
        v = self._matched_values(ctx, seg, mask)
        return {"count": int(v.size), "sum": float(v.sum()),
                "min": float(v.min()) if v.size else None,
                "max": float(v.max()) if v.size else None}

    def reduce(self, partials):
        count = sum(p["count"] for p in partials)
        s = sum(p["sum"] for p in partials)
        mins = [p["min"] for p in partials if p["min"] is not None]
        maxs = [p["max"] for p in partials if p["max"] is not None]
        return {"count": count, "sum": s,
                "min": min(mins) if mins else None,
                "max": max(maxs) if maxs else None,
                "avg": (s / count) if count else None}


class ExtendedStatsAgg(_NumericMetricAgg):
    def __init__(self, body):
        super().__init__(body)
        try:
            self.sigma = float(body.get("sigma", 2.0))
        except (TypeError, ValueError):
            from ..common.errors import XContentParseError
            raise XContentParseError(
                f"[extended_stats] failed to parse field [sigma]: "
                f"[{body.get('sigma')}] is not a number")
        if self.sigma < 0:
            self._sigma_error = True

    def collect(self, ctx, seg, mask):
        if getattr(self, "_sigma_error", False):
            raise IllegalArgumentError(
                f"[sigma] must be greater than or equal to 0. "
                f"Found [{self.sigma}] in [{self.name}]")
        return self._collect_inner(ctx, seg, mask)

    def _collect_inner(self, ctx, seg, mask):
        v = self._matched_values(ctx, seg, mask)
        return {"count": int(v.size), "sum": float(v.sum()),
                "sum_sq": float((v * v).sum()),
                "min": float(v.min()) if v.size else None,
                "max": float(v.max()) if v.size else None}

    def reduce(self, partials):
        count = sum(p["count"] for p in partials)
        s = sum(p["sum"] for p in partials)
        ss = sum(p["sum_sq"] for p in partials)
        mins = [p["min"] for p in partials if p["min"] is not None]
        maxs = [p["max"] for p in partials if p["max"] is not None]
        out = {"count": count, "sum": s,
               "min": min(mins) if mins else None,
               "max": max(maxs) if maxs else None,
               "avg": (s / count) if count else None,
               "sum_of_squares": ss if count else None}
        if count:
            var = max(ss / count - (s / count) ** 2, 0.0)
            std = math.sqrt(var)
            out["variance"] = var
            out["std_deviation"] = std
            out["std_deviation_bounds"] = {
                "upper": s / count + self.sigma * std,
                "lower": s / count - self.sigma * std,
            }
        else:
            out["variance"] = out["std_deviation"] = None
            out["std_deviation_bounds"] = {"upper": None, "lower": None}
        return out


class CardinalityAgg(Aggregator):
    """Distinct-value count. Exact per-shard via value sets below
    ``precision_threshold``; above it the segment collects an HLL++
    register sketch instead (reference:
    ``metrics/CardinalityAggregator.java`` /
    ``HyperLogLogPlusPlus.java``). The regime trigger is the SEGMENT's
    cached distinct-value count (``ops/aggs.distinct_count``) — a
    route-independent property, so the fused planner stages and the
    legacy two-pass path always pick the same representation and
    return identical values. Sketch merge is one elementwise register
    ``maximum`` (ICI-friendly like the top-k payload reduce); mixed
    set/sketch partials fold the raw values into the registers with the
    same scalar hash."""

    PRECISION_DEFAULT = 3000

    def __init__(self, body):
        pt = body.get("precision_threshold")
        if pt is not None and int(pt) < 0:
            self._pt_error = int(pt)
        self.missing = body.get("missing")
        self.field = body.get("field")
        if self.field is None:
            raise ParsingError("cardinality requires [field]")
        self.precision_threshold = int(
            body.get("precision_threshold", self.PRECISION_DEFAULT))

    def _use_hll(self, ctx, seg) -> bool:
        if self.missing is not None or self.precision_threshold <= 0:
            return False
        field = _concrete(ctx.mapper, self.field)
        if field not in getattr(seg, "keyword_fields", {}) and \
                field not in getattr(seg, "numeric_fields", {}):
            return False             # runtime/absent fields: exact sets
        return ops_aggs.distinct_count(seg, field) >= \
            self.precision_threshold

    def collect(self, ctx, seg, mask):
        if getattr(self, "_pt_error", None) is not None:
            raise IllegalArgumentError(
                f"[precisionThreshold] must be greater than or equal to "
                f"0. Found [{self._pt_error}] in [{self.name}]")
        if self._use_hll(ctx, seg):
            field = _concrete(ctx.mapper, self.field)
            pairs = ops_aggs.hll_sketch_pairs(seg, field)
            if pairs["n_pairs"] >= ops_aggs.DEVICE_MIN_PAIRS:
                # device register-max kernel over the cached sorted
                # pairs; host twin below is bitwise-identical (integer
                # max is order-independent)
                from ..common.telemetry import record_agg_pairs
                record_agg_pairs(pairs["n_pairs"])
                regs = np.asarray(ops_aggs.masked_register_max(
                    pairs["off_dev"], pairs["docs_dev"],
                    pairs["rhos_dev"],
                    _device_mask(seg, mask)))[: pairs["m"]]
            else:
                regs = ops_aggs.host_register_max(pairs, mask)
            return {"hll": regs, "p": ops_aggs.HLL_P}
        kw = _keyword_pairs(seg, self.field, ctx.mapper)
        num = _numeric_pairs(seg, self.field, ctx.mapper) \
            if kw is None else None
        out: set = set()
        has = np.zeros(mask.shape[0], bool)
        if kw is not None:
            docs, ords, terms = kw
            out = {terms[o] for o in np.unique(ords[mask[docs]])}
            has[docs] = True
        elif num is not None:
            docs, vals = num
            out = set(np.unique(vals[mask[docs]]).tolist())
            has[docs] = True
        if self.missing is not None and (mask & ~has).any():
            out.add(self.missing)
        return {"values": out}

    def reduce(self, partials):
        from ..common.telemetry import record_agg_sketch_merge
        regs = None
        sets: List[set] = []
        for p in partials:
            if "hll" in p:
                record_agg_sketch_merge("hll")
                regs = p["hll"].copy() if regs is None \
                    else ops_aggs.hll_merge(regs, p["hll"])
            else:
                record_agg_sketch_merge("exact")
                sets.append(p["values"])
        if regs is None:
            u: set = set()
            for s in sets:
                u |= s
            return {"value": len(u)}
        for s in sets:
            regs = ops_aggs.hll_add_values(regs, s, ops_aggs.HLL_P)
        return {"value": ops_aggs.hll_estimate(regs)}


def _hdr_quantize(chosen: np.ndarray, allv: np.ndarray,
                  digits: int) -> np.ndarray:
    """HdrHistogram DoubleHistogram value quantization. The double→long
    conversion ratio auto-ranges so the smallest nonzero magnitude lands
    in [subBucketHalfCount, subBucketCount); a stored long's reported
    value is the highest long mapping to the same bucket slot
    (``highestEquivalentValue``), scaled back to double space."""
    import math
    sub_bucket_count = 1 << math.ceil(math.log2(2 * 10 ** digits))
    half_bl = (sub_bucket_count // 2).bit_length()
    pos = allv[allv > 0]
    if pos.size == 0:
        return chosen
    vmin = float(pos.min())
    k = (half_bl - 1) - math.floor(math.log2(vmin))
    ratio = 2.0 ** k
    out = []
    for x in chosen.tolist():
        if x <= 0:
            out.append(x)
            continue
        sv = int(x * ratio)
        unit = 1 << max(0, sv.bit_length() - half_bl)
        out.append(((sv // unit) * unit + unit - 1) / ratio)
    return np.asarray(out)


class HdrNegativeValueError(ElasticsearchError):
    """HDR histograms cannot record negatives — the reference throws
    ArrayIndexOutOfBoundsException from DoubleHistogram, failing THAT
    SHARD (its conformance suite asserts exactly this failure type)."""

    status = 500
    error_type = "array_index_out_of_bounds_exception"


class PercentilesAgg(_NumericMetricAgg):
    """Exact percentiles via full value collection (the reference
    approximates with TDigest — ``metrics/TDigestState``; exact is
    stricter and deterministic, sketch planned for giant shards)."""

    DEFAULT_PERCENTS = (1.0, 5.0, 25.0, 50.0, 75.0, 95.0, 99.0)

    def __init__(self, body):
        super().__init__(body)
        percents = body.get("percents", self.DEFAULT_PERCENTS)
        if not isinstance(percents, (list, tuple)) or not percents or \
                any(not isinstance(x, (int, float)) or x < 0 or x > 100
                    for x in percents):
            raise IllegalArgumentError(
                f"[percents] must not be empty and all values must be "
                f"between 0 and 100, got {percents}")
        self.percents = tuple(percents)
        self.keyed = bool(body.get("keyed", True))
        td = body.get("tdigest") or {}
        compression = td.get("compression")
        if compression is not None and float(compression) < 0:
            raise IllegalArgumentError(
                f"[compression] must be greater than or equal to 0. "
                f"Found [{float(compression)}]")
        hdr = body.get("hdr")
        self.hdr = hdr is not None
        self.hdr_digits = 3
        if hdr:
            digits = hdr.get("number_of_significant_value_digits", 3)
            if digits is None or not (0 <= int(digits) <= 5):
                raise IllegalArgumentError(
                    "[numberOfSignificantValueDigits] must be between 0 "
                    "and 5")
            self.hdr_digits = int(digits)

    def collect(self, ctx, seg, mask):
        vals = self._matched_values(ctx, seg, mask)
        if self.hdr and vals.size and float(np.min(vals)) < 0:
            raise HdrNegativeValueError(
                "Histogram recorded value cannot be negative.")
        return {"values": vals}

    def _quantiles(self, allv: np.ndarray):
        if self.hdr:
            # HDR semantics: the recorded value at ceil(q·n) rank, then
            # quantized to the top of its histogram bucket
            # (``DoubleHistogram.getValueAtPercentile`` returns
            # highestEquivalentValue — conformance asserts the exact
            # quantized doubles, e.g. 51 → 51.0302734375)
            v = np.sort(allv)
            # countAtPercentile = max(round(p/100·n), 1) — the +0.5
            # floor rounding in Histogram.getValueAtPercentile
            idx = np.maximum(
                (np.asarray(self.percents) / 100.0 * v.size + 0.5)
                .astype(int), 1) - 1
            chosen = v[np.minimum(idx, v.size - 1)]
            return _hdr_quantize(chosen, allv, self.hdr_digits)
        # Hazen interpolation (q·n − ½): what the reference's TDigest
        # converges to on exactly-held data — its tiny-shard unit
        # expectations (values.1\.0 == min, midpoints between points)
        # only hold under this rule, not numpy's default linear one
        return np.percentile(allv, self.percents, method="hazen")

    def reduce(self, partials):
        allv = np.concatenate([p["values"] for p in partials]) \
            if partials else np.empty(0)
        if allv.size == 0:
            vals = {f"{p}": None for p in self.percents}
        else:
            qs = self._quantiles(allv)
            vals = {f"{p}": float(q) for p, q in zip(self.percents, qs)}
        if self.keyed:
            return {"values": vals}
        return {"values": [{"key": float(p), "value": v}
                           for p, v in vals.items()]}


class PercentileRanksAgg(_NumericMetricAgg):
    def __init__(self, body):
        super().__init__(body)
        self.values = tuple(body.get("values", ()))
        if not self.values:
            raise ParsingError("percentile_ranks requires [values]")
        self.keyed = bool(body.get("keyed", True))

    def collect(self, ctx, seg, mask):
        return {"values": self._matched_values(ctx, seg, mask)}

    def reduce(self, partials):
        allv = np.concatenate([p["values"] for p in partials]) \
            if partials else np.empty(0)
        out = {}
        for v in self.values:
            if allv.size == 0:
                out[f"{float(v)}"] = None
            else:
                out[f"{float(v)}"] = float(
                    (allv <= v).sum() / allv.size * 100.0)
        if self.keyed:
            return {"values": out}
        return {"values": [{"key": float(k), "value": val}
                           for k, val in out.items()]}


class WeightedAvgAgg(Aggregator):
    def __init__(self, body):
        try:
            self.value_field = body["value"]["field"]
            self.weight_field = body["weight"]["field"]
        except (KeyError, TypeError):
            raise ParsingError(
                "weighted_avg requires [value.field] and [weight.field]")

    def collect(self, ctx, seg, mask):
        vp = _numeric_pairs(seg, self.value_field)
        wp = _numeric_pairs(seg, self.weight_field)
        if vp is None or wp is None:
            return {"num": 0.0, "den": 0.0}
        # single-valued join on doc id
        vdocs, vvals = vp
        wdocs, wvals = wp
        wmap = np.zeros(mask.shape[0])
        wmap[wdocs] = wvals
        has_w = np.zeros(mask.shape[0], bool)
        has_w[wdocs] = True
        pm = mask[vdocs] & has_w[vdocs]
        w = wmap[vdocs][pm]
        v = vvals[pm]
        return {"num": float((v * w).sum()), "den": float(w.sum())}

    def reduce(self, partials):
        num = sum(p["num"] for p in partials)
        den = sum(p["den"] for p in partials)
        return {"value": (num / den) if den else None}


class MedianAbsoluteDeviationAgg(_NumericMetricAgg):
    def __init__(self, body):
        super().__init__(body)
        comp = body.get("compression")
        if comp is not None and float(comp) <= 0:
            self._comp_error = float(comp)

    def collect(self, ctx, seg, mask):
        if getattr(self, "_comp_error", None) is not None:
            raise IllegalArgumentError(
                f"[compression] must be greater than 0. "
                f"Found [{self._comp_error}] in [{self.name}]")
        return {"values": self._matched_values(ctx, seg, mask)}

    def reduce(self, partials):
        allv = np.concatenate([p["values"] for p in partials]) \
            if partials else np.empty(0)
        if allv.size == 0:
            return {"value": None}
        med = np.median(allv)
        return {"value": float(np.median(np.abs(allv - med)))}


class TopHitsAgg(Aggregator):
    """Per-bucket top hits (reference: ``metrics/TopHitsAggregator.java``).
    Scores travel in the context; ``sort`` overrides them. Inside a
    ``nested`` agg the mask selects CHILD rows, which render as root hits
    with ``_nested`` coordinates; a sort on a nested field from root
    space rolls child doc values up to the parent (mode min)."""

    def __init__(self, body):
        self.size = int(body.get("size", 3))
        self.from_ = int(body.get("from", 0))
        self.source = body.get("_source", True)
        self.seq_no_primary_term = bool(body.get("seq_no_primary_term",
                                                 False))
        self._sorts = []                 # (field, desc?, nested_path)
        sort = body.get("sort")
        if isinstance(sort, (str, dict)):
            sort = [sort]
        for item in sort or []:
            if isinstance(item, str):
                self._sorts.append((item, item == "_score", None))
            elif isinstance(item, dict):
                for f, spec in item.items():
                    if isinstance(spec, str):
                        self._sorts.append((f, spec == "desc", None))
                    else:
                        spec = spec or {}
                        self._sorts.append(
                            (f, spec.get("order") == "desc",
                             (spec.get("nested") or {}).get("path")))

    def _sort_vals(self, ctx, seg, field, desc):
        """row → value for one sort field, using the ES default sort
        mode (min for asc, max for desc); values on child rows also
        roll up to their parent for root-space sorting."""

        def better(a, b):
            return a > b if desc else a < b

        kw = _keyword_pairs(seg, field)
        direct: Dict[int, Any] = {}
        if kw is not None:
            pdocs, ords, terms = kw
            for d, o in zip(pdocs.tolist(), ords.tolist()):
                v = terms[o]
                if d not in direct or better(v, direct[d]):
                    direct[d] = v
        else:
            num = _numeric_pairs(seg, field, ctx.mapper)
            if num is not None:
                pdocs, nvals = num
                for d, v in zip(pdocs.tolist(), nvals.tolist()):
                    if d not in direct or better(v, direct[d]):
                        direct[d] = v
        rolled: Dict[int, Any] = {}
        for d, v in direct.items():
            r = int(seg.parent_of[d])
            if r != d and (r not in rolled or better(v, rolled[r])):
                rolled[r] = v
        return direct, rolled

    def _nested_coords(self, seg, d):
        """(path, offset, root) for a child row, or None for a root."""
        root = int(seg.parent_of[d])
        if root == d:
            return None
        for path, pm in seg.nested_paths.items():
            if pm[d]:
                siblings = np.flatnonzero(
                    pm & (seg.parent_of[: seg.n_docs] == root))
                return path, int(np.searchsorted(siblings, d)), root
        return None

    def collect(self, ctx, seg, mask):
        scores = getattr(ctx, "seg_scores", {}).get(seg.seg_id)
        docs = np.flatnonzero(mask[: seg.n_docs])
        if docs.size == 0:
            return {"hits": [], "total": 0}
        if scores is not None:
            sc = scores[docs]
        else:
            sc = np.ones(docs.size, np.float32)
        rows = list(range(docs.size))
        sort_keys: Dict[Tuple[int, int], Any] = {}
        if self._sorts:
            for li, (field, desc, _np_) in enumerate(self._sorts):
                if field == "_score":
                    for i in rows:
                        sort_keys[(li, i)] = float(sc[i])
                    continue
                direct, rolled = self._sort_vals(ctx, seg, field, desc)
                for i in rows:
                    d = int(docs[i])
                    sort_keys[(li, i)] = direct.get(d, rolled.get(d))
            # stable multi-key: sort by each level from last to first,
            # missing values always last regardless of direction
            for li in range(len(self._sorts) - 1, -1, -1):
                field, desc, _np_ = self._sorts[li]
                present = [i for i in rows
                           if sort_keys[(li, i)] is not None]
                absent = [i for i in rows if sort_keys[(li, i)] is None]
                present.sort(key=lambda i: sort_keys[(li, i)],
                             reverse=bool(desc))
                rows = present + absent
        else:
            rows = np.lexsort((docs, -sc)).tolist()
        keep = rows[: self.from_ + self.size]
        hits = []
        index_name = getattr(ctx.mapper, "index_name", None)
        for i in keep:
            d = int(docs[i])
            nc = self._nested_coords(seg, d)
            root = nc[2] if nc else d
            src = seg.sources[root]
            if nc and isinstance(src, dict):
                try:
                    obj = src
                    for part in nc[0].split("."):
                        obj = obj[part]
                    src = obj[nc[1]] if isinstance(obj, list) else obj
                except (KeyError, IndexError, TypeError):
                    src = None
            score_sorted = not self._sorts or \
                any(f == "_score" for f, _, _ in self._sorts)
            h = {"_index": index_name, "_id": seg.doc_uids[root],
                 "_score": float(sc[i]) if score_sorted else None,
                 "_source": src if self.source else None}
            if nc:
                h["_nested"] = {"field": nc[0], "offset": nc[1]}
            if self.seq_no_primary_term:
                h["_seq_no"] = int(seg.seq_nos[root])
                h["_primary_term"] = 1
            if self._sorts:
                h["sort"] = [sort_keys[(li, i)]
                             for li in range(len(self._sorts))]
            hits.append(h)
        return {"hits": hits, "total": int(docs.size)}

    def reduce(self, partials):
        total = sum(p["total"] for p in partials)
        allh = [h for p in partials for h in p["hits"]]
        if self._sorts:
            # cross-segment merge with per-level direction: flip the
            # comparison per level via the stable multi-pass again
            for li in range(len(self._sorts) - 1, -1, -1):
                desc = bool(self._sorts[li][1])
                present = [h for h in allh if h["sort"][li] is not None]
                absent = [h for h in allh if h["sort"][li] is None]
                present.sort(key=lambda h: h["sort"][li],
                             reverse=desc)
                allh = present + absent
            max_score = None
        else:
            allh.sort(key=lambda h: (-h["_score"], h["_id"]))
            max_score = allh[0]["_score"] if allh else None
        window = allh[self.from_: self.from_ + self.size]
        return {"hits": {
            "total": {"value": total, "relation": "eq"},
            "max_score": max_score,
            "hits": window}}


# ---------------------------------------------------------------------------
# bucket aggregations
# ---------------------------------------------------------------------------


def _mask_count(seg, bucket_docs_mask) -> int:
    """Doc count of a bucket mask, honoring _doc_count weights."""
    w = _doc_weights(seg)
    if w is None:
        return int(bucket_docs_mask.sum())
    return int(w[bucket_docs_mask[: seg.n_docs]].sum())


def _bucket_payload(agg: BucketAggregator, ctx, seg, bucket_docs_mask):
    """(count, sub_partials) for one bucket in one segment."""
    return (_mask_count(seg, bucket_docs_mask),
            _sub_results(agg, ctx, seg, bucket_docs_mask))


class TermsAgg(BucketAggregator):
    """Bucket per distinct value (reference:
    ``bucket/terms/GlobalOrdinalsStringTermsAggregator.java``). Ordinal
    counting is a segment_sum over the doc-values pair column."""

    def __init__(self, body):
        self.field = body.get("field")
        if self.field is None:
            raise ParsingError(
                "Required one of fields [field, script], but none were "
                "specified. ")
        self.size = int(body.get("size", 10))
        self.shard_size = int(body.get("shard_size",
                                       self.size * 3 // 2 + 10))
        self.min_doc_count = int(body.get("min_doc_count", 1))
        self.order = body.get("order", {"_count": "desc"})
        self.missing = body.get("missing")
        self.value_type = body.get("value_type")
        self.include = body.get("include")
        self.exclude = body.get("exclude")

    #: IncludeExclude.HASH_PARTITIONING_SEED
    _PARTITION_SEED = 31

    def _check_regex_support(self, mapper) -> None:
        ft = _field_type(mapper, self.field) if mapper else None
        tn = getattr(ft, "type_name", None)
        for v in (self.include, self.exclude):
            if isinstance(v, str) and tn not in ("keyword", "text", None):
                raise IllegalArgumentError(
                    f"Aggregation [{self.name}] cannot support regular "
                    f"expression style include/exclude settings as they "
                    f"can only be applied to string fields. Use an array "
                    f"of values for include/exclude clauses")

    def _coerce_key(self, mapper, v):
        """An include/exclude/missing value in request space → key space
        (dates parse to epoch millis, booleans to 1/0)."""
        ft = _field_type(mapper, self.field) if mapper else None
        try:
            if isinstance(ft, DateFieldType) or self.value_type == "date":
                return float(parse_date_millis(v))
            if isinstance(ft, BooleanFieldType) or                     self.value_type == "boolean":
                if isinstance(v, bool):
                    return 1.0 if v else 0.0
                return 1.0 if str(v) == "true" else 0.0
            if isinstance(ft, NumberFieldType) or self.value_type in (
                    "long", "double"):
                return float(v)
        except Exception:   # noqa: BLE001 — keep raw on parse failure
            pass
        return v

    def _key_included(self, key) -> bool:
        mapper = getattr(self, "_mapper", None)
        inc, exc = self.include, self.exclude
        if isinstance(inc, dict):            # partition form
            from ..utils.murmur3 import murmur3_32
            n = int(inc.get("num_partitions", 1))
            p = int(inc.get("partition", 0))
            if isinstance(key, (int, float)) and not isinstance(key, bool):
                # LongFilter: floorMod of the SIGNED mixed hash
                h = _mix64(int(key))
                if h >= 1 << 63:
                    h -= 1 << 64
                if h % n != p:               # python % IS floorMod
                    return False
            else:
                h = murmur3_32(str(key).encode(), self._PARTITION_SEED)
                if h >= 1 << 31:
                    h -= 1 << 32
                if h % n != p:
                    return False
        elif isinstance(inc, list):
            if getattr(self, "_inc_coerced", None) is None:
                self._inc_coerced = {self._coerce_key(mapper, v)
                                     for v in inc}
            if key not in self._inc_coerced:
                return False
        elif isinstance(inc, str):
            if re.fullmatch(inc, str(key)) is None:
                return False
        if isinstance(exc, list):
            if getattr(self, "_exc_coerced", None) is None:
                self._exc_coerced = {self._coerce_key(mapper, v)
                                     for v in exc}
            if key in self._exc_coerced:
                return False
        elif isinstance(exc, str):
            if re.fullmatch(exc, str(key)) is not None:
                return False
        return True

    def collect(self, ctx, seg, mask):
        """Per-segment partial: ``(buckets, trunc_err)``. Without sub-aggs,
        counts are exact for every distinct term (vectorized unique/counts —
        no cap needed). With sub-aggs, each term costs a full bucket mask, so
        collection is capped at shard_size ranked by segment-local count and
        ``trunc_err`` carries the last kept count — the upper bound on what a
        dropped term could have had (reference:
        ``InternalTerms.java`` docCountError accounting)."""
        buckets: Dict[Any, Tuple[int, dict]] = {}
        trunc_err = 0
        self._mapper = ctx.mapper        # for key_as_string at reduce
        self._check_regex_support(ctx.mapper)
        if self.field == "_index":
            # metadata field: every doc of the segment carries the
            # owning index's name as its single value
            name = getattr(ctx.mapper, "index_name", "") or ""
            cnt = _mask_count(seg, mask)
            if cnt or self.min_doc_count == 0:
                buckets[name] = (_bucket_payload(self, ctx, seg, mask)
                                 if self.subs else (cnt, {}))
            return buckets, 0
        if ctx.mapper is not None and getattr(self, "_raw", {}).get(
                "execution_hint") != "map":
            # global-ordinals execution loads fielddata (stats accounting)
            getattr(ctx.mapper, "fielddata_loaded", set()).add(
                _concrete(ctx.mapper, self.field))
        kw = _keyword_pairs(seg, self.field, ctx.mapper)
        if kw is not None and self.min_doc_count == 0:
            for t in kw[2]:
                buckets.setdefault(t, (0, {}))
        if kw is not None:
            docs, ords, terms = kw
            if docs.shape[0] >= ops_aggs.DEVICE_MIN_PAIRS and \
                    _doc_weights(seg) is None:
                # device hot path: ordinal-CSR cumsum-diff counts (exact
                # int32 — bitwise-identical to the numpy unique path)
                from ..common.telemetry import record_agg_pairs
                record_agg_pairs(docs.shape[0])
                off_dev, pdocs_dev, V = ops_aggs.ordinal_csr(seg, self.field)
                counts_all = np.asarray(ops_aggs.masked_ordinal_counts(
                    off_dev, pdocs_dev, _device_mask(seg, mask)))[:V]
                sel_ords = np.flatnonzero(counts_all)
                counts = counts_all[sel_ords]
                pm = None
            else:
                pm = mask[docs]
                w = _doc_weights(seg)
                if w is None:
                    sel_ords, counts = np.unique(ords[pm],
                                                 return_counts=True)
                else:
                    sel_ords, inv = np.unique(ords[pm],
                                              return_inverse=True)
                    counts = np.bincount(
                        inv, weights=w[docs[pm]]).astype(np.int64)
            if self.subs:
                if self.include is not None or self.exclude is not None:
                    # filter BEFORE the shard_size cap (the reference's
                    # IncludeExclude runs during collection)
                    keep = np.asarray([self._key_included(terms[int(o)])
                                       for o in sel_ords], bool)
                    sel_ords, counts = sel_ords[keep], counts[keep]
                order = np.argsort(-counts, kind="stable")
                if order.size > self.shard_size:
                    trunc_err = int(counts[order[self.shard_size - 1]])
                    order = order[: self.shard_size]
                if pm is None and order.size:
                    pm = mask[docs]
                for i in order:
                    o = int(sel_ords[i])
                    bucket_docs = np.zeros(mask.shape[0], bool)
                    bucket_docs[docs[pm & (ords == o)]] = True
                    buckets[terms[o]] = _bucket_payload(self, ctx, seg,
                                                        mask & bucket_docs)
            else:
                for i, c in zip(sel_ords.tolist(), counts.tolist()):
                    buckets[terms[i]] = (int(c), {})
        else:
            num = _numeric_pairs(seg, self.field, ctx.mapper)
            if num is not None:
                docs, vals = num
                pm = mask[docs]
                w = _doc_weights(seg)
                if w is None:
                    sel_vals, counts = np.unique(vals[pm],
                                                 return_counts=True)
                else:
                    sel_vals, inv = np.unique(vals[pm],
                                              return_inverse=True)
                    counts = np.bincount(
                        inv, weights=w[docs[pm]]).astype(np.int64)
                if self.subs:
                    if self.include is not None or \
                            self.exclude is not None:
                        keep = np.asarray(
                            [self._key_included(
                                int(v) if float(v).is_integer() else v)
                             for v in sel_vals], bool)
                        sel_vals, counts = sel_vals[keep], counts[keep]
                    order = np.argsort(-counts, kind="stable")
                    if order.size > self.shard_size:
                        trunc_err = int(counts[order[self.shard_size - 1]])
                        order = order[: self.shard_size]
                    for i in order:
                        v = sel_vals[i]
                        bucket_docs = np.zeros(mask.shape[0], bool)
                        bucket_docs[docs[pm & (vals == v)]] = True
                        buckets[v] = _bucket_payload(self, ctx, seg,
                                                     mask & bucket_docs)
                else:
                    for v, c in zip(sel_vals.tolist(), counts.tolist()):
                        buckets[v] = (c, {})
        if self.missing is not None:
            has = np.zeros(mask.shape[0], bool)
            if kw is not None:
                has[kw[0]] = True
            elif _numeric_pairs(seg, self.field) is not None:
                has[_numeric_pairs(seg, self.field)[0]] = True
            miss_mask = mask & ~has
            if miss_mask.any():
                missing_key = self._coerce_key(ctx.mapper, self.missing)
                buckets[missing_key] = _bucket_payload(
                    self, ctx, seg, miss_mask) if self.subs else \
                    (int(miss_mask.sum()), {})
        return buckets, trunc_err

    def _bucket_key_as_string(self, mapper, key):
        ft = _field_type(mapper, self.field) if mapper else None
        if isinstance(ft, BooleanFieldType) or \
                getattr(self, "value_type", None) == "boolean":
            return "true" if key else "false"
        if isinstance(ft, DateFieldType) or \
                getattr(self, "value_type", None) == "date":
            return format_date_millis(float(key))
        return None

    def _sort_key(self, ctx=None):
        ((field, direction),) = list(self.order.items())[:1] or \
            [("_count", "desc")]
        sign = -1 if direction == "desc" else 1
        return field, sign

    def reduce(self, partials):
        merged: Dict[Any, List] = {}
        err_bound = 0
        for p in partials:
            bkts, trunc_err = p
            err_bound += trunc_err
            for key, (count, subs) in bkts.items():
                merged.setdefault(key, []).append((count, subs))
        rows = []
        for key, items in merged.items():
            count = sum(c for c, _ in items)
            if count < self.min_doc_count:
                continue
            if not self._key_included(key):
                continue
            subs = _reduce_subs(self, [s for _, s in items]) \
                if self.subs else {}
            rows.append((key, count, subs))
        field, sign = self._sort_key()

        def keyfn(row):
            key, count, subs = row
            if field == "_count":
                return (sign * count, key)
            if field == "_key" or field == "_term":
                return (sign * key if isinstance(key, (int, float))
                        else key, ) if sign == 1 else (_Rev(key),)
            # sub-agg metric order, e.g. "price_avg" or "stats.avg"
            path = field.split(".")
            v = subs.get(path[0], {})
            v = v.get(path[1] if len(path) > 1 else "value")
            return (sign * (v if v is not None else float("-inf")), key)

        rows.sort(key=keyfn)
        total_other = sum(c for _, c, _ in rows)
        rows = rows[: self.size]
        total_other -= sum(c for _, c, _ in rows)
        out_buckets = []
        mapper = getattr(self, "_mapper", None)
        for key, count, subs in rows:
            b = {"key": key, "doc_count": count}
            if isinstance(key, float) and key.is_integer():
                b["key"] = int(key)
            kas = self._bucket_key_as_string(mapper, b["key"])
            if kas is not None:
                b["key_as_string"] = kas
            b.update(subs)
            out_buckets.append(b)
        return {"doc_count_error_upper_bound": err_bound,
                "sum_other_doc_count": total_other,
                "buckets": out_buckets}


class _Rev:
    """Inverts comparison for desc string sort keys."""

    def __init__(self, v):
        self.v = v

    def __lt__(self, other):
        return other.v < self.v

    def __eq__(self, other):
        return self.v == other.v


class HistogramAgg(BucketAggregator):
    def __init__(self, body):
        self.field = body.get("field")
        if self.field is None or "interval" not in body:
            raise ParsingError("histogram requires [field] and [interval]")
        self.interval = float(body["interval"])
        if self.interval <= 0:
            raise ParsingError("[interval] must be > 0")
        self.offset = float(body.get("offset", 0.0))
        self.min_doc_count = int(body.get("min_doc_count", 0))
        self.format = body.get("format")
        bounds = body.get("extended_bounds")
        self.extended_bounds = ((float(bounds["min"]), float(bounds["max"]))
                                if bounds else None)
        hb = body.get("hard_bounds")
        self.hard_bounds = ((float(hb["min"]), float(hb["max"]))
                            if hb else None)

    def _bucket_ids(self, vals):
        return np.floor((vals - self.offset) / self.interval)

    def _range_field_collect(self, ctx, seg, mask):
        """Histogram over a RANGE field: every doc interval contributes
        one count to each bucket it overlaps (RangeHistogramAggregator)."""
        g = seg.numeric_fields.get(f"{self.field}._gte")
        l = seg.numeric_fields.get(f"{self.field}._lte")
        if g is None or l is None:
            return {}
        out: Dict[float, list] = {}
        lo_clip = self.hard_bounds[0] if self.hard_bounds else None
        hi_clip = self.hard_bounds[1] if self.hard_bounds else None
        pm = mask[g.docs_host]
        for lo_v, hi_v, doc in zip(g.vals_host[pm], l.vals_host[pm],
                                   g.docs_host[pm]):
            if lo_clip is not None:
                lo_v = max(lo_v, lo_clip)
            if hi_clip is not None:
                hi_v = min(hi_v, hi_clip)
            if hi_v < lo_v:
                continue
            b0 = int(math.floor((lo_v - self.offset) / self.interval))
            b1 = int(math.floor((hi_v - self.offset) / self.interval))
            if b1 - b0 > 100000:
                raise IllegalArgumentError(
                    f"Trying to create too many buckets. Must be less "
                    f"than or equal to: [{MAX_BUCKETS[0]}]. This limit "
                    f"can be set by changing the [search.max_buckets] "
                    f"cluster level setting.")
            for bid in range(b0, b1 + 1):
                key = bid * self.interval + self.offset
                cur = out.setdefault(float(key), [0, {}])
                cur[0] += 1
        return {k: (c, s_) for k, (c, s_) in out.items()}

    def collect(self, ctx, seg, mask):
        ft = ctx.mapper.field_type(self.field) if ctx.mapper else None
        if isinstance(ft, RangeFieldType):
            return self._range_field_collect(ctx, seg, mask)
        num = _numeric_pairs(seg, self.field, ctx.mapper)
        if num is None:
            return {}
        docs, vals = num
        if self.hard_bounds:
            sel = (vals >= self.hard_bounds[0]) & \
                  (vals <= self.hard_bounds[1])
            docs, vals = docs[sel], vals[sel]
        if (docs.shape[0] >= ops_aggs.DEVICE_MIN_PAIRS and
                not self.subs and not self.hard_bounds):
            # device hot path: cached exact bucket ids + one-hot counts
            ids_dev, pdocs_dev, n_buckets, base = \
                ops_aggs.histogram_bucket_ids(seg, self.field, self.interval,
                                              self.offset)
            if ids_dev is not None and n_buckets:
                # static kernel shape rounds up through the shape
                # lattice (ESTP-J04): n_buckets is data-dependent (value
                # span / interval), and an unbucketed value compiles a
                # fresh one-hot kernel per distinct histogram width; the
                # padding buckets count nothing and are sliced off
                from ..common.telemetry import record_agg_pairs
                from ..utils.shapes import round_up_pow2
                record_agg_pairs(docs.shape[0])
                nb_pad = round_up_pow2(n_buckets, 8)
                counts = np.asarray(ops_aggs.masked_bucket_counts(
                    ids_dev, pdocs_dev, _device_mask(seg, mask),
                    n_buckets=nb_pad))[:n_buckets]
                out = {}
                for bid in np.flatnonzero(counts):
                    key = (base + bid) * self.interval + self.offset
                    out[float(key)] = (int(counts[bid]), {})
                return out
        pm = mask[docs]
        ids = self._bucket_ids(vals[pm])
        out = {}
        for bid in np.unique(ids):
            key = bid * self.interval + self.offset
            if self.subs:
                bucket_docs = np.zeros(mask.shape[0], bool)
                bucket_docs[docs[pm][ids == bid]] = True
                out[float(key)] = _bucket_payload(self, ctx, seg,
                                                  mask & bucket_docs)
            else:
                out[float(key)] = (int((ids == bid).sum()), {})
        return out

    def reduce(self, partials):
        merged: Dict[float, List] = {}
        for p in partials:
            for key, item in p.items():
                merged.setdefault(key, []).append(item)
        keys = sorted(merged)
        if self.extended_bounds and (keys or self.min_doc_count == 0):
            lo = math.floor((self.extended_bounds[0] - self.offset)
                            / self.interval) * self.interval + self.offset
            hi = self.extended_bounds[1]
            k = lo
            while k <= hi:
                merged.setdefault(float(k), [])
                k += self.interval
            keys = sorted(merged)
        # densify gaps when min_doc_count == 0
        if self.min_doc_count == 0 and keys:
            k = keys[0]
            while k <= keys[-1] + 1e-9:
                merged.setdefault(float(round(k, 9)), [])
                k += self.interval
            keys = sorted(merged)
        buckets = []
        for key in keys:
            items = merged[key]
            count = sum(c for c, _ in items)
            if count < self.min_doc_count:
                continue
            subs = _reduce_subs(self, [s for _, s in items]) \
                if self.subs else {}
            k_out = int(key) if float(key).is_integer() else key
            b = {"key": k_out, "doc_count": count}
            if self.format:
                from .fetch import decimal_format
                b["key_as_string"] = decimal_format(float(key), self.format)
            b.update(subs)
            buckets.append(b)
        return {"buckets": buckets}


_CALENDAR_INTERVALS = {
    "second": "s", "1s": "s", "minute": "m", "1m": "m", "hour": "h",
    "1h": "h", "day": "d", "1d": "d", "week": "w", "1w": "w",
    "month": "M", "1M": "M", "quarter": "q", "1q": "q", "year": "y",
    "1y": "y",
}

_FIXED_UNITS_MS = {"ms": 1.0, "s": 1000.0, "m": 60_000.0, "h": 3_600_000.0,
                   "d": 86_400_000.0}


def _parse_fixed_interval(s: str) -> float:
    import re as _re
    m = _re.fullmatch(r"(\d+)(ms|s|m|h|d)", s)
    if not m:
        raise ParsingError(f"invalid fixed_interval [{s}]")
    return float(m.group(1)) * _FIXED_UNITS_MS[m.group(2)]


def _calendar_floor(millis: np.ndarray, unit: str) -> np.ndarray:
    """Floor epoch-millis to calendar bucket starts (UTC)."""
    dt = millis.astype("int64").astype("datetime64[ms]")
    if unit == "s":
        out = dt.astype("datetime64[s]")
    elif unit == "m":
        out = dt.astype("datetime64[m]")
    elif unit == "h":
        out = dt.astype("datetime64[h]")
    elif unit == "d":
        out = dt.astype("datetime64[D]")
    elif unit == "w":
        # ISO weeks start Monday; epoch (1970-01-01) was a Thursday
        days = dt.astype("datetime64[D]").astype("int64")
        out = ((days - 4) // 7 * 7 + 4).astype("datetime64[D]")
    elif unit == "M":
        out = dt.astype("datetime64[M]")
    elif unit == "q":
        months = dt.astype("datetime64[M]").astype("int64")
        out = (months // 3 * 3).astype("datetime64[M]")
    elif unit == "y":
        out = dt.astype("datetime64[Y]")
    else:  # pragma: no cover
        raise ParsingError(f"unknown calendar unit [{unit}]")
    return out.astype("datetime64[ms]").astype("int64").astype(np.float64)


def _parse_offset_ms(s) -> float:
    if isinstance(s, (int, float)):
        return float(s)
    s = str(s)
    sign = -1.0 if s.startswith("-") else 1.0
    from ..common.settings import parse_time_millis
    return sign * parse_time_millis(s.lstrip("+-"))


def _tz_offset_ms(tz: str, at_ms: float) -> float:
    """UTC offset (ms) of a zone at an instant; fixed "+HH:MM" or IANA."""
    import datetime
    m = re.match(r"^([+-])(\d{2}):?(\d{2})$", tz)
    if m:
        sign = 1 if m.group(1) == "+" else -1
        return sign * (int(m.group(2)) * 3600 + int(m.group(3)) * 60) * 1000
    import zoneinfo
    z = zoneinfo.ZoneInfo(tz)
    dt = datetime.datetime.fromtimestamp(at_ms / 1000.0, tz=z)
    return dt.utcoffset().total_seconds() * 1000


class DateHistogramAgg(BucketAggregator):
    def __init__(self, body):
        self.field = body.get("field")
        if self.field is None:
            raise ParsingError("date_histogram requires [field]")
        cal = body.get("calendar_interval")
        fixed = body.get("fixed_interval") or body.get("interval")
        self.min_doc_count = int(body.get("min_doc_count", 0))
        self.offset_ms = _parse_offset_ms(body.get("offset", 0))
        self.format = body.get("format")
        self.time_zone = body.get("time_zone")
        self.keyed = bool(body.get("keyed", False))
        hb = body.get("hard_bounds")
        self.hard_bounds = ((parse_date_millis(hb["min"]),
                             parse_date_millis(hb["max"]))
                            if hb else None)
        if cal:
            unit = _CALENDAR_INTERVALS.get(cal)
            if unit is None:
                raise ParsingError(f"invalid calendar_interval [{cal}]")
            self.calendar_unit: Optional[str] = unit
            self.fixed_ms = None
        elif fixed:
            self.calendar_unit = None
            self.fixed_ms = _parse_fixed_interval(str(fixed)) \
                if isinstance(fixed, str) else float(fixed)
        else:
            raise ParsingError(
                "date_histogram requires calendar_interval or fixed_interval")

    def _keys_for(self, vals: np.ndarray) -> np.ndarray:
        if not self.time_zone:
            shift = self.offset_ms
            v = vals - shift
            if self.calendar_unit is not None:
                return _calendar_floor(v, self.calendar_unit) + shift
            return np.floor(v / self.fixed_ms) * self.fixed_ms + shift
        # per-value utc offsets (hour-cached — DST transitions move the
        # offset mid-stream); falls back to one offset on huge spans
        hours = vals // 3_600_000.0
        uniq = np.unique(hours)
        if uniq.size > 10000:
            off = np.full(vals.shape,
                          _tz_offset_ms(self.time_zone,
                                        float(vals[0]) if vals.size
                                        else 0.0))
        else:
            of_hour = {h: _tz_offset_ms(self.time_zone, h * 3_600_000.0)
                       for h in uniq.tolist()}
            off = np.asarray([of_hour[h] for h in hours.tolist()])
        shift = self.offset_ms - off
        v = vals - shift
        if self.calendar_unit is not None:
            return _calendar_floor(v, self.calendar_unit) + shift
        return np.floor(v / self.fixed_ms) * self.fixed_ms + shift

    def _next_key(self, key: float) -> float:
        """Start of the bucket after ``key`` (for empty-bucket filling).
        Variable-length calendar units advance by overshooting past the
        next boundary and re-flooring — immune to day-of-month overflow."""
        if self.calendar_unit is None:
            return key + self.fixed_ms
        u = self.calendar_unit
        fixed = {"s": 1000, "m": 60000, "h": 3600000,
                 "d": 86400000, "w": 7 * 86400000}.get(u)
        if fixed is not None:
            return key + fixed
        overshoot = {"M": 32, "q": 93, "y": 367}[u] * 86400000.0
        return float(self._keys_for(np.asarray([key + overshoot]))[0])

    def _key_as_string(self, key: float) -> str:
        from .fetch import java_date_format
        if self.format:
            return java_date_format(key, self.format)
        if self.time_zone:
            off = _tz_offset_ms(self.time_zone, key)
            local = key + off
            base = format_date_millis(local)[:-1]       # strip Z
            sign = "+" if off >= 0 else "-"
            off = abs(int(off)) // 60000
            return f"{base}{sign}{off // 60:02d}:{off % 60:02d}"
        return format_date_millis(key)

    def collect(self, ctx, seg, mask):
        ft = ctx.mapper.field_type(self.field) if ctx.mapper else None
        if isinstance(ft, RangeFieldType):
            g = seg.numeric_fields.get(f"{self.field}._gte")
            l = seg.numeric_fields.get(f"{self.field}._lte")
            if g is None or l is None:
                return {}
            out: Dict[float, tuple] = {}
            pm = mask[g.docs_host]
            for lo_v, hi_v in zip(g.vals_host[pm], l.vals_host[pm]):
                if self.hard_bounds:
                    lo_v = max(lo_v, self.hard_bounds[0])
                    hi_v = min(hi_v, self.hard_bounds[1])
                if hi_v < lo_v:
                    continue
                k = float(self._keys_for(np.asarray([lo_v]))[0])
                guard = 0
                while k <= hi_v and guard < 100000:
                    c, s_ = out.get(k, (0, {}))
                    out[k] = (c + 1, s_)
                    k = self._next_key(k)
                    guard += 1
            return out
        num = _numeric_pairs(seg, self.field, ctx.mapper)
        if num is None:
            return {}
        docs, vals = num
        if self.hard_bounds:
            sel = (vals >= self.hard_bounds[0]) & \
                  (vals <= self.hard_bounds[1])
            docs, vals = docs[sel], vals[sel]
        if (self.fixed_ms is not None and not self.time_zone and
                not self.subs and not self.hard_bounds and
                docs.shape[0] >= ops_aggs.DEVICE_MIN_PAIRS and
                _doc_weights(seg) is None):
            # fixed-interval, no-tz date_histogram IS a histogram over
            # epoch-millis: reuse the cached bucket-id plane. The key
            # reconstruction (base + bid) * fixed_ms + offset_ms runs
            # the same f64 floor/multiply as _keys_for, so bucket keys
            # are bitwise-identical to the host path
            ids_dev, pdocs_dev, n_buckets, base = \
                ops_aggs.histogram_bucket_ids(seg, self.field,
                                              self.fixed_ms,
                                              self.offset_ms)
            if ids_dev is not None and n_buckets:
                from ..common.telemetry import record_agg_pairs
                from ..utils.shapes import round_up_pow2
                record_agg_pairs(docs.shape[0])
                nb_pad = round_up_pow2(n_buckets, 8)
                counts = np.asarray(ops_aggs.masked_bucket_counts(
                    ids_dev, pdocs_dev, _device_mask(seg, mask),
                    n_buckets=nb_pad))[:n_buckets]
                out = {}
                for bid in np.flatnonzero(counts):
                    key = (base + bid) * self.fixed_ms + self.offset_ms
                    out[float(key)] = (int(counts[bid]), {})
                return out
        pm = mask[docs]
        keys = self._keys_for(vals[pm])
        w = _doc_weights(seg)
        out = {}
        for key in np.unique(keys):
            if self.subs:
                bucket_docs = np.zeros(mask.shape[0], bool)
                bucket_docs[docs[pm][keys == key]] = True
                out[float(key)] = _bucket_payload(self, ctx, seg,
                                                  mask & bucket_docs)
            elif w is None:
                out[float(key)] = (int((keys == key).sum()), {})
            else:
                out[float(key)] = (
                    int(w[docs[pm][keys == key]].sum()), {})
        return out

    def reduce(self, partials):
        merged: Dict[float, List] = {}
        for p in partials:
            for key, item in p.items():
                merged.setdefault(key, []).append(item)
        keys = sorted(merged)
        if keys and self.min_doc_count == 0:
            # fill the gaps: contiguous buckets from min to max key
            filled = []
            k = keys[0]
            while k <= keys[-1] + 0.5:
                filled.append(k)
                nk = self._next_key(k)
                if nk <= k:            # safety against zero progress
                    break
                k = nk
            keys = [k for k in filled if k <= keys[-1] + 0.5]
        buckets = []
        for key in keys:
            items = merged.get(key, [])
            count = sum(c for c, _ in items)
            if count < self.min_doc_count:
                continue
            subs = _reduce_subs(self, [s for _, s in items]) \
                if self.subs else {}
            b = {"key": int(key) if float(key).is_integer() else key,
                 "key_as_string": self._key_as_string(key),
                 "doc_count": count}
            b.update(subs)
            buckets.append(b)
        if self.keyed:
            return {"buckets": {b["key_as_string"]:
                                {k: v for k, v in b.items()}
                                for b in buckets}}
        return {"buckets": buckets}


def _dt_from_ms_agg(ms: float):
    import datetime
    return datetime.datetime.fromtimestamp(ms / 1000.0,
                                           tz=datetime.timezone.utc)


class RangeAgg(BucketAggregator):
    def __init__(self, body):
        self.field = body.get("field")
        self.ranges = body.get("ranges")
        if self.field is None or not self.ranges:
            raise ParsingError("range requires [field] and [ranges]")
        self.keyed = bool(body.get("keyed", False))
        self.missing = body.get("missing")

    def _resolve(self, ctx):
        """collect-time hook: date_range snapshots the field's format
        here (bound parsing and key rendering are format-dependent)."""

    # bound parsing/formatting hooks: date_range/ip_range override these
    # (aggs_extra.py)
    def _parse_bound(self, v, which: str) -> float:
        return float(v)

    def _format_bound(self, v: float):
        return float(v)

    def _bounds_salt(self):
        """Memoization salt: date_range parses bounds with the field's
        format, which differs per index in a cross-index search."""
        return None

    def _bounds(self, r):
        # bounds resolve ONCE per (request, format) and memoize:
        # date-math 'now' must not re-resolve between collect and reduce
        cache = getattr(self, "_bounds_cache", None)
        if cache is None:
            cache = self._bounds_cache = {}
        k = (id(r), self._bounds_salt())
        if k not in cache:
            frm = r.get("from")
            to = r.get("to")
            cache[k] = (
                self._parse_bound(frm, "from") if frm is not None else None,
                self._parse_bound(to, "to") if to is not None else None)
        return cache[k]

    def _range_key(self, r) -> str:
        if "key" in r:
            return r["key"]
        lo, hi = self._bounds(r)
        f = "*" if lo is None else f"{self._format_bound(lo)}"
        t = "*" if hi is None else f"{self._format_bound(hi)}"
        return f"{f}-{t}"

    def collect(self, ctx, seg, mask):
        self._resolve(ctx)
        num = _numeric_pairs(seg, self.field, ctx.mapper)
        miss_val = miss_docs = None
        if self.missing is not None:
            miss_val = self._parse_bound(self.missing, "from")
            has = np.zeros(mask.shape[0], bool)
            if num is not None:
                has[num[0]] = True
            miss_docs = mask & ~has
        out = {}
        for ri, r in enumerate(self.ranges):
            key = ri          # ordinal: display keys may be per-format
            lo, hi = self._bounds(r)
            if num is None and miss_docs is None:
                out[key] = (0, {n: a.collect(ctx, seg,
                                             np.zeros_like(mask))
                                for n, a in self.subs.items()} if self.subs
                            else {})
                continue
            bucket_docs = np.zeros(mask.shape[0], bool)
            if num is not None:
                docs, vals = num
                sel = np.ones(vals.shape[0], bool)
                if lo is not None:
                    sel &= vals >= lo
                if hi is not None:
                    sel &= vals < hi
                pm = mask[docs] & sel
                bucket_docs[docs[pm]] = True
            if miss_docs is not None and \
                    (lo is None or miss_val >= lo) and \
                    (hi is None or miss_val < hi):
                bucket_docs |= miss_docs
            bm = mask & bucket_docs
            if self.subs:
                out[key] = _bucket_payload(self, ctx, seg, bm)
            else:
                out[key] = (int(bm.sum()), {})
        return out

    def reduce(self, partials):
        # the reference sorts ranges by (from, to) before bucketing
        # (AbstractRangeBuilder.processRanges → sortRanges)
        inf = float("inf")

        def _order(r):
            lo, hi = self._bounds(r)
            return (-inf if lo is None else lo, inf if hi is None else hi)

        buckets = []
        order = sorted(range(len(self.ranges)),
                       key=lambda i: _order(self.ranges[i]))
        for ri in order:
            r = self.ranges[ri]
            key = self._range_key(r)
            items = [p[ri] for p in partials if ri in p]
            count = sum(c for c, _ in items)
            subs = _reduce_subs(self, [s for _, s in items]) \
                if self.subs else {}
            b = {"key": key, "doc_count": count}
            lo, hi = self._bounds(r)
            if lo is not None:
                b["from"] = self._format_bound(lo)
            if hi is not None:
                b["to"] = self._format_bound(hi)
            b.update(subs)
            buckets.append(b)
        if self.keyed:
            return {"buckets": {b.pop("key"): b for b in buckets}}
        return {"buckets": buckets}


class FilterAgg(BucketAggregator):
    def __init__(self, body):
        from .query_dsl import parse_query
        self.query = parse_query(body)

    def collect(self, ctx, seg, mask):
        import jax.numpy as jnp
        _, qmask = self.query.execute(ctx.shard_ctx, seg)
        fm = mask & np.asarray(qmask)
        if self.subs:
            return _bucket_payload(self, ctx, seg, fm)
        return (int(fm.sum()), {})

    def reduce(self, partials):
        count = sum(c for c, _ in partials)
        out = {"doc_count": count}
        if self.subs:
            out.update(_reduce_subs(self, [s for _, s in partials]))
        return out


class FiltersAgg(BucketAggregator):
    def __init__(self, body):
        from .query_dsl import parse_query
        filters = body.get("filters")
        if not filters:
            raise IllegalArgumentError("[filters] cannot be empty")
        if isinstance(filters, dict):
            self.keyed = True
            self.filters = {k: parse_query(v) for k, v in filters.items()}
        else:
            self.keyed = False
            self.filters = {str(i): parse_query(v)
                            for i, v in enumerate(filters)}

    def collect(self, ctx, seg, mask):
        out = {}
        for key, q in self.filters.items():
            _, qmask = q.execute(ctx.shard_ctx, seg)
            fm = mask & np.asarray(qmask)
            if self.subs:
                out[key] = _bucket_payload(self, ctx, seg, fm)
            else:
                out[key] = (int(fm.sum()), {})
        return out

    def reduce(self, partials):
        buckets = {}
        for key in self.filters:
            items = [p[key] for p in partials]
            count = sum(c for c, _ in items)
            b = {"doc_count": count}
            if self.subs:
                b.update(_reduce_subs(self, [s for _, s in items]))
            buckets[key] = b
        if self.keyed:
            return {"buckets": buckets}
        return {"buckets": [buckets[str(i)] for i in range(len(buckets))]}


class MissingAgg(BucketAggregator):
    def __init__(self, body):
        self.field = body.get("field")
        if self.field is None:
            raise ParsingError("missing requires [field]")
        self.missing = body.get("missing")

    def collect(self, ctx, seg, mask):
        if self.missing is not None:
            # a missing-value substitute means no doc is ever "missing"
            mm0 = np.zeros(mask.shape[0], bool)
            if self.subs:
                return _bucket_payload(self, ctx, seg, mm0)
            return (0, {})
        has = np.zeros(mask.shape[0], bool)
        kw = _keyword_pairs(seg, self.field, ctx.mapper)
        if kw is not None:
            has[kw[0]] = True
        num = _numeric_pairs(seg, self.field, ctx.mapper)
        if num is not None:
            has[num[0]] = True
        tf = seg.text_fields.get(self.field)
        if tf is not None:
            has[: seg.n_docs] |= tf.doc_len_host > 0
        mm = mask & ~has
        if self.subs:
            return _bucket_payload(self, ctx, seg, mm)
        return (int(mm.sum()), {})

    def reduce(self, partials):
        count = sum(c for c, _ in partials)
        out = {"doc_count": count}
        if self.subs:
            out.update(_reduce_subs(self, [s for _, s in partials]))
        return out


class GlobalAgg(BucketAggregator):
    """Ignores the query: buckets over every live doc (reference:
    ``bucket/global/``)."""

    def __init__(self, body):
        pass

    def collect(self, ctx, seg, mask):
        gm = np.zeros(mask.shape[0], bool)
        gm[: seg.n_docs] = seg.live
        if seg.has_nested:
            gm[: seg.n_docs] &= seg.parent_mask    # children stay hidden
        if self.subs:
            return _bucket_payload(self, ctx, seg, gm)
        return (int(gm.sum()), {})

    def reduce(self, partials):
        count = sum(c for c, _ in partials)
        out = {"doc_count": count}
        if self.subs:
            out.update(_reduce_subs(self, [s for _, s in partials]))
        return out


# ---------------------------------------------------------------------------
# pipeline aggregations
# ---------------------------------------------------------------------------


def _resolve_buckets_path(sibling_results: dict, path: str):
    """Extract per-bucket metric series, e.g. "sales>stats.avg" or
    "sales._count" (reference: ``pipeline/BucketHelpers.java``)."""
    parts = path.replace(">", ".").split(".")
    agg_name = parts[0]
    sib = sibling_results.get(agg_name)
    if sib is None or "buckets" not in sib:
        raise IllegalArgumentError(
            f"buckets_path [{path}] must reference a multi-bucket sibling")
    buckets = sib["buckets"]
    if isinstance(buckets, dict):       # keyed response form
        buckets = list(buckets.values())
    series = []
    for b in buckets:
        v: Any = b
        if len(parts) == 1 or parts[1] == "_count":
            v = b["doc_count"]
        elif b.get("doc_count") == 0:
            # GapPolicy.SKIP: an empty bucket's metric is treated as
            # missing, not 0 (``BucketHelpers.resolveBucketValue``)
            v = None
        else:
            sp = parts[1:]
            for i, p in enumerate(sp):
                if isinstance(v, dict) and isinstance(v.get(p), dict) \
                        and "buckets" in v[p] and i + 1 < len(sp):
                    # traversing INTO a multi-bucket agg yields one value
                    # per inner bucket — an array, never a number
                    raise IllegalArgumentError(
                        "buckets_path must reference either a number "
                        "value or a single value numeric metric "
                        "aggregation, got: [Object[]] at aggregation "
                        f"[{p}]")
                if isinstance(v, dict):
                    v = v.get(p)
            if isinstance(v, dict) and "buckets" in v:
                raise IllegalArgumentError(
                    "buckets_path must reference either a number value "
                    "or a single value numeric metric aggregation, got: "
                    f"[{_internal_agg_class(v)}] at aggregation "
                    f"[{sp[-1]}]")
            if isinstance(v, dict) and "value" not in v and \
                    any(k in v for k in ("values", "min", "std_deviation")):
                raise IllegalArgumentError(
                    "buckets_path must reference either a number value "
                    "or a single value numeric metric aggregation, but "
                    f"[{sp[-1]}] contains multiple values. Please "
                    "specify which to use.")
            if isinstance(v, dict):
                v = v.get("value")
        series.append(v)
    return buckets, series


def _internal_agg_class(node: dict) -> str:
    """Best-effort reference class name for a multi-bucket result node,
    keyed off the bucket key type (LongTerms/DoubleTerms/StringTerms —
    ``BucketHelpers.formatResolutionError`` surfaces the class)."""
    blist = node.get("buckets")
    blist = list(blist.values()) if isinstance(blist, dict) else blist
    keys = [b.get("key") for b in (blist or []) if isinstance(b, dict)]
    if any(isinstance(k, str) for k in keys):
        return "StringTerms"
    if any(isinstance(k, float) and not float(k).is_integer()
           for k in keys):
        return "DoubleTerms"
    return "LongTerms"


class _SiblingPipelineAgg(PipelineAggregator):
    def __init__(self, body):
        self.buckets_path = body.get("buckets_path")
        if not self.buckets_path:
            raise ParsingError("pipeline aggregation requires [buckets_path]")

    def _values(self, sibling_results):
        _, series = _resolve_buckets_path(sibling_results, self.buckets_path)
        return [v for v in series if v is not None]


class AvgBucketAgg(_SiblingPipelineAgg):
    def apply(self, sibling_results):
        v = self._values(sibling_results)
        return {"value": (sum(v) / len(v)) if v else None}


class SumBucketAgg(_SiblingPipelineAgg):
    def apply(self, sibling_results):
        v = self._values(sibling_results)
        return {"value": sum(v) if v else 0.0}


class MinBucketAgg(_SiblingPipelineAgg):
    def apply(self, sibling_results):
        v = self._values(sibling_results)
        return {"value": min(v) if v else None}


class MaxBucketAgg(_SiblingPipelineAgg):
    def apply(self, sibling_results):
        v = self._values(sibling_results)
        return {"value": max(v) if v else None}


class StatsBucketAgg(_SiblingPipelineAgg):
    def apply(self, sibling_results):
        v = self._values(sibling_results)
        if not v:
            return {"count": 0, "min": None, "max": None, "avg": None,
                    "sum": 0.0}
        return {"count": len(v), "min": min(v), "max": max(v),
                "avg": sum(v) / len(v), "sum": sum(v)}


class CumulativeSumAgg(_SiblingPipelineAgg):
    parent_pipeline = True

    def apply(self, sibling_results):
        buckets, series = _resolve_buckets_path(
            sibling_results, self.buckets_path)
        total = 0.0
        for b, v in zip(buckets, series):
            total += v or 0.0
            b.setdefault("cumulative_sum", {"value": total})
            b["cumulative_sum"] = {"value": total}
        return {"_applied_to": self.buckets_path.split(">")[0].split(".")[0]}

    def apply_parent(self, name, parent_node):
        blist = parent_node.get("buckets")
        blist = list(blist.values()) if isinstance(blist, dict) else blist
        total = 0.0
        for b, v in zip(blist, _bucket_series(blist, self.buckets_path)):
            total += v or 0.0
            b[name] = {"value": total}


class DerivativeAgg(_SiblingPipelineAgg):
    parent_pipeline = True

    def apply(self, sibling_results):
        buckets, series = _resolve_buckets_path(
            sibling_results, self.buckets_path)
        prev = None
        for b, v in zip(buckets, series):
            if prev is not None and v is not None:
                b["derivative"] = {"value": v - prev}
            prev = v if v is not None else prev
        return {"_applied_to": self.buckets_path.split(">")[0].split(".")[0]}

    def apply_parent(self, name, parent_node):
        blist = parent_node.get("buckets")
        blist = list(blist.values()) if isinstance(blist, dict) else blist
        series = _bucket_series(blist, self.buckets_path)
        prev = None
        for b, v in zip(blist, series):
            if prev is not None and v is not None:
                b[name] = {"value": v - prev}
            prev = v if v is not None else prev


class MovingFnAgg(PipelineAggregator):
    """moving_fn (reference: ``pipeline/MovFnPipelineAggregator``): a
    sliding window over the parent's bucket metric series, evaluated by
    a MovingFunctions.<fn>(values) script subset."""

    parent_pipeline = True

    _FNS = {
        "max": lambda v: max(v) if v else None,
        "min": lambda v: min(v) if v else None,
        "sum": lambda v: sum(v) if v else 0.0,
        "unweightedAvg": lambda v: (sum(v) / len(v)) if v else None,
        "stdDev": None,      # handled specially (needs avg argument)
        "linearWeightedAvg": lambda v: (
            sum((i + 1) * x for i, x in enumerate(v)) /
            sum(range(1, len(v) + 1))) if v else None,
    }

    def __init__(self, body):
        self.buckets_path = body.get("buckets_path")
        self.window = body.get("window")
        self.shift = int(body.get("shift", 0))
        script = body.get("script")
        if isinstance(script, dict):
            script = script.get("source")
        self.script = script or ""
        if self.buckets_path is None or self.window is None:
            raise ParsingError("moving_fn requires [buckets_path] and "
                               "[window]")
        if int(self.window) <= 0:
            raise IllegalArgumentError(
                "[window] must be a positive, non-zero integer.")
        self.window = int(self.window)
        m = re.search(r"MovingFunctions\.(\w+)\s*\(", self.script)
        self.fn = m.group(1) if m else None

    def apply_parent(self, name, parent_node):
        blist = parent_node.get("buckets")
        blist = list(blist.values()) if isinstance(blist, dict) else blist
        series = _bucket_series(blist, self.buckets_path)
        for i, b in enumerate(blist):
            # window covers [i - window + shift, i + shift)
            lo = max(0, i - self.window + self.shift)
            hi = max(0, i + self.shift)
            vals = [v for v in series[lo:hi] if v is not None]
            if self.fn == "stdDev":
                if vals:
                    avg = sum(vals) / len(vals)
                    out = (sum((x - avg) ** 2 for x in vals)
                           / len(vals)) ** 0.5
                else:
                    out = None
            else:
                fn = self._FNS.get(self.fn)
                out = fn(vals) if fn else None
            if out is not None:
                b[name] = {"value": out}

    def apply(self, sibling_results):
        raise IllegalArgumentError(
            "moving_fn must be used inside a histogram parent")


class SerialDiffAgg(PipelineAggregator):
    parent_pipeline = True

    def __init__(self, body):
        self.buckets_path = body.get("buckets_path")
        if self.buckets_path is None:
            raise ParsingError("serial_diff requires [buckets_path]")
        self.lag = int(body.get("lag", 1))
        if self.lag <= 0:
            raise IllegalArgumentError(
                "lag must be a positive, non-zero integer")

    def apply_parent(self, name, parent_node):
        blist = parent_node.get("buckets")
        blist = list(blist.values()) if isinstance(blist, dict) else blist
        series = _bucket_series(blist, self.buckets_path)
        for i, b in enumerate(blist):
            if i >= self.lag and series[i] is not None and \
                    series[i - self.lag] is not None:
                b[name] = {"value": series[i] - series[i - self.lag]}


class BucketSelectorAgg(PipelineAggregator):
    parent_pipeline = True

    def __init__(self, body):
        self.buckets_paths = body.get("buckets_path")
        script = body.get("script")
        if isinstance(script, dict):
            script = script.get("source")
        self.script = script
        if not isinstance(self.buckets_paths, dict) or not self.script:
            raise ParsingError(
                "bucket_selector requires [buckets_path] map and [script]")

    def apply_parent(self, name, parent_node):
        from ..utils.expressions import evaluate_expression
        blist = parent_node.get("buckets")
        keyed = isinstance(blist, dict)
        items = list(blist.items()) if keyed else list(enumerate(blist))
        series = {var: _bucket_series(
            [b for _, b in items], path)
            for var, path in self.buckets_paths.items()}
        kept = []
        for i, (k, b) in enumerate(items):
            params = {v: series[v][i] for v in series}
            if any(p is None for p in params.values()):
                continue
            if evaluate_expression(self.script, params):
                kept.append((k, b))
        if keyed:
            parent_node["buckets"] = {k: b for k, b in kept}
        else:
            parent_node["buckets"] = [b for _, b in kept]

    def apply(self, sibling_results):
        raise IllegalArgumentError(
            "bucket_selector must be used inside a multi-bucket parent")


class BucketSortAgg(PipelineAggregator):
    parent_pipeline = True

    def __init__(self, body):
        self.sort = body.get("sort") or []
        self.from_ = int(body.get("from", 0))
        self.size = body.get("size")
        self.gap_policy = body.get("gap_policy", "skip")

    def apply_parent(self, name, parent_node):
        blist = parent_node.get("buckets")
        if isinstance(blist, dict):
            return                          # keyed responses keep order
        out = list(blist)
        for clause in reversed(self.sort if isinstance(self.sort, list)
                               else [self.sort]):
            if isinstance(clause, str):
                path, order = clause, "asc"
            else:
                (path, spec), = clause.items()
                order = spec.get("order", "asc") \
                    if isinstance(spec, dict) else spec
            series = dict(zip(map(id, out), _bucket_series(out, path)))
            present = [b for b in out if series[id(b)] is not None]
            absent = [b for b in out if series[id(b)] is None]
            present.sort(key=lambda b: series[id(b)],
                         reverse=(order == "desc"))
            out = present + absent         # gap buckets always last
        end = None if self.size is None else self.from_ + int(self.size)
        parent_node["buckets"] = out[self.from_: end]

    def apply(self, sibling_results):
        raise IllegalArgumentError(
            f"bucket_sort aggregation [{self.name}] must be declared "
            f"inside of another aggregation")


class BucketScriptAgg(PipelineAggregator):
    """Arithmetic over sibling bucket metrics using a safe expression
    evaluator (the reference runs Painless — ``pipeline/BucketScript``;
    here a restricted arithmetic grammar, see utils/expressions)."""

    def __init__(self, body):
        self.buckets_paths = body.get("buckets_path")
        self.script = body.get("script")
        if not isinstance(self.buckets_paths, dict) or not self.script:
            raise ParsingError(
                "bucket_script requires [buckets_path] map and [script]")
        if isinstance(self.script, dict):
            self.script = self.script.get("source")

    def apply(self, sibling_results):
        from ..utils.expressions import evaluate_expression
        series = {}
        buckets_ref = None
        for var, path in self.buckets_paths.items():
            buckets, vals = _resolve_buckets_path(sibling_results, path)
            series[var] = vals
            buckets_ref = buckets
        if buckets_ref is None:
            return {}
        for i, b in enumerate(buckets_ref):
            params = {v: series[v][i] for v in series}
            if any(p is None for p in params.values()):
                continue
            b[self.name] = {"value": evaluate_expression(self.script, params)}
        return {"_applied_to": next(iter(self.buckets_paths.values()))
                .split(">")[0].split(".")[0]}

    parent_pipeline = True

    def apply_parent(self, name, parent_node):
        from ..utils.expressions import evaluate_expression
        blist = parent_node.get("buckets")
        blist = list(blist.values()) if isinstance(blist, dict) else blist
        series = {var: _bucket_series(blist, path)
                  for var, path in self.buckets_paths.items()}
        for i, b in enumerate(blist):
            params = {v: series[v][i] for v in series}
            if any(p is None for p in params.values()):
                continue
            b[name] = {"value": evaluate_expression(self.script, params)}


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

class ScriptedMetricAgg(Aggregator):
    """scripted_metric: init/map per segment, combine per partial, reduce
    once across every shard's partials (reference:
    ``metrics/ScriptedMetricAggregator.java``; scripts run through the
    sandboxed Painless-lite engine, ``script/painless_lite.py``).

    Divergence (documented): map/combine run per SEGMENT rather than per
    shard — combine must stay associative, which every reference example
    (and the reference's own reduce contract) already requires. ``doc``
    reads field values out of the stored ``_source`` (the engine's
    doc-values view for scripts)."""

    def __init__(self, body):
        def src(key):
            v = body.get(key)
            if isinstance(v, dict):
                v = v.get("source")
            return v
        self.init_script = src("init_script")
        self.map_script = src("map_script")
        if not self.map_script:
            raise IllegalArgumentError(
                "[map_script] must be provided for metric aggregations.")
        self.combine_script = src("combine_script")
        self.reduce_script = src("reduce_script")
        self.params = body.get("params") or {}

    def collect(self, ctx, seg, mask):
        import copy

        from ..script.painless_lite import DocAccessor
        from ..script.service import DEFAULT as _scripts
        state: dict = {}
        params = copy.deepcopy(self.params)
        if self.init_script:
            _scripts.run(self.init_script,
                         {"state": state, "params": params})
        mask_h = np.asarray(mask)
        compiled = _scripts.compile(self.map_script)
        for local in np.flatnonzero(mask_h[: seg.n_docs]):
            source = seg.sources[int(local)] or {}

            def lookup(field, _s=source):
                v = _s.get(field)
                if v is None and "." in field:
                    node = _s
                    for part in field.split("."):
                        node = node.get(part) if isinstance(node, dict) \
                            else None
                        if node is None:
                            break
                    v = node
                return v if isinstance(v, list) else (
                    [] if v is None else [v])
            compiled.run({"state": state, "params": params,
                          "doc": DocAccessor(lookup)})
        if self.combine_script:
            return _scripts.run(self.combine_script,
                                {"state": state, "params": params})
        return state

    def reduce(self, partials):
        import copy
        from ..script.service import DEFAULT as _scripts
        states = list(partials)
        if self.reduce_script:
            value = _scripts.run(self.reduce_script, {
                "states": states,
                "params": copy.deepcopy(self.params)})
        else:
            value = states
        return {"value": value}


_AGG_PARSERS = {
    "scripted_metric": ScriptedMetricAgg,
    "avg": AvgAgg,
    "sum": SumAgg,
    "min": MinAgg,
    "max": MaxAgg,
    "value_count": ValueCountAgg,
    "stats": StatsAgg,
    "extended_stats": ExtendedStatsAgg,
    "cardinality": CardinalityAgg,
    "percentiles": PercentilesAgg,
    "percentile_ranks": PercentileRanksAgg,
    "weighted_avg": WeightedAvgAgg,
    "median_absolute_deviation": MedianAbsoluteDeviationAgg,
    "top_hits": TopHitsAgg,
    "terms": TermsAgg,
    "histogram": HistogramAgg,
    "date_histogram": DateHistogramAgg,
    "range": RangeAgg,
    "filter": FilterAgg,
    "filters": FiltersAgg,
    "missing": MissingAgg,
    "global": GlobalAgg,
    "avg_bucket": AvgBucketAgg,
    "sum_bucket": SumBucketAgg,
    "min_bucket": MinBucketAgg,
    "max_bucket": MaxBucketAgg,
    "stats_bucket": StatsBucketAgg,
    "cumulative_sum": CumulativeSumAgg,
    "derivative": DerivativeAgg,
    "bucket_script": BucketScriptAgg,
    "bucket_selector": BucketSelectorAgg,
    "bucket_sort": BucketSortAgg,
    "moving_fn": MovingFnAgg,
    "serial_diff": SerialDiffAgg,
}

# composite / significant_terms / rare_terms / sampler / nested /
# reverse_nested live in aggs_extra.py; it registers itself into
# _AGG_PARSERS at its own module bottom, which keeps BOTH import orders
# safe (importing aggs_extra first re-enters here only to bind names)
from . import aggs_extra as _aggs_extra      # noqa: E402, F401
from . import aggs_geo as _aggs_geo          # noqa: E402, F401
from . import aggs_analytics as _aggs_analytics   # noqa: E402, F401
