"""Fetch phase: turn matched (segment, doc) pairs into response hits.

Re-design of the reference fetch phase (``search/fetch/FetchPhase.java:73``
+ 15 sub-phases under ``search/fetch/subphase/``): _source loading and
filtering, docvalue_fields, stored fields and highlighting. Fetch is pure
host work over the tiny top-k result set — nothing here touches the device
(the reference similarly runs fetch on the much smaller hit list).
"""

from __future__ import annotations

import fnmatch
import re
from typing import Any, Dict, List, Optional, Sequence

from ..common.errors import IllegalArgumentError, ParsingError
from ..index.mapping import (DateFieldType, MapperService, format_date_millis)
from ..index.segment import Segment


# ---------------------------------------------------------------------------
# _source filtering (reference: search/fetch/subphase/FetchSourcePhase.java)
# ---------------------------------------------------------------------------


def _match_any(path: str, patterns: Sequence[str]) -> bool:
    return any(fnmatch.fnmatchcase(path, p) or path.startswith(p + ".")
               or fnmatch.fnmatchcase(path.split(".")[0], p)
               for p in patterns)


def _filter_tree(obj: Any, prefix: str, includes, excludes):
    if not isinstance(obj, dict):
        return obj
    out = {}
    for k, v in obj.items():
        path = f"{prefix}{k}"
        if excludes and _match_any(path, excludes):
            continue
        if includes:
            # keep if the path matches, or is an ancestor of a match
            direct = _match_any(path, includes)
            ancestor = any(p.startswith(path + ".") for p in includes)
            if not direct and not ancestor:
                continue
            if not direct and ancestor and isinstance(v, dict):
                v = _filter_tree(v, path + ".", includes, excludes)
                if not v:
                    continue
                out[k] = v
                continue
        if isinstance(v, dict):
            out[k] = _filter_tree(v, path + ".", None, excludes)
        else:
            out[k] = v
    return out


def filter_source(source: Optional[dict], spec) -> Optional[dict]:
    """Apply the request's ``_source`` spec: True/False, "field", ["f1",
    "f2*"], or {"includes": [...], "excludes": [...]}."""
    if source is None or spec is True or spec is None:
        return source
    if spec is False:
        return None
    if isinstance(spec, str):
        spec = [spec]
    if isinstance(spec, list):
        return _filter_tree(source, "", spec, None)
    if isinstance(spec, dict):
        inc = spec.get("includes") or spec.get("include")
        exc = spec.get("excludes") or spec.get("exclude")
        if isinstance(inc, str):
            inc = [inc]
        if isinstance(exc, str):
            exc = [exc]
        return _filter_tree(source, "", inc or None, exc or None)
    raise ParsingError(f"invalid _source spec [{spec}]")


# ---------------------------------------------------------------------------
# docvalue_fields (reference: subphase/FetchDocValuesPhase.java)
# ---------------------------------------------------------------------------


def format_date_ns(ns: int, pattern: str) -> str:
    """Java-pattern render at NANOS resolution, with quoted literals
    ('T'), u-years, long S runs and X zone (date_nanos docvalue
    formats)."""
    import datetime
    dt = datetime.datetime.fromtimestamp(
        (ns // 10 ** 9), tz=datetime.timezone.utc)
    frac9 = f"{ns % 10 ** 9:09d}"
    reps = {"y": "%Y", "u": "%Y", "M": "%m", "d": "%d", "H": "%H",
            "m": "%M", "s": "%S"}

    def _render(m):
        if m.group(1) is not None:          # 'quoted literal'
            return m.group(1)[1:-1] or "'"
        run = m.group(0)
        c = run[0]
        if c == "S":
            return frac9[: len(run)]
        if c in ("X", "Z"):
            return "Z" if c == "X" else "+0000"
        if set(run) == {"e"}:
            return str(dt.isoweekday()).rjust(len(run), "0")
        if c in reps:
            return dt.strftime(reps[c])
        return run
    import re as _re
    return _re.sub(r"('(?:[^']|'')*')|([a-zA-Z])\2*",
                   lambda m: _render(m), pattern)


def docvalue_fields(seg: Segment, mapper: MapperService, local_doc: int,
                    specs: Sequence) -> Dict[str, List[Any]]:
    out: Dict[str, List[Any]] = {}
    for spec in specs:
        if isinstance(spec, dict):
            field = spec.get("field")
            fmt = spec.get("format")
        else:
            field, fmt = spec, None
        if field is None:
            raise ParsingError("docvalue_fields entries require [field]")
        if field == "_seq_no":
            out["_seq_no"] = [int(seg.seq_nos[local_doc])]
            continue
        ft = mapper.field_type(field)
        vals: List[Any] = []
        is_ns = isinstance(ft, DateFieldType) and ft.nanos
        if is_ns:
            i64 = getattr(seg, "int64_fields", {}).get(ft.name or field)
            if i64 is not None:
                idocs, ivals = i64
                sel64 = idocs == local_doc
                ns_list = ivals[sel64].tolist()
            else:
                ns_list = []
        nf = seg.numeric_fields.get(field)
        if nf is not None:
            sel = nf.docs_host == local_doc
            is_date = isinstance(ft, DateFieldType)
            for vi, v in enumerate(nf.vals_host[sel]):
                ns = 0
                if is_ns and vi < len(ns_list):
                    ns = ns_list[vi]
                elif is_date:
                    # integral ms → exact int arithmetic (float64*1e6
                    # rounds off the low digits at epoch scale)
                    ns = int(v) * 10 ** 6 if float(v).is_integer() \
                        else int(round(float(v) * 1e6))
                if fmt is not None and "#" in fmt:
                    vals.append(decimal_format(float(v), fmt))
                elif isinstance(ft, DateFieldType) and fmt == \
                        "epoch_millis":
                    rem = ns % 10 ** 6
                    vals.append(f"{ns // 10 ** 6}.{rem:06d}" if rem
                                else str(ns // 10 ** 6))
                elif isinstance(ft, DateFieldType) and fmt not in (
                        None, "strict_date_optional_time", "date"):
                    vals.append(format_date_ns(ns, fmt)
                                if ("'" in fmt or "S" * 4 in fmt
                                    or "X" in fmt or "u" in fmt or is_ns)
                                else java_date_format(float(v), fmt))
                elif isinstance(ft, DateFieldType) or fmt in (
                        "date", "strict_date_optional_time"):
                    vals.append(format_date_millis(ns // 10 ** 6
                                                   if is_ns
                                                   else float(v)))
                elif float(v).is_integer() and ft is not None and \
                        getattr(ft, "type_name", "") in (
                            "long", "integer", "short", "byte"):
                    vals.append(int(v))
                else:
                    vals.append(float(v))
        kf = seg.keyword_fields.get(field)
        if kf is not None:
            sel = kf.dv_docs_host == local_doc
            vals.extend(kf.ord_terms[o] for o in kf.dv_ords_host[sel])
        if vals:
            # repeated specs for one field (different formats) append in
            # spec order, like FetchDocValuesPhase
            out.setdefault(field, []).extend(vals)
    return out


# ---------------------------------------------------------------------------
# highlight (reference: subphase/highlight/ — unified highlighter)
# ---------------------------------------------------------------------------


def _best_fragments(text: str, spans: List, fragment_size: int,
                    number_of_fragments: int,
                    pre: str, post: str) -> List[str]:
    """Split around matched spans into up-to-N fragments with tags."""
    if not spans:
        return []
    spans.sort()
    if number_of_fragments == 0:
        # whole field value as one fragment
        frags = [(0, len(text), spans)]
    else:
        frags = []
        used: set = set()
        for start, end in spans:
            fs = max(0, start - fragment_size // 2)
            fe = min(len(text), fs + fragment_size)
            key = fs // max(fragment_size, 1)
            if key in used:
                continue
            used.add(key)
            inside = [(s, e) for s, e in spans if s >= fs and e <= fe]
            frags.append((fs, fe, inside))
            if len(frags) >= number_of_fragments:
                break
    out = []
    for fs, fe, inside in frags:
        parts = []
        cur = fs
        for s, e in inside:
            parts.append(text[cur:s])
            parts.append(pre + text[s:e] + post)
            cur = e
        parts.append(text[cur:fe])
        out.append("".join(parts))
    return out


def highlight(mapper: MapperService, source: Optional[dict],
              highlight_spec: dict,
              query_terms: Dict[str, set]) -> Dict[str, List[str]]:
    """Highlight query terms in the hit's source values. The analyzer's
    token offsets locate match spans; tags wrap them."""
    if not source:
        return {}
    fields_spec = highlight_spec.get("fields", {})
    if isinstance(fields_spec, list):  # ES also allows a list of singletons
        merged = {}
        for f in fields_spec:
            merged.update(f)
        fields_spec = merged
    pre = (highlight_spec.get("pre_tags") or ["<em>"])[0]
    post = (highlight_spec.get("post_tags") or ["</em>"])[0]
    field_terms = highlight_spec.get("_field_terms") or {}
    max_ao = highlight_spec.get("_max_analyzed_offset")
    # wildcard field patterns expand over the mapping (ES matches every
    # mapped field; only those with terms produce output)
    expanded: Dict[str, dict] = {}
    for field, fspec in fields_spec.items():
        if "*" in field:
            from ..index.mapping import resolve_field_patterns
            for name in resolve_field_patterns(mapper, field):
                expanded.setdefault(name, fspec)
        else:
            expanded[field] = fspec
    out: Dict[str, List[str]] = {}
    for field, fspec in expanded.items():
        fspec = fspec or {}
        frag_size = int(fspec.get("fragment_size",
                                  highlight_spec.get("fragment_size", 100)))
        n_frags = int(fspec.get("number_of_fragments",
                                highlight_spec.get("number_of_fragments", 5)))
        ft = mapper.field_type(field)
        if ft is None:
            continue
        rfm = fspec.get("require_field_match",
                        highlight_spec.get("require_field_match", True))
        if field in field_terms:            # highlight_query override
            terms = field_terms[field]
        elif rfm in (False, "false"):
            # any query term from any field may highlight this one
            terms = set().union(*query_terms.values()) \
                if query_terms else set()
        else:
            terms = query_terms.get(field, set())
            if not terms and "." in field:
                # multi-field subfield: fall back to the parent's terms
                terms = query_terms.get(field.rsplit(".", 1)[0], set())
        if not terms:
            continue
        # walk the source path (multi-field subfields read the parent's
        # source value, like the reference's SourceFieldMapper lookup)
        def _walk(path):
            v = source
            for part in path.split("."):
                if not isinstance(v, dict) or part not in v:
                    return None
                v = v[part]
            return v
        value = _walk(field)
        if value is None and "." in field:
            value = _walk(field.rsplit(".", 1)[0])
        if value is None:
            continue
        values = value if isinstance(value, list) else [value]
        analyzer = getattr(ft, "search_analyzer", None) or \
            getattr(ft, "analyzer", None)
        frags: List[str] = []
        ign = getattr(ft, "ignore_above", None)
        if max_ao is not None:
            # re-analysis beyond the cap is rejected; offsets stored at
            # index time (index_options offsets / term vectors) let the
            # unified and fvh highlighters skip re-analysis
            has_offsets = ft.params.get("index_options") == "offsets" or \
                ft.params.get("term_vector") == "with_positions_offsets"
            hl_type = fspec.get("type", highlight_spec.get("type"))
            needs_analysis = hl_type == "plain" or not has_offsets
            if needs_analysis and any(len(str(v)) > max_ao
                                      for v in values):
                raise IllegalArgumentError(
                    f"The length of [{field}] field of a doc exceeds "
                    f"the [index.highlight.max_analyzed_offset] limit "
                    f"of [{max_ao}]. To avoid this error, set the query "
                    f"parameter [max_analyzed_offset] to a value less "
                    f"than index setting value and this will tolerate "
                    f"long field values by truncating them.")
        for v in values:
            text = str(v)
            if ign is not None and len(text) > ign:
                continue    # value was ignored at index time: no marks
            spans = []
            if analyzer is not None:
                for tok in analyzer.analyze(text):
                    if tok.term in terms:
                        spans.append((tok.start_offset, tok.end_offset))
            else:  # keyword: whole-value match
                if text in terms:
                    spans.append((0, len(text)))
            frags.extend(_best_fragments(text, spans, frag_size, n_frags,
                                         pre, post))
        if frags:
            out[field] = frags[: n_frags if n_frags > 0 else None]
    return out


# ---------------------------------------------------------------------------
# fields retrieval (reference: subphase/FetchFieldsPhase.java +
# fetch/subphase/FieldFetcher.java — source-driven, formatted values)
# ---------------------------------------------------------------------------

_JAVA_STRFTIME = [("yyyy", "%Y"), ("MM", "%m"), ("dd", "%d"), ("HH", "%H"),
                  ("mm", "%M"), ("ss", "%S")]


def java_date_format(millis: float, pattern: str) -> str:
    """Subset of Joda/Java date patterns → formatted UTC string."""
    import datetime
    if pattern in ("epoch_millis",):
        return str(int(millis))
    dt = datetime.datetime.fromtimestamp(millis / 1000.0,
                                         tz=datetime.timezone.utc)
    # tokenize runs of pattern letters so literal text survives intact
    reps = {"yyyy": "%Y", "MM": "%m", "dd": "%d", "HH": "%H",
            "mm": "%M", "ss": "%S"}

    def _render(m):
        run = m.group(0)
        if run == "SSS":
            return f"{dt.microsecond // 1000:03d}"
        if set(run) == {"e"}:            # ISO day-of-week number
            return str(dt.isoweekday()).rjust(len(run), "0")
        if run in reps:
            return dt.strftime(reps[run])
        return run
    import re as _re
    return _re.sub(r"([a-zA-Z])\1*", _render, pattern)


def decimal_format(value: float, pattern: str) -> str:
    """Minimal java DecimalFormat: '#.0' style numeric subpatterns with
    optional literal prefix/suffix text ("Value is #.0")."""
    import re as _re
    m = _re.search(r"[#0]+(?:\.[#0]+)?", pattern)
    if not m:
        return pattern
    num = m.group(0)
    if "." in num:
        decimals = len(num.split(".", 1)[1])
        formatted = f"{value:.{decimals}f}"
    else:
        formatted = str(int(round(value)))
    return pattern[: m.start()] + formatted + pattern[m.end():]


def _source_path_values(src, path: str) -> List[Any]:
    """All values at a dotted path, traversing dicts and flattening lists."""
    nodes = [src]
    for part in path.split("."):
        nxt: List[Any] = []
        for n in nodes:
            if isinstance(n, list):
                n_items = n
            else:
                n_items = [n]
            for item in n_items:
                if isinstance(item, dict) and part in item:
                    v = item[part]
                    nxt.extend(v if isinstance(v, list) else [v])
        nodes = nxt
    return [n for n in nodes if n is not None]


def fetch_fields(mapper: MapperService, src: Optional[dict],
                 specs: Sequence) -> Dict[str, List[Any]]:
    """The ``fields`` request option: formatted values extracted from
    _source for every mapped field matching each pattern."""
    import fnmatch
    from ..index.mapping import (AliasFieldType, NumberFieldType,
                                 ObjectFieldType, RangeFieldType,
                                 BooleanFieldType, TokenCountFieldType)
    from ..common.errors import IllegalArgumentError
    out: Dict[str, List[Any]] = {}
    if not isinstance(src, dict):
        return out
    mapped = mapper._fields
    for spec in specs:
        if isinstance(spec, dict):
            pattern = spec.get("field")
            fmt = spec.get("format")
        else:
            pattern, fmt = spec, None
        if pattern is None:
            raise ParsingError("[fields] entries require [field]")
        matches = [pattern] if pattern in mapped else [
            f for f in mapped
            if fnmatch.fnmatchcase(f, pattern)]
        for f in matches:
            ft = mapped.get(f)
            if isinstance(ft, ObjectFieldType):
                continue
            path = f
            if isinstance(ft, AliasFieldType):
                path = ft.path
                ft = mapper.field_type(f)
            if fmt is not None and not isinstance(
                    ft, (DateFieldType, RangeFieldType)):
                raise IllegalArgumentError(
                    f"Field [{f}] of type [{getattr(ft, 'type_name', '?')}]"
                    f" doesn't support formats.")
            raw = _source_path_values(src, path)
            if not raw and "." in path:
                # multi-field subfield: values live at the PARENT's path
                parent = path.rsplit(".", 1)[0]
                pft = mapped.get(parent)
                if pft is not None and not isinstance(pft, ObjectFieldType):
                    raw = _source_path_values(src, parent)
            vals: List[Any] = []
            for v in raw:
                try:
                    if isinstance(ft, DateFieldType):
                        ms = ft.parse_value(v)
                        vals.append(java_date_format(ms, fmt)
                                    if fmt else
                                    (v if isinstance(v, str) else ms))
                    elif isinstance(ft, TokenCountFieldType):
                        if not ft.doc_values:
                            continue     # no doc values → not retrievable
                        vals.append(int(ft.parse_value(v)))
                    elif isinstance(ft, RangeFieldType):
                        vals.append(v)
                    elif isinstance(ft, NumberFieldType):
                        n = float(ft.parse_value(v))
                        vals.append(int(n) if ft.type_name in (
                            "long", "integer", "short", "byte")
                            else n)
                    elif isinstance(ft, BooleanFieldType):
                        vals.append(v if isinstance(v, bool)
                                    else str(v).lower() == "true")
                    else:
                        vals.append(v if isinstance(v, (dict, bool))
                                    else str(v))
                except IllegalArgumentError:
                    raise
                except Exception:   # noqa: BLE001 — malformed value skip
                    continue
            if vals:
                out[f] = vals
    return out
