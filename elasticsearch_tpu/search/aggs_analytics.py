"""Analytics-plugin aggregations: boxplot, top_metrics, string_stats,
t_test, rate, multi_terms.

Reference: ``x-pack/plugin/analytics/src/main/java/.../analytics/`` —
``boxplot/BoxplotAggregator.java`` (TDigest-backed quartiles),
``topmetrics/TopMetricsAggregator.java`` (per-shard top-by-sort metric
rows), ``stringstats/StringStatsAggregator.java`` (length stats + Shannon
entropy over UTF-8 term bytes), ``ttest/TTestAggregator.java``
(paired / homoscedastic / heteroscedastic with two-tailed p-value),
``rate/RateAggregator.java`` (per-calendar-unit normalization inside a
date_histogram), ``multiterms/MultiTermsAggregator.java`` (terms over
composite tuple keys).

TPU-first shape: every collection is a vectorized columnar pass (numpy on
the host mirror of the doc-values columns — the same columns the device
agg kernels consume); partials are tiny data-only dicts that merge exactly
at the coordinator, so cluster reduces reuse the single-node path.
Exactness over sketches: quartiles/percentile math here is exact rather
than TDigest-approximate (documented divergence; conformance tolerances
accept exact answers).
"""
from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..common.errors import IllegalArgumentError, ParsingError
from .aggregations import (Aggregator, BucketAggregator, _NumericMetricAgg,
                           _bucket_payload, _doc_weights, _format_key,
                           _keyword_pairs, _numeric_pairs, _reduce_subs,
                           _Rev)


# ---------------------------------------------------------------------------
# boxplot
# ---------------------------------------------------------------------------

class BoxplotAgg(_NumericMetricAgg):
    """Quartiles + 1.5·IQR whiskers (``BoxplotAggregator.java``). Exact
    values collection; linear interpolation between closest ranks matches
    the reference's TDigest behavior at conformance scale."""

    def __init__(self, body):
        super().__init__(body)
        # compression is accepted for API parity; the exact path ignores it
        self.compression = float(body.get("compression", 100.0))

    def collect(self, ctx, seg, mask):
        v = self._matched_values(ctx, seg, mask)
        return {"values": v.tolist()}

    def reduce(self, partials):
        vals = np.sort(np.concatenate(
            [np.asarray(p["values"], np.float64) for p in partials])
            if partials else np.empty(0))
        if vals.size == 0:
            inf = float("inf")
            return {"min": inf, "max": -inf, "q1": None, "q2": None,
                    "q3": None, "lower": inf, "upper": -inf}
        q1, q2, q3 = (float(np.percentile(vals, p, method="linear"))
                      for p in (25.0, 50.0, 75.0))
        iqr = q3 - q1
        in_fence = vals[(vals >= q1 - 1.5 * iqr) & (vals <= q3 + 1.5 * iqr)]
        return {"min": float(vals[0]), "max": float(vals[-1]),
                "q1": q1, "q2": q2, "q3": q3,
                "lower": float(in_fence[0]), "upper": float(in_fence[-1])}


# ---------------------------------------------------------------------------
# top_metrics
# ---------------------------------------------------------------------------

class TopMetricsAgg(Aggregator):
    """Metric values of the top-sorted docs (``TopMetricsAggregator``)."""

    def __init__(self, body):
        metrics = body.get("metrics")
        if metrics is None:
            raise ParsingError("[top_metrics] requires [metrics]")
        if isinstance(metrics, dict):
            metrics = [metrics]
        self.metric_fields = [m["field"] for m in metrics]
        sort = body.get("sort")
        if sort is None:
            raise ParsingError("[top_metrics] requires [sort]")
        if isinstance(sort, list):
            sort = sort[0]
        if isinstance(sort, str):
            sort = {sort: {"order": "asc"}}
        (self.sort_field, spec), = sort.items()
        if isinstance(spec, str):
            spec = {"order": spec}
        self.sort_asc = spec.get("order", "asc") == "asc"
        self.size = int(body.get("size", 1))

    def collect(self, ctx, seg, mask):
        self._mapper = ctx.mapper
        pairs = _numeric_pairs(seg, self.sort_field, ctx.mapper)
        if pairs is None:
            return {"rows": []}
        docs, svals = pairs
        pm = mask[docs]
        docs, svals = docs[pm], svals[pm]
        if docs.size == 0:
            return {"rows": []}
        k = min(self.size, docs.size)
        order = np.argsort(svals, kind="stable")
        sel = order[:k] if self.sort_asc else order[::-1][:k]
        rows = []
        metric_cols = {}
        for f in self.metric_fields:
            mp = _numeric_pairs(seg, f, ctx.mapper)
            col: Dict[int, float] = {}
            if mp is not None:
                for d, v in zip(mp[0], mp[1]):
                    col.setdefault(int(d), float(v))
            metric_cols[f] = col
        for i in sel:
            d = int(docs[i])
            rows.append({"sort": [float(svals[i])],
                         "metrics": {f: metric_cols[f].get(d)
                                     for f in self.metric_fields}})
        return {"rows": rows}

    def reduce(self, partials):
        rows = [r for p in partials for r in p["rows"]]
        rows.sort(key=lambda r: r["sort"][0], reverse=not self.sort_asc)
        rows = rows[: self.size]
        mapper = getattr(self, "_mapper", None)
        out_rows = []
        for r in rows:
            key, kas = _format_key(mapper, self.sort_field, r["sort"][0])
            out_rows.append({"sort": [kas if kas is not None else key],
                             "metrics": r["metrics"]})
        return {"top": out_rows}


# ---------------------------------------------------------------------------
# string_stats
# ---------------------------------------------------------------------------

class StringStatsAgg(Aggregator):
    """Length stats + Shannon entropy over term UTF-8 bytes
    (``StringStatsAggregator.java``)."""

    def __init__(self, body):
        self.field = body.get("field")
        if self.field is None:
            raise ParsingError("[string_stats] requires [field]")
        self.show_distribution = bool(body.get("show_distribution", False))

    def collect(self, ctx, seg, mask):
        kw = _keyword_pairs(seg, self.field, ctx.mapper)
        counts: Dict[str, int] = {}
        n = 0
        len_sum = 0
        len_min: Optional[int] = None
        len_max: Optional[int] = None
        if kw is not None:
            docs, ords, terms = kw
            pm = mask[docs]
            for o in ords[pm]:
                t = terms[int(o)]
                n += 1
                bs = t.encode("utf-8")
                len_sum += len(bs)
                ln = len(bs)
                len_min = ln if len_min is None else min(len_min, ln)
                len_max = ln if len_max is None else max(len_max, ln)
                for ch in t:
                    counts[ch] = counts.get(ch, 0) + 1
        return {"count": n, "len_sum": len_sum, "min": len_min,
                "max": len_max, "chars": counts}

    def reduce(self, partials):
        count = sum(p["count"] for p in partials)
        if count == 0:
            out = {"count": 0, "min_length": None, "max_length": None,
                   "avg_length": None, "entropy": 0.0}
            if self.show_distribution:
                out["distribution"] = {}
            return out
        len_sum = sum(p["len_sum"] for p in partials)
        mins = [p["min"] for p in partials if p["min"] is not None]
        maxs = [p["max"] for p in partials if p["max"] is not None]
        chars: Dict[str, int] = {}
        for p in partials:
            for ch, c in p["chars"].items():
                chars[ch] = chars.get(ch, 0) + c
        total_chars = sum(chars.values())
        entropy = 0.0
        dist = {}
        if total_chars:
            for ch, c in chars.items():
                pr = c / total_chars
                entropy -= pr * math.log2(pr)
                dist[ch] = pr
        out = {"count": count, "min_length": min(mins),
               "max_length": max(maxs),
               "avg_length": len_sum / count, "entropy": entropy}
        if self.show_distribution:
            out["distribution"] = dict(
                sorted(dist.items(), key=lambda kv: (-kv[1], kv[0])))
        return out


# ---------------------------------------------------------------------------
# t_test
# ---------------------------------------------------------------------------

def _t_sf(t: float, df: float) -> float:
    """Two-tailed p-value for the t-distribution via the regularized
    incomplete beta function (continued fraction — Numerical Recipes
    betacf form; the reference delegates to commons-math's TDistribution)."""
    if df <= 0 or math.isnan(t):
        return float("nan")
    if t == 0.0:
        return 1.0
    x = df / (df + t * t)
    if x >= 1.0:
        return 1.0
    if x <= 0.0:
        return 0.0
    a, b = df / 2.0, 0.5

    def betacf(a_, b_, x_):
        qab, qap, qam = a_ + b_, a_ + 1.0, a_ - 1.0
        c, d = 1.0, 1.0 - qab * x_ / qap
        if abs(d) < 1e-30:
            d = 1e-30
        d = 1.0 / d
        h = d
        for m in range(1, 200):
            m2 = 2 * m
            aa = m * (b_ - m) * x_ / ((qam + m2) * (a_ + m2))
            d = 1.0 + aa * d
            if abs(d) < 1e-30:
                d = 1e-30
            c = 1.0 + aa / c
            if abs(c) < 1e-30:
                c = 1e-30
            d = 1.0 / d
            h *= d * c
            aa = -(a_ + m) * (qab + m) * x_ / ((a_ + m2) * (qap + m2))
            d = 1.0 + aa * d
            if abs(d) < 1e-30:
                d = 1e-30
            c = 1.0 + aa / c
            if abs(c) < 1e-30:
                c = 1e-30
            d = 1.0 / d
            delta = d * c
            h *= delta
            if abs(delta - 1.0) < 1e-12:
                break
        return h

    lbeta = (math.lgamma(a + b) - math.lgamma(a) - math.lgamma(b)
             + a * math.log(x) + b * math.log(1.0 - x))
    if x < (a + 1.0) / (a + b + 2.0):
        ib = math.exp(lbeta) * betacf(a, b, x) / a
    else:
        ib = 1.0 - math.exp(lbeta) * betacf(b, a, 1.0 - x) / b
    return min(max(ib, 0.0), 1.0)


class TTestAgg(Aggregator):
    """Student's / Welch's t-test (``ttest/TTestAggregator.java``)."""

    def __init__(self, body):
        a, b = body.get("a"), body.get("b")
        if not a or not b or "field" not in a or "field" not in b:
            raise ParsingError(
                "[t_test] requires [a.field] and [b.field]")
        self.a_field, self.b_field = a["field"], b["field"]
        self.a_filter, self.b_filter = a.get("filter"), b.get("filter")
        self.type = body.get("type", "heteroscedastic")
        if self.type not in ("paired", "homoscedastic", "heteroscedastic"):
            raise ParsingError(f"invalid t_test type [{self.type}]")
        if self.type == "paired" and (self.a_filter or self.b_filter):
            raise IllegalArgumentError(
                "Paired t-test doesn't support filters")

    def _filtered_mask(self, ctx, seg, mask, flt):
        if flt is None:
            return mask
        from .query_dsl import parse_query
        q = parse_query(flt)
        _, qmask = q.execute(ctx.shard_ctx, seg)
        return mask & np.asarray(qmask)

    def _moments(self, ctx, seg, mask, field) -> dict:
        pairs = _numeric_pairs(seg, field, ctx.mapper)
        if pairs is None:
            return {"n": 0, "sum": 0.0, "sumsq": 0.0}
        docs, vals = pairs
        pm = mask[docs]
        v = vals[pm]
        return {"n": int(v.size), "sum": float(v.sum()),
                "sumsq": float((v * v).sum())}

    def collect(self, ctx, seg, mask):
        if self.type == "paired":
            pa = _numeric_pairs(seg, self.a_field, ctx.mapper)
            pb = _numeric_pairs(seg, self.b_field, ctx.mapper)
            col_a: Dict[int, float] = {}
            col_b: Dict[int, float] = {}
            if pa is not None:
                for d, v in zip(pa[0], pa[1]):
                    col_a.setdefault(int(d), float(v))
            if pb is not None:
                for d, v in zip(pb[0], pb[1]):
                    col_b.setdefault(int(d), float(v))
            idx = np.flatnonzero(mask[: seg.n_docs])
            diffs = [col_a[d] - col_b[d] for d in idx
                     if d in col_a and d in col_b]
            arr = np.asarray(diffs, np.float64)
            return {"d": {"n": int(arr.size), "sum": float(arr.sum()),
                          "sumsq": float((arr * arr).sum())}}
        am = self._filtered_mask(ctx, seg, mask, self.a_filter)
        bm = self._filtered_mask(ctx, seg, mask, self.b_filter)
        return {"a": self._moments(ctx, seg, am, self.a_field),
                "b": self._moments(ctx, seg, bm, self.b_field)}

    @staticmethod
    def _merge(ms: List[dict]) -> Tuple[int, float, float]:
        n = sum(m["n"] for m in ms)
        s = sum(m["sum"] for m in ms)
        ss = sum(m["sumsq"] for m in ms)
        return n, s, ss

    def reduce(self, partials):
        if self.type == "paired":
            n, s, ss = self._merge([p["d"] for p in partials])
            if n < 2:
                return {"value": None}
            mean = s / n
            var = (ss - n * mean * mean) / (n - 1)
            if var <= 0:
                return {"value": 0.0 if mean else None}
            t = mean / math.sqrt(var / n)
            return {"value": _t_sf(t, n - 1)}
        na, sa, ssa = self._merge([p["a"] for p in partials])
        nb, sb, ssb = self._merge([p["b"] for p in partials])
        if na < 2 or nb < 2:
            return {"value": None}
        ma, mb = sa / na, sb / nb
        va = (ssa - na * ma * ma) / (na - 1)
        vb = (ssb - nb * mb * mb) / (nb - 1)
        if self.type == "homoscedastic":
            sp2 = ((na - 1) * va + (nb - 1) * vb) / (na + nb - 2)
            if sp2 <= 0:
                return {"value": None}
            t = (ma - mb) / math.sqrt(sp2 * (1.0 / na + 1.0 / nb))
            return {"value": _t_sf(t, na + nb - 2)}
        sea, seb = va / na, vb / nb
        se = sea + seb
        if se <= 0:
            return {"value": None}
        t = (ma - mb) / math.sqrt(se)
        df = se * se / (sea * sea / (na - 1) + seb * seb / (nb - 1))
        return {"value": _t_sf(t, df)}


# ---------------------------------------------------------------------------
# rate
# ---------------------------------------------------------------------------

#: calendar unit → fixed millis (Rounding unit lengths the reference's
#: RateAggregator uses for interval ratios)
_UNIT_MS = {"second": 1e3, "minute": 6e4, "hour": 3.6e6, "day": 8.64e7,
            "week": 6.048e8, "month": 2.592e9, "quarter": 7.776e9,
            "year": 3.1536e10}


class RateAgg(_NumericMetricAgg):
    """Per-unit rate inside a date_histogram (``RateAggregator.java``).
    parse_aggs stamps ``_parent_interval_ms`` from the enclosing
    date_histogram (the reference resolves the same way via the parent's
    Rounding)."""

    _needs_parent_interval = True

    def __init__(self, body):
        self.field = body.get("field")          # optional: doc-count rate
        self.missing = body.get("missing")
        unit = body.get("unit", "day")
        if unit not in _UNIT_MS:
            raise ParsingError(f"Unsupported unit [{unit}]")
        self.unit = unit
        self.mode = body.get("mode", "sum")
        if self.mode not in ("sum", "value_count"):
            raise ParsingError(f"Unsupported rate mode [{self.mode}]")
        self._parent_interval_ms: Optional[float] = None

    def collect(self, ctx, seg, mask):
        if self.field is None:
            w = _doc_weights(seg)
            n = (float(mask[: seg.n_docs].sum()) if w is None
                 else float(w[mask[: seg.n_docs]].sum()))
            return {"sum": n}
        v = self._matched_values(ctx, seg, mask)
        return {"sum": float(v.sum()) if self.mode == "sum"
                else float(v.size)}

    def reduce(self, partials):
        if self._parent_interval_ms is None:
            raise IllegalArgumentError(
                "The rate aggregation can only be used inside a "
                "date histogram")
        total = sum(p["sum"] for p in partials)
        factor = self._parent_interval_ms / _UNIT_MS[self.unit]
        return {"value": total / factor if factor else None}


# ---------------------------------------------------------------------------
# multi_terms
# ---------------------------------------------------------------------------

class MultiTermsAgg(BucketAggregator):
    """Terms over tuple keys (``MultiTermsAggregator.java``). Tuple key
    columns materialize per source the same way composite sources do; the
    bucket space is their per-doc cartesian product."""

    def __init__(self, body):
        terms = body.get("terms")
        if not terms or not isinstance(terms, list) or len(terms) < 2:
            raise IllegalArgumentError(
                "The [terms] parameter in the aggregation [multi_terms] "
                "must be present and have at least 2 fields")
        self.fields = []
        self.missings = []
        for t in terms:
            if "field" not in t:
                raise ParsingError(
                    "[multi_terms] each term needs a [field]")
            self.fields.append(t["field"])
            self.missings.append(t.get("missing"))
        self.size = int(body.get("size", 10))
        self.shard_size = int(body.get("shard_size",
                                       self.size * 3 // 2 + 10))
        self.min_doc_count = int(body.get("min_doc_count", 1))
        self.order = body.get("order", {"_count": "desc"})

    def _key_col(self, ctx, seg, field, missing) -> List[List[Any]]:
        col: List[List[Any]] = [[] for _ in range(seg.n_docs)]
        kw = _keyword_pairs(seg, field, ctx.mapper)
        if kw is not None:
            docs, ords, terms = kw
            for d, o in zip(docs, ords):
                col[int(d)].append(terms[int(o)])
        else:
            num = _numeric_pairs(seg, field, ctx.mapper)
            if num is not None:
                for d, v in zip(num[0], num[1]):
                    fv = float(v)
                    col[int(d)].append(int(fv) if fv.is_integer() else fv)
        if missing is not None:
            for c in col:
                if not c:
                    c.append(missing)
        return [list(dict.fromkeys(c)) for c in col]

    def collect(self, ctx, seg, mask):
        import itertools as _it
        self._mapper = ctx.mapper
        cols = [self._key_col(ctx, seg, f, m)
                for f, m in zip(self.fields, self.missings)]
        idx = np.flatnonzero(mask[: seg.n_docs])
        by_key_docs: Dict[tuple, List[int]] = {}
        for d in idx:
            per = [c[d] for c in cols]
            if any(not vs for vs in per):
                continue
            for key in _it.product(*per):
                by_key_docs.setdefault(key, []).append(int(d))
        w = _doc_weights(seg)
        counts = {key: (len(ds) if w is None else int(w[ds].sum()))
                  for key, ds in by_key_docs.items()}
        trunc_err = 0
        if self.subs and len(by_key_docs) > self.shard_size:
            # each kept key costs a full bucket collection: cap at
            # shard_size by segment-local count; the dropped tail bounds
            # the doc-count error (InternalTerms docCountError accounting)
            ranked = sorted(by_key_docs, key=lambda k: (-counts[k],))
            kept = set(ranked[: self.shard_size])
            trunc_err = counts[ranked[self.shard_size]] \
                if len(ranked) > self.shard_size else 0
            by_key_docs = {k: v for k, v in by_key_docs.items()
                           if k in kept}
        buckets: Dict[tuple, Tuple[int, dict]] = {}
        for key, ds in by_key_docs.items():
            if self.subs:
                bm = np.zeros(mask.shape[0], bool)
                bm[ds] = True
                buckets[key] = _bucket_payload(self, ctx, seg, bm)
            else:
                buckets[key] = (counts[key], {})
        return buckets, trunc_err

    def _sort_key(self):
        order = self.order
        if isinstance(order, list):
            order = order[0]
        (field, direction), = order.items()
        return field, (1 if direction == "asc" else -1)

    def reduce(self, partials):
        merged: Dict[tuple, List] = {}
        err_bound = 0
        for p in partials:
            bkts, trunc_err = p
            err_bound += trunc_err
            for key, (count, subs) in bkts.items():
                merged.setdefault(key, []).append((count, subs))
        rows = []
        for key, items in merged.items():
            count = sum(c for c, _ in items)
            if count < self.min_doc_count:
                continue
            subs = _reduce_subs(self, [s for _, s in items]) \
                if self.subs else {}
            rows.append((key, count, subs))
        field, sign = self._sort_key()

        def keyfn(row):
            key, count, subs = row
            if field == "_count":
                return (sign * count,) + tuple(
                    k if isinstance(k, str) else str(k) for k in key)
            if field == "_key":
                return tuple((sign * k if isinstance(k, (int, float))
                              else (k if sign == 1 else _Rev(k)))
                             for k in key)
            path = field.split(".")
            v = subs.get(path[0], {})
            v = v.get(path[1] if len(path) > 1 else "value")
            return (sign * (v if v is not None else float("-inf")),)

        rows.sort(key=keyfn)
        total_other = sum(c for _, c, _ in rows)
        rows = rows[: self.size]
        total_other -= sum(c for _, c, _ in rows)
        out = []
        for key, count, subs in rows:
            b = {"key": list(key),
                 "key_as_string": "|".join(str(k) for k in key),
                 "doc_count": count}
            b.update(subs)
            out.append(b)
        return {"doc_count_error_upper_bound": err_bound,
                "sum_other_doc_count": total_other, "buckets": out}


# ---------------------------------------------------------------------------
# registration (same late-binding pattern as aggs_extra)
# ---------------------------------------------------------------------------

from .aggregations import _AGG_PARSERS      # noqa: E402

_AGG_PARSERS.update({
    "boxplot": BoxplotAgg,
    "top_metrics": TopMetricsAgg,
    "string_stats": StringStatsAgg,
    "t_test": TTestAgg,
    "rate": RateAgg,
    "multi_terms": MultiTermsAgg,
})
