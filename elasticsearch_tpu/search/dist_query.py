"""Distributed query execution: scatter per shard, one global reduce.

Re-design of the reference's search coordination
(``action/search/AbstractSearchAsyncAction.java:70`` fans the query to one
copy of every shard; ``SearchPhaseController.java:155-219`` merges the
per-shard ``TopDocs``/aggregation trees on the coordinating node). The
full query DSL — bool trees, filters, sort, knn, highlights — executes
*per shard* through :class:`ShardSearcher` (each shard's segments live on
its device; the bag-of-words/kNN hot paths additionally have the fully
on-mesh SPMD plane in ``parallel/dist_search.py``), and this module is
the coordinating side:

- **DFS phase always-on**: term statistics (df, avgdl, doc counts) are
  computed over ALL shards and injected into every shard's context, so
  scores are identical to a single pooled searcher
  (``search/dfs/DfsPhase.java`` — the reference makes this opt-in; global
  stats are cheap host-side sums here).
- **Query phase**: every shard returns its top ``from+size`` window
  (sorted by the request's sort), its total, and its per-segment
  aggregation inputs.
- **Reduce**: hits merge by the sort key with the global
  ``(shard, segment, doc)`` tie-break (ES's ``_shard_doc``); aggregation
  partials from every shard's segments reduce ONCE globally — per-shard
  pre-reduce would break exactness for terms/cardinality.
- **search_after**: the composite score cursor carries a global shard-doc
  component; the coordinator rewrites it into the correct per-shard local
  cursor (strict-below for shards ordered before the cursor shard, local
  composite on it, ties-allowed after it).

``rank.rrf`` requests fall back to the pooled single-searcher path:
reciprocal-rank fusion needs *global* per-ranking positions, which a
per-shard scatter cannot provide without shipping full rankings — the
reference centralizes RRF on the coordinator the same way.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..index.mapping import MapperService
from .aggregations import (AggregationContext, parse_aggs,
                           run_aggregations_multi)
from .query_dsl import ShardContext
from .shard_search import (ShardHit, ShardSearcher, ShardSearchResult,
                           _tree_needs_scores, collapse_first_by_key)

#: bits reserved for the (segment, doc) part of the global shard-doc key
_LOCAL_BITS = 48


def _required_ranges(query_spec) -> List[tuple]:
    """Extract (field, lo, hi) bounds every match MUST satisfy: top-level
    ``range`` clauses plus those inside ``bool.must``/``bool.filter``
    (recursively). should/must_not never make a clause required."""
    out: List[tuple] = []
    if not isinstance(query_spec, dict):
        return out
    if "range" in query_spec:
        for field, cond in query_spec["range"].items():
            if not isinstance(cond, dict):
                continue
            lo = cond.get("gte", cond.get("gt"))
            hi = cond.get("lte", cond.get("lt"))
            if isinstance(lo, str) or isinstance(hi, str):
                # date strings resolve against each shard's own field
                # format inside _shard_can_match
                out.append((field, lo, hi))
                continue
            out.append((field,
                        float(lo) if lo is not None else float("-inf"),
                        float(hi) if hi is not None else float("inf")))
    b = query_spec.get("bool")
    if isinstance(b, dict):
        for section in ("must", "filter"):
            clauses = b.get(section) or []
            if isinstance(clauses, dict):
                clauses = [clauses]
            for c in clauses:
                out.extend(_required_ranges(c))
    return out


def _shard_can_match(shard: "ShardSearcher", bounds: List[tuple]) -> bool:
    """False iff some required range is disjoint from the shard's
    [min, max] for that field across every segment."""
    for field, lo, hi in bounds:
        from ..index.mapping import DateFieldType, parse_date_millis
        ft = shard.mapper.field_type(field)
        if isinstance(ft, DateFieldType):
            # resolve bounds with this shard's date mapping, using the
            # QUERY layer's coercion (a bare 4-digit number reads as a
            # year, not epoch millis — RangeQuery._bound); hi rounds UP
            # so the skip test stays conservative — can-match must
            # never drop a shard that could hold matches
            def _co(v):
                if isinstance(v, (int, float)) and not isinstance(
                        v, bool) and 1000 <= v <= 9999 and \
                        float(v).is_integer():
                    return str(int(v))
                return v
            try:
                lo = parse_date_millis(_co(lo), ft.format) \
                    if isinstance(_co(lo), str) else (
                        float(lo) if lo is not None else float("-inf"))
                hi = parse_date_millis(_co(hi), ft.format,
                                       round_up=True) \
                    if isinstance(_co(hi), str) else (
                        float(hi) if hi is not None else float("inf"))
            except Exception:   # noqa: BLE001 — unparseable: no skip
                continue
            if lo is None:
                lo = float("-inf")
            if hi is None:
                hi = float("inf")
        elif isinstance(lo, str) or isinstance(hi, str):
            continue                  # non-date string bounds: no skip
        fmin, fmax = float("inf"), float("-inf")
        present = False
        for seg in shard.segments:
            nf = seg.numeric_fields.get(field)
            if nf is None or nf.vals_host.size == 0:
                continue
            cache = getattr(seg, "_minmax_cache", None)
            if cache is None:
                cache = seg._minmax_cache = {}
            mm = cache.get(field)
            if mm is None:
                mm = cache[field] = (float(nf.vals_host.min()),
                                     float(nf.vals_host.max()))
            present = True
            fmin = min(fmin, mm[0])
            fmax = max(fmax, mm[1])
        if not present:
            # not a plain numeric column (range/runtime/unmapped field):
            # the heuristic cannot reason about it — never skip on it
            continue
        if fmax < lo or fmin > hi:
            return False
    return True


class DfsShardContext(ShardContext):
    """Per-shard context whose statistics delegate to the cross-shard
    union — the always-on DFS phase."""

    def __init__(self, segments, mapper, global_ctx: ShardContext):
        super().__init__(segments, mapper)
        self._global = global_ctx
        self.total_docs = global_ctx.total_docs

    def term_df(self, field: str, term: str) -> int:
        return self._global.term_df(field, term)

    def field_avgdl(self, field: str) -> float:
        return self._global.field_avgdl(field)


class FixedStatsContext(ShardContext):
    """Shard context with externally-supplied term statistics (the
    cluster-level DFS phase: node-local stats are NOT comparable across
    nodes, so the search coordinator collects cluster-wide df/avgdl/doc
    counts first and pins them here). Terms absent from the table fall
    back to local stats — best effort for expansions (wildcards etc.) the
    stats round could not anticipate."""

    def __init__(self, segments, mapper, stats: dict):
        super().__init__(segments, mapper)
        self._stats = stats
        self.total_docs = int(stats.get("total_docs", self.total_docs))

    def term_df(self, field: str, term: str) -> int:
        df = self._stats.get("terms", {}).get(field, {}).get(term)
        if df is not None:
            return int(df)
        return super().term_df(field, term)

    def field_avgdl(self, field: str) -> float:
        fs = self._stats.get("fields", {}).get(field)
        if fs:
            sum_dl, doc_count = fs
            return sum_dl / doc_count if doc_count else 1.0
        return super().field_avgdl(field)


class _Desc:
    """Inverts comparisons for descending non-numeric sort keys."""

    __slots__ = ("v",)

    def __init__(self, v):
        self.v = v

    def __lt__(self, other):
        return other.v < self.v

    def __eq__(self, other):
        return self.v == other.v


def merge_sort_key(clauses: List[dict], sort_values: List[Any]) -> tuple:
    """Clause-aware coordinator merge key over a hit's raw sort values
    (``SearchPhaseController``'s cross-shard comparator): numbers negate
    for desc, strings wrap in a comparison-inverting proxy, None obeys the
    clause's missing-first/last placement."""
    parts = []
    for clause, v in zip(clauses, sort_values):
        desc = clause["order"] == "desc"
        missing_first = clause["missing"] == "_first"
        if v is None:
            parts.append((-1 if missing_first else 1, 0))
        elif isinstance(v, (int, float)) and not isinstance(v, bool):
            parts.append((0, -float(v) if desc else float(v)))
        else:
            parts.append((0, _Desc(v) if desc else v))
    return tuple(parts)


class DistributedSearcher:
    """Coordinating-node search over one searcher per shard."""

    def __init__(self, shard_segment_lists: List[list],
                 mapper: MapperService, plane_provider=None,
                 knn_plane_provider=None, fused_provider=None):
        all_segments = [s for segs in shard_segment_lists for s in segs]
        self._global_ctx = ShardContext(all_segments, mapper)
        self.mapper = mapper
        self.plane_provider = plane_provider
        self.knn_plane_provider = knn_plane_provider
        self.fused_provider = fused_provider
        self.shards: List[ShardSearcher] = []
        # flattened-filtered segment index -> (shard, shard-local filtered
        # segment): the pooled plane route returns hits in global-segment
        # space and must rewrite cursors into the coordinator's
        # (shard << _LOCAL_BITS | seg << 32 | doc) encoding
        self._seg_owner: List[Tuple[int, int]] = []
        for shard_idx, segs in enumerate(shard_segment_lists):
            searcher = ShardSearcher(segs, mapper,
                                     knn_plane_provider=knn_plane_provider)
            searcher.ctx = DfsShardContext(searcher.segments, mapper,
                                           self._global_ctx)
            self.shards.append(searcher)
            for li in range(len(searcher.segments)):
                self._seg_owner.append((shard_idx, li))

    # ------------------------------------------------------------------

    def search(self, body: Optional[dict] = None, *,
               collect_agg_inputs: bool = False) -> ShardSearchResult:
        """``collect_agg_inputs``: skip the global agg reduce and attach
        ``result.agg_inputs_by_shard`` — [(shard_searcher, agg_inputs)] —
        so an outer coordinator (the cluster tier) can reduce ONCE across
        nodes without re-executing the query phase."""
        body = body or {}
        if body.get("rank") and "rrf" in body["rank"]:
            # global-rank fusion: run pooled (see module docstring);
            # the fused provider rides along so a lowerable hybrid RRF
            # body serves as ONE planned dispatch over the pooled list
            pooled = ShardSearcher(
                self._global_ctx.segments, self.mapper,
                knn_plane_provider=self.knn_plane_provider,
                fused_provider=self.fused_provider)
            pooled.ctx = self._global_ctx
            return pooled.search(body)

        # plane route: when the tiered TPU plane can serve this body, run
        # POOLED over the flattened segment list — the plane is itself the
        # multi-shard scatter-gather (shard-ascending tie order == the
        # coordinator's merge order), so fanning out per index shard first
        # would only re-partition work the device mesh already partitions
        if self.plane_provider is not None and not collect_agg_inputs:
            from .plane_route import body_eligible, extract_bag_of_terms
            if body_eligible(body):
                ext = extract_bag_of_terms(body.get("query"), self.mapper)
                if ext is not None and self.plane_provider(
                        self._global_ctx.segments, ext[0]) is not None:
                    pooled = ShardSearcher(self._global_ctx.segments,
                                           self.mapper,
                                           plane_provider=self.plane_provider)
                    pooled.ctx = self._global_ctx
                    res = pooled.search(body)
                    for h in res.hits:
                        sh, li = self._seg_owner[h.seg_idx]
                        h.sort_values = [h.score, self._global_shard_doc(
                            sh, li, h.local_doc)]
                    return res

        size = int(body.get("size", 10))
        from_ = int(body.get("from", 0))
        track_total_hits = body.get("track_total_hits", True)
        aggs_spec = body.get("aggs") or body.get("aggregations")
        sort_spec = body.get("sort")
        search_after = body.get("search_after")
        use_field_sort = False
        clauses = None
        if sort_spec:
            clauses = self.shards[0]._normalize_sort(sort_spec) \
                if self.shards else []
            use_field_sort = bool(clauses) and \
                clauses[0]["field"] != "_score"

        shard_body = dict(body)
        shard_body["size"] = size + from_
        shard_body["from"] = 0
        shard_body.pop("aggs", None)
        shard_body.pop("aggregations", None)
        # suggesters run ONCE against the cross-shard term dictionaries
        # (per-shard suggestion option sets would diverge and not merge)
        suggest_spec = shard_body.pop("suggest", None)
        if aggs_spec:
            shard_body["aggs"] = aggs_spec          # parsed, inputs only
        if isinstance(track_total_hits, int) and not isinstance(
                track_total_hits, bool):
            shard_body["track_total_hits"] = True   # cap at the coordinator
            # the integer threshold means the caller accepts approximate
            # totals — preserve that intent for the shards' block-max
            # prune gating (the rewrite above would otherwise read as
            # "exact totals required" and force every shard eager); the
            # coordinator already merges per-shard "gte" relations
            shard_body.setdefault("prune", True)
        # shards append the implicit trailing _doc tiebreak themselves
        # (ShardSearcher._field_sorted_page) and return n_user+1 values
        n_user_sort = len(clauses) if clauses else 0

        # -- knn DFS phase: per-shard candidates → global top-k -------------
        knn_overrides = self._global_knn(body.get("knn"))

        # can_match pre-filter (CanMatchPreFilterSearchPhase.java:58): skip
        # shards whose numeric ranges cannot satisfy a required range
        # clause. Suppressed when aggregations are present (a global agg
        # must still see every shard) or knn runs (vector hits ignore the
        # query ranges).
        can_skip = not aggs_spec and not knn_overrides
        bounds = _required_ranges(body.get("query")) if can_skip else []
        self.last_skipped = 0

        per_shard: List[ShardSearchResult] = []
        empty = ShardSearchResult(total=0, total_relation="eq", hits=[],
                                  max_score=None)
        for shard_idx, shard in enumerate(self.shards):
            if bounds and not _shard_can_match(shard, bounds):
                self.last_skipped += 1
                per_shard.append(empty)
                continue
            sb = shard_body
            if search_after is not None:
                local_after = self._local_cursor_any(
                    search_after, shard_idx, use_field_sort, n_user_sort)
                sb = dict(shard_body)
                if local_after is not None:
                    sb["search_after"] = local_after
                else:
                    sb.pop("search_after", None)
            per_shard.append(shard.search(
                sb, collect_agg_inputs=True,
                knn_override=(knn_overrides[shard_idx]
                              if knn_overrides is not None else None)))

        # -- per-shard aggregation pre-collect (partial-failure scope) ------
        # an agg that errors on ONE shard (e.g. HDR percentiles meeting a
        # negative value) fails THAT shard — its hits drop, the request
        # survives with _shards.failures (the reference's
        # ShardSearchFailure semantics)
        shard_failures: List[dict] = []
        failed_shards: set = set()
        precollected = None
        aggs = None
        if aggs_spec and not collect_agg_inputs:
            from ..common.errors import ElasticsearchError
            aggs = parse_aggs(aggs_spec)
            need_scores = _tree_needs_scores(aggs)
            precollected = {}
            from .aggregations import PipelineAggregator, _collect_fn
            for shard_idx, (shard, r) in enumerate(zip(self.shards,
                                                       per_shard)):
                seg_scores = {seg.seg_id: sc
                              for seg, _, sc in (r.agg_inputs or [])
                              if sc is not None} if need_scores else {}
                ctx = AggregationContext(self.mapper,
                                         shard_ctx=shard.ctx,
                                         seg_scores=seg_scores)
                got: Dict[str, list] = {}
                try:
                    for name, agg in aggs.items():
                        if isinstance(agg, PipelineAggregator):
                            continue
                        fn = _collect_fn(agg, ctx)
                        got[name] = [fn(ctx, seg, mask)
                                     for seg, mask, _ in
                                     (r.agg_inputs or [])]
                except ElasticsearchError as e:
                    failed_shards.add(shard_idx)
                    shard_failures.append({
                        "shard": shard_idx, "node": None,
                        "reason": {"type": e.error_type,
                                   "reason": str(e)},
                        "status": e.status,
                        "_exc": e})
                    continue
                for name, parts in got.items():
                    precollected.setdefault(name, []).extend(parts)
            if failed_shards and not any(precollected.values()):
                # every data-bearing shard failed (empty shards succeed
                # vacuously): the request fails with the underlying
                # cause (the reference's SearchPhaseExecutionException
                # carries the cause's status — a 400 validation error
                # stays a 400)
                raise shard_failures[0]["_exc"]
            for f in shard_failures:
                f.pop("_exc", None)

        # -- totals ---------------------------------------------------------
        total = sum(r.total for i, r in enumerate(per_shard)
                    if i not in failed_shards)
        total_relation = "gte" if any(r.total_relation == "gte"
                                      for r in per_shard) else "eq"
        if isinstance(track_total_hits, int) and not isinstance(
                track_total_hits, bool) and total > track_total_hits:
            total = track_total_hits
            total_relation = "gte"

        # -- merge hits (SearchPhaseController.sortDocs) --------------------
        merged: List[Tuple[tuple, int, ShardHit]] = []
        for shard_idx, r in enumerate(per_shard):
            if shard_idx in failed_shards:
                continue
            for h in r.hits:
                merged.append((self._merge_key(clauses, use_field_sort,
                                               shard_idx, h),
                               shard_idx, h))
        merged.sort(key=lambda t: t[0])
        collapse_field = (body.get("collapse") or {}).get("field")
        if collapse_field:
            # shards collapsed locally; dedupe groups ACROSS shards too
            merged = collapse_first_by_key(
                merged, lambda t: (t[2].fields or {}).get(
                    collapse_field, [None])[0])
        page = merged[from_: from_ + size]
        hits: List[ShardHit] = []
        max_score = None
        for key, shard_idx, h in page:
            # rewrite the tiebreak into the GLOBAL shard-doc space so the
            # cursor round-trips across shards
            if not use_field_sort and h.score is not None:
                h.sort_values = [h.score, self._global_shard_doc(
                    shard_idx, h.seg_idx, h.local_doc)]
            elif use_field_sort and h.sort_values is not None and \
                    len(h.sort_values) == n_user_sort + 1:
                local_sd = int(h.sort_values[-1])
                h.sort_values = h.sort_values[:-1] + [
                    (shard_idx << _LOCAL_BITS) | local_sd]
            hits.append(h)
        scores = [r.max_score for r in per_shard if r.max_score is not None]
        if scores:
            max_score = max(scores)

        # -- one global aggregation reduce ----------------------------------
        agg_results = None
        agg_inputs_by_shard = None
        if aggs_spec and collect_agg_inputs:
            agg_inputs_by_shard = [(shard, r.agg_inputs or [])
                                   for shard, r in zip(self.shards,
                                                       per_shard)]
        elif aggs_spec:
            # partials were pre-collected per shard above (with failure
            # scoping); one shared reduce over the survivors
            agg_results = run_aggregations_multi(
                aggs, [], extra_partials=precollected or {})

        suggest_out = None
        if suggest_spec:
            from .suggest import run_suggest
            suggest_out = run_suggest(self._global_ctx, suggest_spec)
        profile_out = None
        if body.get("profile"):
            shards_prof = [sh for r in per_shard if r.profile
                           for sh in r.profile["shards"]]
            if shards_prof:
                profile_out = {"shards": shards_prof}

        result = ShardSearchResult(total=total,
                                   total_relation=total_relation,
                                   hits=hits, max_score=max_score,
                                   aggregations=agg_results,
                                   profile=profile_out,
                                   suggest=suggest_out,
                                   shard_failures=shard_failures or None)
        result.agg_inputs_by_shard = agg_inputs_by_shard
        return result

    def count(self, body: Optional[dict] = None) -> int:
        return sum(s.count(body) for s in self.shards)

    # ------------------------------------------------------------------

    @staticmethod
    def _global_shard_doc(shard_idx: int, seg_idx: int, doc: int) -> int:
        return (shard_idx << _LOCAL_BITS) | (seg_idx << 32) | doc

    def _global_knn(self, knn_spec):
        """knn DFS phase: each shard surfaces its local top-k per ranking,
        the coordinator keeps the GLOBAL top-k and hands each shard its
        slice (the reference's ``KnnSearchBuilder`` DFS round-trip —
        per-shard-k hybrid scoring would otherwise boost docs that are not
        global knn winners)."""
        if not knn_spec:
            return None
        specs = knn_spec if isinstance(knn_spec, list) else [knn_spec]
        overrides = [[[] for _ in specs] for _ in self.shards]
        for ri, spec in enumerate(specs):
            k = int(spec.get("k", 10))
            cands = []
            for si, shard in enumerate(self.shards):
                for sc, seg_idx, d in shard._knn_candidates(spec):
                    cands.append((sc, si, seg_idx, d))
            cands.sort(key=lambda c: (-c[0], c[1], c[2], c[3]))
            for sc, si, seg_idx, d in cands[:k]:
                overrides[si][ri].append((sc, seg_idx, d))
        return overrides

    @staticmethod
    def _local_cursor_any(search_after, shard_idx: int,
                          use_field_sort: bool, n_user_sort: int):
        """Rewrite a global cursor into the shard's local cursor (see
        module docstring). Returns None for 'no cursor on this shard'."""
        if not use_field_sort:
            if len(search_after) < 2:
                return list(search_after)
            a_score = search_after[0]
            gsd = int(search_after[1])
            cursor_shard = gsd >> _LOCAL_BITS
            local_sd = gsd & ((1 << _LOCAL_BITS) - 1)
            if shard_idx < cursor_shard:
                return [a_score]             # strictly below the score
            if shard_idx == cursor_shard:
                return [a_score, local_sd]   # local composite
            return [a_score, -1]             # ties allowed (after cursor)
        if len(search_after) == n_user_sort:
            # caller-built cursor without the implicit _shard_doc: the
            # shard applies legacy strict-tuple semantics itself
            return list(search_after)
        prefix = list(search_after[:-1])
        try:
            gsd = int(search_after[-1])
        except (OverflowError, ValueError):
            # inf sentinel from an upstream coordinator: strict everywhere
            return prefix + [float("inf")]
        if gsd < 0:
            return prefix + [-1.0]           # ties allowed everywhere
        cursor_shard = gsd >> _LOCAL_BITS
        local_sd = gsd & ((1 << _LOCAL_BITS) - 1)
        if shard_idx < cursor_shard:
            # equal-prefix rows must NOT pass: max _doc key
            return prefix + [float((1 << _LOCAL_BITS) - 1)]
        if shard_idx == cursor_shard:
            return prefix + [float(local_sd)]
        # equal-prefix rows all pass
        return prefix + [-1.0]

    def _merge_key(self, clauses, use_field_sort: bool, shard_idx: int,
                   h: ShardHit) -> tuple:
        tie = (shard_idx, h.seg_idx, h.local_doc)
        if not use_field_sort:
            score = h.score if h.score is not None else float("-inf")
            return (-score,) + tie
        return (merge_sort_key(clauses, h.sort_values or []),) + tie
