"""Additional bucket aggregations: composite, significant/rare terms,
sampler, nested/reverse_nested.

Reference counterparts:

- ``bucket/composite/CompositeAggregator.java`` — paginable multi-source
  buckets ordered by the natural source tuple order with ``after`` keys;
  here each source materializes a per-doc key column, the tuple key set
  builds vectorized per segment, and the reduce slices the globally-sorted
  tuple space (exact pagination, no coordinator approximation needed
  because partials carry every tuple past the cursor up to ``size`` per
  segment... sized by the same bound the reference uses).
- ``bucket/terms/SignificantTermsAggregator`` — foreground vs background
  counts scored by JLH (default) / chi-square / GND-style mutual
  information. Background = the whole shard (or a ``background_filter``).
- ``bucket/terms/RareTermsAggregator`` — long-tail terms with doc count
  at/below ``max_doc_count`` (exact per shard, merged exactly because
  partials keep full counts).
- ``bucket/sampler/SamplerAggregator`` — restrict sub-aggregations to the
  top ``shard_size`` scoring docs per shard.
- ``bucket/nested/NestedAggregator`` + ``ReverseNestedAggregator`` — hop
  the mask between the parent doc space and a nested path's hidden child
  docs (block-join arrays from ``index/segment.py``).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..common.errors import IllegalArgumentError, ParsingError
from .aggregations import (Aggregator, BucketAggregator, RangeAgg,
                           _bucket_payload, _keyword_pairs, _numeric_pairs,
                           _reduce_subs, _sub_results)


# ---------------------------------------------------------------------------
# composite
# ---------------------------------------------------------------------------


def _composite_interval(kind: str, cfg: dict) -> float:
    """Resolve a histogram/date_histogram source's bucket width in value
    space (millis for dates), accepting the ES interval spellings."""
    from .aggregations import _CALENDAR_INTERVALS, _parse_fixed_interval
    try:
        if kind == "histogram":
            return float(cfg["interval"])
        for key in ("fixed_interval", "interval"):
            v = cfg.get(key)
            if v is None:
                continue
            if isinstance(v, (int, float)):
                return float(v)
            return _parse_fixed_interval(str(v))
        cal = cfg.get("calendar_interval")
        if cal is not None:
            # calendar units approximate to fixed widths in the composite
            # key space (the reference's composite rounds the same way for
            # fixed units; month/year calendar rounding is approximated)
            unit = _CALENDAR_INTERVALS.get(cal, cal)
            return {"s": 1e3, "m": 6e4, "h": 3.6e6, "d": 8.64e7,
                    "w": 6.048e8, "M": 2.592e9, "q": 7.776e9,
                    "y": 3.1536e10}[unit]
        raise KeyError("interval")
    except (KeyError, TypeError, ValueError) as e:
        raise ParsingError(
            f"[composite] invalid interval for a [{kind}] source: "
            f"{cfg}") from e


class CompositeAgg(BucketAggregator):
    """Paginable multi-source buckets."""

    MAX_BUCKETS_CEILING = 65536

    def __init__(self, body: dict):
        if "sources" not in body:
            raise ParsingError("Required [sources]")
        sources = body.get("sources")
        if not sources or not isinstance(sources, list):
            raise ParsingError(
                "Composite [sources] cannot be null or empty")
        self.sources = []
        seen_names = set()
        dups = []
        for s in sources:
            if not isinstance(s, dict) or len(s) != 1:
                raise ParsingError(
                    "[composite] each source must be {name: {type: ...}}")
            (name, spec), = s.items()
            if name in seen_names:
                dups.append(name)
            seen_names.add(name)
            kinds = [k for k in ("terms", "histogram", "date_histogram",
                                 "geotile_grid")
                     if k in spec]
            if len(kinds) != 1:
                raise ParsingError(
                    f"[composite] source [{name}] must define exactly one "
                    f"of terms/histogram/date_histogram/geotile_grid")
            kind = kinds[0]
            cfg = spec[kind]
            self.sources.append({
                "name": name, "kind": kind,
                "field": cfg.get("field"),
                "interval": (_composite_interval(kind, cfg)
                             if kind in ("histogram", "date_histogram")
                             else None),
                "order": cfg.get("order", "asc"),
                "format": cfg.get("format"),
                "time_zone": cfg.get("time_zone"),
                "offset": cfg.get("offset"),
                "precision": int(cfg.get("precision", 7)),
                "calendar": cfg.get("calendar_interval"),
            })
        if dups:
            raise IllegalArgumentError(
                f"Composite source names must be unique, found "
                f"duplicates: [{','.join(sorted(set(dups)))}]")
        from .aggregations import MAX_BUCKETS
        self.size = int(body.get("size", 10))
        if self.size > MAX_BUCKETS[0]:
            raise IllegalArgumentError(
                f"Trying to create too many buckets. Must be less than or "
                f"equal to: [{MAX_BUCKETS[0]}] but was [{self.size}]. "
                f"This limit can be set by changing the "
                f"[search.max_buckets] cluster level setting.")
        self.after = body.get("after")

    def _render_key_value(self, src, v):
        from ..index.mapping import format_date_millis
        if src["kind"] == "date_histogram" and isinstance(v, (int, float)):
            if src["format"] == "iso8601" or (
                    src["format"] is None and src.get("time_zone")):
                tz = src.get("time_zone")
                if tz:
                    from .aggregations import _tz_offset_ms
                    off = _tz_offset_ms(tz, float(v))
                    base = format_date_millis(float(v) + off)[:-1]
                    sign = "+" if off >= 0 else "-"
                    o = abs(int(off)) // 60000
                    return f"{base}{sign}{o // 60:02d}:{o % 60:02d}"
                return format_date_millis(float(v))
            if src["format"]:
                from .fetch import java_date_format
                return java_date_format(float(v), src["format"])
            mapper = getattr(self, "_mapper", None)
            ft = mapper.field_type(src["field"]) if mapper else None
            from ..index.mapping import DateFieldType
            if isinstance(ft, DateFieldType) and ft.nanos:
                return format_date_millis(float(v))
            return int(v)
        if isinstance(v, float) and v.is_integer():
            return int(v)
        return v

    def _parse_after_value(self, src, v):
        if src["kind"] == "date_histogram" and isinstance(v, str):
            import re as _re
            from ..index.mapping import parse_date_millis
            try:
                ms = float(parse_date_millis(v))
            except Exception:   # noqa: BLE001
                return v
            # a cursor without an explicit zone reads in the SOURCE's tz
            if src.get("time_zone") and not _re.search(
                    r"(Z|[+-]\d{2}:?\d{2})$", v):
                from .aggregations import _tz_offset_ms
                ms -= _tz_offset_ms(src["time_zone"], ms)
            return ms
        return v

    # -- per-source key values ----------------------------------------------

    def _key_values(self, seg, src) -> list:
        """per-doc LIST of keys (every value of a multi-valued field forms
        its own combination — CompositeValuesSourceBuilder semantics);
        empty list = missing, excluded like the reference default."""
        n = seg.n_docs
        col = [[] for _ in range(n)]
        if src["kind"] == "geotile_grid":
            la = seg.numeric_fields.get(f"{src['field']}._lat")
            lo = seg.numeric_fields.get(f"{src['field']}._lon")
            if la is not None and lo is not None:
                from .aggs_geo import geotile_key
                for d, lat, lon in zip(la.docs_host, la.vals_host,
                                       lo.vals_host):
                    col[int(d)].append(
                        geotile_key(lat, lon, src["precision"]))
            return col
        if src["kind"] == "terms":
            kw = _keyword_pairs(seg, src["field"])
            if kw is not None:
                docs, ords, terms = kw
                for d, o in zip(docs, ords):
                    col[int(d)].append(terms[int(o)])
                return col
        num = _numeric_pairs(seg, src["field"])
        if num is not None:
            docs, vals = num
            if src["kind"] == "terms":
                for d, v in zip(docs, vals):
                    col[int(d)].append(float(v))
            else:
                iv = src["interval"]
                shift = 0.0
                if src.get("offset"):
                    from .aggregations import _parse_offset_ms
                    shift += _parse_offset_ms(src["offset"])
                if src.get("time_zone") and vals.size:
                    from .aggregations import _tz_offset_ms
                    shift -= _tz_offset_ms(src["time_zone"],
                                           float(vals[0]))
                cal = src.get("calendar")
                if cal is not None and src["kind"] == "date_histogram":
                    # true calendar rounding (weeks start Monday, months/
                    # quarters/years at their calendar boundary) — same
                    # rule as the standalone date_histogram
                    from .aggregations import (_CALENDAR_INTERVALS,
                                               _calendar_floor)
                    unit = _CALENDAR_INTERVALS.get(cal, cal)
                    keys = _calendar_floor(
                        np.asarray(vals, np.float64) - shift, unit) + shift
                    for d, k in zip(docs, keys):
                        col[int(d)].append(float(k))
                else:
                    for d, v in zip(docs, vals):
                        col[int(d)].append(
                            float(np.floor((v - shift) / iv) * iv + shift))
        # dedupe per doc, preserving order
        return [list(dict.fromkeys(c)) for c in col]

    def collect(self, ctx, seg, mask):
        import itertools as _it
        self._mapper = ctx.mapper
        docs_mask = mask[: seg.n_docs]
        cols = [self._key_values(seg, s) for s in self.sources]
        idx = np.flatnonzero(docs_mask)
        buckets: Dict[tuple, Tuple[int, dict]] = {}
        by_key_docs: Dict[tuple, List[int]] = {}
        for d in idx:
            per_source = [c[d] for c in cols]
            if any(not vs for vs in per_source):
                continue
            for key in _it.product(*per_source):
                by_key_docs.setdefault(key, []).append(int(d))
        from .aggregations import _doc_weights
        w = _doc_weights(seg)
        for key, ds in by_key_docs.items():
            n = len(ds) if w is None else int(w[ds].sum())
            if self.subs:
                bm = np.zeros(mask.shape[0], bool)
                bm[ds] = True
                sub = _bucket_payload(self, ctx, seg, bm)[1]
                buckets[key] = (n, sub)
            else:
                buckets[key] = (n, {})
        return buckets

    def _tuple_sort_key(self, key: tuple):
        parts = []
        for v, src in zip(key, self.sources):
            desc = src["order"] == "desc"
            if src["kind"] == "geotile_grid" and isinstance(v, str):
                z, x, y = (int(t) for t in v.split("/"))
                t3 = (z, x, y)
                parts.append((0, tuple(-c for c in t3) if desc else t3))
            elif isinstance(v, str):
                parts.append((1, _RevStr(v) if desc else v))
            else:
                parts.append((0, -float(v) if desc else float(v)))
        return tuple(parts)

    def reduce(self, partials):
        merged: Dict[tuple, List] = {}
        for p in partials:
            for key, item in p.items():
                merged.setdefault(key, []).append(item)
        keys = sorted(merged, key=self._tuple_sort_key)
        if self.after is not None:
            missing = [s["name"] for s in self.sources
                       if s["name"] not in self.after]
            if missing:
                raise ParsingError(
                    f"[composite] after key is missing sources {missing}")
            after_key = tuple(
                self._parse_after_value(s, self.after[s["name"]])
                for s in self.sources)
            ak = self._tuple_sort_key(after_key)
            keys = [k for k in keys if self._tuple_sort_key(k) > ak]
        page = keys[: self.size]
        buckets = []
        for key in page:
            items = merged[key]
            count = sum(c for c, _ in items)
            b = {"key": {s["name"]: self._render_key_value(s, v)
                         for s, v in zip(self.sources, key)},
                 "doc_count": count}
            if self.subs:
                b.update(_reduce_subs(self, [s for _, s in items]))
            buckets.append(b)
        out = {"buckets": buckets}
        if page:
            out["after_key"] = {
                s["name"]: self._render_key_value(s, v)
                for s, v in zip(self.sources, page[-1])}
        return out


class _RevStr:
    __slots__ = ("v",)

    def __init__(self, v):
        self.v = v

    def __lt__(self, other):
        return other.v < self.v

    def __eq__(self, other):
        return self.v == other.v

    def __gt__(self, other):
        return other.v > self.v


# ---------------------------------------------------------------------------
# significant_terms / rare_terms
# ---------------------------------------------------------------------------


def _jlh(fg, fg_total, bg, bg_total) -> float:
    if fg == 0 or fg_total == 0 or bg_total == 0:
        return 0.0
    fg_pct = fg / fg_total
    bg_pct = bg / bg_total if bg_total else 0.0
    if fg_pct <= bg_pct or bg_pct == 0:
        return 0.0
    return (fg_pct - bg_pct) * (fg_pct / bg_pct)


def _chi_square(fg, fg_total, bg, bg_total) -> float:
    # 2x2 contingency chi-square with the reference's
    # include_negatives=false default
    a, b = fg, bg - fg if bg >= fg else 0
    c, d = fg_total - fg, max(bg_total - bg - (fg_total - fg), 0)
    n = a + b + c + d
    if n == 0 or (a + b) == 0 or (c + d) == 0 or (a + c) == 0 or \
            (b + d) == 0:
        return 0.0
    num = n * (a * d - b * c) ** 2
    den = (a + b) * (c + d) * (a + c) * (b + d)
    score = num / den
    if (a / (a + c) if a + c else 0) < (b / (b + d) if b + d else 0):
        return 0.0
    return score


def _check_regex_include_exclude(agg, mapper) -> None:
    """Regex-form include/exclude is string-fields-only
    (``IncludeExclude`` builds a LongFilter for numerics and rejects
    regex): shared by rare_terms and significant_terms."""
    if isinstance(agg.include, str) or isinstance(agg.exclude, str):
        from .aggregations import _field_type
        from ..index.mapping import KeywordFieldType, TextFieldType
        ft = _field_type(mapper, agg.field)
        if ft is not None and not isinstance(
                ft, (KeywordFieldType, TextFieldType)):
            raise IllegalArgumentError(
                f"Aggregation [{getattr(agg, 'name', agg.field)}] "
                f"cannot support regular expression style "
                f"include/exclude settings as they can only be "
                f"applied to string fields. Use an array of values "
                f"for include/exclude clauses")


def _include_exclude_passes(agg, key, inc_set, exc_set) -> bool:
    """One term against the agg's include/exclude (list sets are
    pre-coerced by the caller; strings are anchored regexes)."""
    import re as _re
    inc, exc = agg.include, agg.exclude
    if inc_set is not None and key not in inc_set:
        return False
    if isinstance(inc, str) and _re.fullmatch(inc, str(key)) is None:
        return False
    if exc_set is not None and key in exc_set:
        return False
    if isinstance(exc, str) and \
            _re.fullmatch(exc, str(key)) is not None:
        return False
    return True


class SignificantTermsAgg(BucketAggregator):
    KNOWN_PARAMS = {"field", "size", "shard_size", "min_doc_count",
                    "shard_min_doc_count", "background_filter", "jlh",
                    "chi_square", "gnd", "mutual_information",
                    "percentage", "script_heuristic", "include", "exclude",
                    "execution_hint", "filter_duplicate_text",
                    "source_fields"}

    def __init__(self, body: dict):
        import difflib
        for k in body:
            if k not in self.KNOWN_PARAMS:
                hint = difflib.get_close_matches(
                    k, sorted(self.KNOWN_PARAMS), n=1)
                suffix = f" did you mean [{hint[0]}]?" if hint else ""
                raise IllegalArgumentError(
                    f"[significant_terms] unknown field [{k}]{suffix}")
        self.field = body.get("field")
        if self.field is None:
            raise ParsingError("significant_terms requires [field]")
        self.size = int(body.get("size", 10))
        self.min_doc_count = int(body.get("min_doc_count", 3))
        self.heuristic = "chi_square" if "chi_square" in body else "jlh"
        self.background_filter = body.get("background_filter")
        self.include = body.get("include")
        self.exclude = body.get("exclude")
        self._inc_set = self._exc_set = None    # built lazily, once
        #: per-segment background stats, accumulated OUTSIDE the bucket
        #: partials: under a bucketing parent, collect only runs for
        #: (segment, bucket) pairs where the bucket exists, but the
        #: background population must span every segment seen. Every
        #: partial carries a reference to this dict so the stats survive
        #: pickling to a coordinating node (the reducing instance over
        #: there is a FRESH parse with an empty dict of its own).
        self._seg_bg: Dict[str, tuple] = {}

    def _bg_token(self, seg) -> str:
        """Segment identity for background dedup. seg_id ('_0', '_1')
        recurs across shards and indices, so segments get stamped with
        a process-unique token that also disambiguates across nodes."""
        tok = getattr(seg, "_sig_bg_token", None)
        if tok is None:
            import uuid
            tok = uuid.uuid4().hex
            seg._sig_bg_token = tok
        return tok

    def _bg_mask(self, ctx, seg, mask):
        if self.background_filter is not None:
            from .query_dsl import parse_query
            _, bgm = parse_query(self.background_filter).execute(
                ctx.shard_ctx, seg)
            return np.asarray(bgm)[: mask.shape[0]] & \
                _live_parents(seg, mask.shape[0])
        return _live_parents(seg, mask.shape[0])

    def _collect_text(self, ctx, seg, mask, f):
        """Postings-CSR path: per-term fg doc counts by bincount over
        posting term-ids (text fields have no doc-values column)."""
        v = len(f.term_ids)
        tid = np.repeat(np.arange(v, dtype=np.int64),
                        np.diff(f.offsets).astype(np.int64))
        terms_sorted = list(f.term_ids)
        tok = self._bg_token(seg)
        if tok not in self._seg_bg:
            bg_mask = self._bg_mask(ctx, seg, mask)
            bg = np.bincount(tid[bg_mask[f.docs_host]], minlength=v)
            self._seg_bg[tok] = (
                int(bg_mask[: seg.n_docs].sum()),
                {terms_sorted[i]: int(bg[i]) for i in np.flatnonzero(bg)})
        fg = np.bincount(tid[mask[f.docs_host]], minlength=v)
        t = {}
        for i in np.flatnonzero(fg):
            t[terms_sorted[i]] = int(fg[i])
        return {"fg_total": int(mask[: seg.n_docs].sum()), "terms": t,
                "seg_bg": self._seg_bg}

    def _key_passes(self, key) -> bool:
        # sig-terms keys are always strings (keyword/text sources),
        # so list include/exclude needs no field-type coercion
        if self._inc_set is None and isinstance(self.include, list):
            self._inc_set = set(self.include)
        if self._exc_set is None and isinstance(self.exclude, list):
            self._exc_set = set(self.exclude)
        return _include_exclude_passes(self, key, self._inc_set,
                                       self._exc_set)

    def collect(self, ctx, seg, mask):
        _check_regex_include_exclude(self, ctx.mapper)
        kw = _keyword_pairs(seg, self.field)
        if kw is None:
            field = self.field
            ft = ctx.mapper.field_type(field) if ctx.mapper else None
            if ft is not None and ft.name != field:
                field = ft.name
            f = seg.text_fields.get(field)
            if f is not None:
                return self._collect_text(ctx, seg, mask, f)
            # field-less segment: its docs still belong to both the
            # foreground and the background populations
            tok = self._bg_token(seg)
            if tok not in self._seg_bg:
                self._seg_bg[tok] = (
                    int(_live_parents(
                        seg, mask.shape[0])[: seg.n_docs].sum()), {})
            return {"fg_total": int(mask[: seg.n_docs].sum()),
                    "terms": {}, "seg_bg": self._seg_bg}
        docs, ords, terms = kw
        tok = self._bg_token(seg)
        if tok not in self._seg_bg:
            bg_mask = self._bg_mask(ctx, seg, mask)
            bg_ords, bg_counts = np.unique(ords[bg_mask[docs]],
                                           return_counts=True)
            self._seg_bg[tok] = (
                int(bg_mask[: seg.n_docs].sum()),
                {terms[o]: int(c) for o, c in
                 zip(bg_ords.tolist(), bg_counts.tolist())})
        pm_fg = mask[docs]
        fg_ords, fg_counts = np.unique(ords[pm_fg], return_counts=True)
        t = {}
        for o, c in zip(fg_ords.tolist(), fg_counts.tolist()):
            t[terms[o]] = c
        return {"fg_total": int(mask[: seg.n_docs].sum()), "terms": t,
                "seg_bg": self._seg_bg}

    def reduce(self, partials):
        fg_total = sum(p["fg_total"] for p in partials)
        # union background stats: the local instance dict plus whatever
        # rode in on (possibly remote) partials, deduped by seg token
        seen = dict(self._seg_bg)
        for p in partials:
            seen.update(p.get("seg_bg") or {})
        bg_total = sum(t for t, _ in seen.values())
        bg_of: Dict[str, int] = {}
        for _, bmap in seen.values():
            for term, c in bmap.items():
                bg_of[term] = bg_of.get(term, 0) + c
        merged: Dict[str, int] = {}
        for p in partials:
            for term, fg in p["terms"].items():
                merged[term] = merged.get(term, 0) + fg
        score_fn = _chi_square if self.heuristic == "chi_square" else _jlh
        rows = []
        for term, fg in merged.items():
            bg = bg_of.get(term, 0)
            if fg < self.min_doc_count or not self._key_passes(term):
                continue
            score = score_fn(fg, fg_total, bg, bg_total)
            if score > 0:
                rows.append((score, term, fg, bg))
        rows.sort(key=lambda r: (-r[0], r[1]))
        return {"doc_count": fg_total,
                "bg_count": bg_total,
                "buckets": [{"key": t, "doc_count": fg, "score": s,
                             "bg_count": bg}
                            for s, t, fg, bg in rows[: self.size]]}


class RareTermsAgg(BucketAggregator):
    def __init__(self, body: dict):
        self.field = body.get("field")
        if self.field is None:
            raise ParsingError("rare_terms requires [field]")
        self.max_doc_count = int(body.get("max_doc_count", 1))
        if not 1 <= self.max_doc_count <= 100:
            raise IllegalArgumentError(
                "[max_doc_count] must be in [1, 100]")
        self.include = body.get("include")
        self.exclude = body.get("exclude")
        self._inc_set = self._exc_set = None    # coerced lazily, once

    def _coerce(self, vals):
        """include/exclude values → key space via the field type (dates
        parse to epoch millis, ips canonicalize, numerics to float)."""
        from .aggregations import _field_type
        from ..index.mapping import (BooleanFieldType, DateFieldType,
                                     IpFieldType, NumberFieldType,
                                     parse_date_millis)
        ft = _field_type(getattr(self, "_mapper", None), self.field)
        out = set()
        for v in vals:
            try:
                if isinstance(ft, DateFieldType):
                    v = float(parse_date_millis(v, ft.format))
                elif isinstance(ft, BooleanFieldType):
                    v = 1.0 if v in (True, "true") else 0.0
                elif isinstance(ft, NumberFieldType):
                    v = float(v)
            except Exception:   # noqa: BLE001 — keep raw on failure
                pass
            out.add(v)
        return out

    def _included(self, key) -> bool:
        if self._inc_set is None and isinstance(self.include, list):
            self._inc_set = self._coerce(self.include)
        if self._exc_set is None and isinstance(self.exclude, list):
            self._exc_set = self._coerce(self.exclude)
        return _include_exclude_passes(self, key, self._inc_set,
                                       self._exc_set)

    def collect(self, ctx, seg, mask):
        self._mapper = ctx.mapper
        _check_regex_include_exclude(self, ctx.mapper)
        buckets: Dict[Any, tuple] = {}
        kw = _keyword_pairs(seg, self.field)
        if kw is not None:
            docs, ords, terms = kw
            pm = mask[docs]
            sel, counts = np.unique(ords[pm], return_counts=True)
            for o, c in zip(sel.tolist(), counts.tolist()):
                sub = {}
                if self.subs:
                    bm = np.zeros(mask.shape[0], bool)
                    bm[docs[pm][ords[pm] == o]] = True
                    sub = _bucket_payload(self, ctx, seg, bm)[1]
                buckets[terms[o]] = (c, sub)
        else:
            num = _numeric_pairs(seg, self.field, ctx.mapper)
            if num is not None:
                docs, vals = num
                pm = mask[docs]
                sel, counts = np.unique(vals[pm], return_counts=True)
                for v, c in zip(sel.tolist(), counts.tolist()):
                    sub = {}
                    if self.subs:
                        bm = np.zeros(mask.shape[0], bool)
                        bm[docs[pm][vals[pm] == v]] = True
                        sub = _bucket_payload(self, ctx, seg, bm)[1]
                    buckets[v] = (c, sub)
        return buckets

    def reduce(self, partials):
        from .aggregations import _reduce_subs, _field_type
        from ..index.mapping import BooleanFieldType, DateFieldType
        merged: Dict[Any, list] = {}
        for p in partials:
            for term, item in p.items():
                cur = merged.setdefault(term, [0, []])
                cur[0] += item[0]
                cur[1].append(item[1])
        rows = [(t, c, subs) for t, (c, subs) in merged.items()
                if c <= self.max_doc_count and self._included(t)]
        rows.sort(key=lambda r: (r[1], str(r[0])))
        mapper = getattr(self, "_mapper", None)
        ft = _field_type(mapper, self.field) if mapper else None
        out = []
        for t, c, subs in rows:
            key = int(t) if isinstance(t, float) and t.is_integer() else t
            b = {"key": key, "doc_count": c}
            if isinstance(ft, BooleanFieldType):
                b["key_as_string"] = "true" if key else "false"
            elif isinstance(ft, DateFieldType):
                from ..index.mapping import format_date_millis
                b["key_as_string"] = format_date_millis(float(t))
            if self.subs:
                b.update(_reduce_subs(self, subs))
            out.append(b)
        return {"buckets": out}


# ---------------------------------------------------------------------------
# sampler
# ---------------------------------------------------------------------------


class SamplerAgg(BucketAggregator):
    """Sub-aggregations over only the top ``shard_size`` scoring docs per
    shard (needs per-segment scores from the query phase)."""

    def __init__(self, body: dict):
        self.shard_size = int(body.get("shard_size", 100))

    def collect(self, ctx, seg, mask):
        scores = ctx.seg_scores.get(seg.seg_id)
        docs_mask = mask[: seg.n_docs]
        idx = np.flatnonzero(docs_mask)
        if scores is not None and idx.size > self.shard_size:
            sc = scores[: seg.n_docs][idx]
            keep = idx[np.argsort(-sc, kind="stable")[: self.shard_size]]
        else:
            keep = idx[: self.shard_size]
        sm = np.zeros(mask.shape[0], bool)
        sm[keep] = True
        return (int(sm.sum()), _sub_results(self, ctx, seg, sm))

    def reduce(self, partials):
        count = sum(c for c, _ in partials)
        out = {"doc_count": count}
        out.update(_reduce_subs(self, [s for _, s in partials]))
        return out


# ---------------------------------------------------------------------------
# nested / reverse_nested
# ---------------------------------------------------------------------------


def _live_parents(seg, n) -> np.ndarray:
    m = np.zeros(n, bool)
    m[: seg.n_docs] = seg.live
    if seg.has_nested:
        m[: seg.n_docs] &= seg.parent_mask
    return m


class NestedAgg(BucketAggregator):
    """Hop the mask from parent docs DOWN to their ``path`` children:
    sub-aggregations then run in the child doc space, where the
    ``path.field`` doc values live."""

    def __init__(self, body: dict):
        self.path = body.get("path")
        if self.path is None:
            raise ParsingError("nested aggregation requires [path]")

    def collect(self, ctx, seg, mask):
        n = mask.shape[0]
        child_mask = np.zeros(n, bool)
        pm = seg.nested_paths.get(self.path)
        if pm is not None:
            child_idx = np.flatnonzero(pm & seg.live[: seg.n_docs])
            parents = seg.parent_of[child_idx]
            keep = mask[parents]
            child_mask[child_idx[keep]] = True
        return (int(child_mask.sum()),
                _sub_results(self, ctx, seg, child_mask))

    def reduce(self, partials):
        count = sum(c for c, _ in partials)
        out = {"doc_count": count}
        out.update(_reduce_subs(self, [s for _, s in partials]))
        return out


class ReverseNestedAgg(BucketAggregator):
    """Inside a ``nested`` agg: hop the (child-space) mask back UP to the
    parent documents."""

    def __init__(self, body: dict):
        self.path = body.get("path")     # None → all the way to the root

    def collect(self, ctx, seg, mask):
        n = mask.shape[0]
        up = np.zeros(n, bool)
        idx = np.flatnonzero(mask[: seg.n_docs])
        if idx.size:
            parents = idx.copy()
            # climb until the target level: root (parent_mask) or the
            # docs belonging to self.path
            target = (seg.nested_paths.get(self.path)
                      if self.path is not None else None)
            for _ in range(8):           # nesting depth bound
                at_target = seg.parent_mask[parents] if target is None \
                    else target[parents]
                done = parents[at_target]
                up[done] = True
                rest = parents[~at_target]
                if rest.size == 0:
                    break
                parents = seg.parent_of[rest]
        return (int(up.sum()), _sub_results(self, ctx, seg, up))

    def reduce(self, partials):
        count = sum(c for c, _ in partials)
        out = {"doc_count": count}
        out.update(_reduce_subs(self, [s for _, s in partials]))
        return out


class DateRangeAgg(RangeAgg):
    """date_range (reference: ``bucket/range/DateRangeAggregationBuilder``):
    bounds parse through the FIELD's date format (epoch_second bounds are
    seconds, not millis) and keys render with it; date-math bounds
    supported via parse_date_millis."""

    def __init__(self, body):
        super().__init__(body)
        self.format = body.get("format")
        self._ffmt = None               # field format, stashed at collect

    def _resolve(self, ctx):
        from ..index.mapping import DateFieldType
        ft = ctx.mapper.field_type(self.field)
        if isinstance(ft, DateFieldType):
            self._ffmt = ft.format

    def _field_fmt(self):
        """Field date format: stashed at collect (_resolve), or derived
        from the injected mapper when reducing REMOTE partials (the
        coordinator never ran collect — see inject_mapper)."""
        if self._ffmt is None:
            mapper = getattr(self, "_mapper", None)
            if mapper is not None:
                from ..index.mapping import DateFieldType
                ft = mapper.field_type(self.field)
                if isinstance(ft, DateFieldType):
                    self._ffmt = ft.format
        return self._ffmt

    def _bounds_salt(self):
        return self.format or self._field_fmt()

    def _parse_bound(self, v, which: str) -> float:
        from ..index.mapping import parse_date_millis
        fmt = self.format or self._field_fmt() or \
            "strict_date_optional_time||epoch_millis"
        return float(parse_date_millis(v, fmt))

    def _format_bound(self, v: float):
        return v

    def _fmt_ms(self, ms: float) -> str:
        from ..index.mapping import format_date_millis
        fmt = (self.format or self._field_fmt() or "").split("||")[0]
        if fmt == "epoch_second":
            return str(int(ms // 1000))
        if fmt == "epoch_millis":
            return str(int(ms))
        if fmt and not fmt.startswith("strict_date_optional_time"):
            from .fetch import java_date_format
            return java_date_format(ms, fmt)
        return format_date_millis(ms)

    def _range_key(self, r) -> str:
        if "key" in r:
            return r["key"]
        lo, hi = self._bounds(r)
        f = "*" if lo is None else self._fmt_ms(lo)
        t = "*" if hi is None else self._fmt_ms(hi)
        return f"{f}-{t}"


class IpRangeAgg(RangeAgg):
    """ip_range (reference: ``bucket/range/IpRangeAggregationBuilder``):
    bounds are addresses or CIDR masks over the ip field's numeric
    column."""

    def __init__(self, body):
        ranges = []
        for r in body.get("ranges") or []:
            if "mask" in r:
                from ..index.mapping import IpFieldType
                bounds = IpFieldType.cidr_bounds(r["mask"])
                if bounds is None:
                    raise ParsingError(
                        f"[ip_range] invalid mask [{r['mask']}]")
                lo, hi = bounds
                r = dict(r, **{"from": lo, "to": hi + 1,
                               "key": r.get("key", r["mask"])})
                r.pop("mask")
            ranges.append(r)
        super().__init__(dict(body, ranges=ranges))

    def _parse_bound(self, v, which: str) -> float:
        if isinstance(v, (int, float)):
            return float(v)
        import ipaddress
        return float(int(ipaddress.ip_address(str(v))))

    def _format_bound(self, v: float):
        import ipaddress
        if 0 <= v < 2 ** 32:
            return str(ipaddress.IPv4Address(int(v)))
        if v < 2 ** 128:
            return str(ipaddress.IPv6Address(int(v)))
        return float(v)                  # past the address space (mask /0)


class _JoinBucketAgg(BucketAggregator):
    """Shared machinery of the parent-join ``children`` / ``parent``
    single-bucket aggregations (reference: ``modules/parent-join/...
    aggregations/ChildrenAggregator.java`` / ``ParentAggregator``)."""

    def __init__(self, body: dict):
        self.rel_type = body.get("type")
        if self.rel_type is None:
            raise ParsingError(
                f"Missing [type] for [{self.kind}] aggregation")

    def _transform(self, ctx, seg, mask) -> np.ndarray:
        from .query_dsl import _join_field, _kw_values_by_doc
        out = np.zeros(seg.n_pad, bool)
        jf = _join_field(ctx)
        if jf is None or jf.parent_rel_of(self.rel_type) is None:
            return out
        parent_rel = jf.parent_rel_of(self.rel_type)
        rels = _kw_values_by_doc(seg, jf.name)
        fam = _kw_values_by_doc(seg, jf.id_field_for(self.rel_type))
        if self.kind == "children":
            # parents in the bucket -> their child docs of rel_type
            bucket_ids = {seg.doc_uids[d]
                          for d in np.flatnonzero(mask[: seg.n_docs])
                          if rels.get(d) == parent_rel}
            for d, pid in fam.items():
                if rels.get(d) == self.rel_type and pid in bucket_ids \
                        and seg.live[d]:
                    out[d] = True
        else:
            # child docs in the bucket -> their parent docs
            pids = {pid for d, pid in fam.items()
                    if mask[d] and rels.get(d) == self.rel_type}
            for pid in pids:
                d = seg.find_doc(pid)
                if d is not None and rels.get(d) == parent_rel and \
                        seg.live[d]:
                    out[d] = True
        return out

    def collect(self, ctx, seg, mask):
        bm = self._transform(ctx, seg, mask)
        if self.subs:
            return _bucket_payload(self, ctx, seg, bm)
        return (int(bm.sum()), {})

    def reduce(self, partials):
        count = sum(c for c, _ in partials)
        out = {"doc_count": count}
        if self.subs:
            out.update(_reduce_subs(self, [s for _, s in partials]))
        return out


class ChildrenAgg(_JoinBucketAgg):
    kind = "children"


class ParentAgg(_JoinBucketAgg):
    #: "type" names the CHILD relation whose parents we bucket
    kind = "parent"


# self-registration: runs after this module's classes exist, against the
# fully-initialized (or at least _AGG_PARSERS-bearing) aggregations module
from .aggregations import _AGG_PARSERS      # noqa: E402

_AGG_PARSERS.update({
    "date_range": DateRangeAgg,
    "ip_range": IpRangeAgg,
    "composite": CompositeAgg,
    "significant_terms": SignificantTermsAgg,
    "rare_terms": RareTermsAgg,
    "sampler": SamplerAgg,
    "nested": NestedAgg,
    "reverse_nested": ReverseNestedAgg,
    "children": ChildrenAgg,
    "parent": ParentAgg,
})
