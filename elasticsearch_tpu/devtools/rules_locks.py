"""Rule family 2 — lock-order safety (ESTP-L*).

Sixteen modules hold locks: dispatcher threads (``search/microbatch``),
the background repack thread (``search/plane_route``), refresh
listeners, the task ledger (``node/task_manager``)… A lock-order
inversion between any two of them is a deadlock that only fires under
production interleavings. These rules extract the package-wide
lock-acquisition graph syntactically and keep it cycle-free at the AST;
the opt-in runtime witness (``common/lockdep.py``, ``ES_TPU_LOCKDEP=1``)
cross-checks the same property against *observed* acquisition order at
test time, so the static graph and the runtime evidence must agree.

- **ESTP-L01 lock-order-cycle** — a cycle in the "held → acquired"
  graph: lock B is ever taken while A is held *and* (possibly through
  call edges and other locks) A while B is held. Every edge is
  annotated with the acquisition site that witnesses it.
- **ESTP-L02 telemetry-under-serving-lock** — code reachable while a
  serving lock is held (dispatcher queue lock, generation registry,
  delta swap, task ledger) must never call into ``common/telemetry`` /
  ``common/tracing``: a collector snapshot or exposition scrape
  contending a metric lock must not be able to stall a dispatch, and a
  telemetry-layer slowdown must never back up the serving path.

Lock identity is per *declaration site* (``module:Class.attr``,
``module:var``), not per instance — the same granularity the runtime
witness uses, so their graphs line up. Two conditions built over one
underlying lock (the microbatcher's ``_cond``/``_work``) collapse into
that lock's node. Resolution is conservative: ``self.X`` resolves
through the project MRO; a bare ``obj.X`` resolves only when the
attribute name is project-unique; everything else contributes no node
(documented limitation — see STATIC_ANALYSIS.md).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .analyzer import Finding, FunctionInfo, Project, _unparse

RULE_L01 = "ESTP-L01"
RULE_L02 = "ESTP-L02"

#: modules whose locks guard the serving path (family-2 rule L02);
#: matched as a dotted suffix so fixture packages work unprefixed
SERVING_LOCK_MODULES = re.compile(
    r"(^|\.)(search\.(microbatch|plane_route)|parallel\.dist_search"
    r"|node\.(task_manager|indices_service))$")

#: attrs excluded from the serving set even in serving modules (metric
#: bookkeeping locks are telemetry-side by design)
_NON_SERVING_ATTR = re.compile(r"metric")

#: flightrec counts as telemetry for L02: a flight-recorder journal
#: write under a serving lock would back serving up behind the
#: observability layer exactly like a registry write would — as do the
#: dispatch-timeline profiler ring (``search/dispatch_profile``) and
#: the roofline auditor (``common/roofline``), both written once per
#: dispatch from the dispatcher loop
TELEMETRY_MODULES = re.compile(
    r"(^|\.)(common\.(telemetry|tracing|flightrec|roofline"
    r"|metrics_history|contprof)"
    r"|search\.(dispatch_profile|plane_tiers|query_insight))$")

_LOCK_CTORS = {"Lock", "RLock"}

#: saved-real-factory aliases (``_REAL_LOCK``/``_REAL_RLOCK``,
#: ``_thread.allocate_lock``) — the witness modules deliberately build
#: their own mutexes from the unwrapped primitives; they are still
#: locks to the analysis
_LOCK_ALIAS_RE = re.compile(r"(?:^|_)R?LOCK$", re.IGNORECASE)


def _is_lock_ctor(call: ast.Call) -> Optional[str]:
    f = call.func
    name = f.attr if isinstance(f, ast.Attribute) else \
        f.id if isinstance(f, ast.Name) else None
    if name is None:
        return None
    if name in _LOCK_CTORS or name == "Condition":
        return name
    if _LOCK_ALIAS_RE.search(name):
        return "RLock"
    return None


class LockTable:
    """Every lock declaration in the project, with resolution maps."""

    def __init__(self):
        #: (module_dotted, varname) -> node  (module-level locks)
        self.module_locks: Dict[Tuple[str, str], str] = {}
        #: class_fqn -> {attr: node}
        self.class_attrs: Dict[str, Dict[str, str]] = {}
        #: fn_fqn -> {varname: node}  (function-local locks, closures)
        self.fn_locals: Dict[str, Dict[str, str]] = {}
        #: attr -> {node}  (unique-name fallback for non-self receivers)
        self.attr_index: Dict[str, Set[str]] = {}
        #: node -> module_dotted
        self.node_module: Dict[str, str] = {}

    def _add(self, node: str, module: str, attr: Optional[str]) -> None:
        self.node_module[node] = module
        if attr:
            self.attr_index.setdefault(attr, set()).add(node)


def build_lock_table(project: Project) -> LockTable:
    table = LockTable()
    for mod in project.modules.values():
        for stmt in mod.tree.body:
            if isinstance(stmt, ast.Assign) and \
                    isinstance(stmt.value, ast.Call) and \
                    _is_lock_ctor(stmt.value) and \
                    len(stmt.targets) == 1 and \
                    isinstance(stmt.targets[0], ast.Name):
                name = stmt.targets[0].id
                node = f"{mod.dotted}:{name}"
                table.module_locks[(mod.dotted, name)] = node
                table._add(node, mod.dotted, None)
    for fn in project.functions.values():
        cls = fn.class_fqn
        cls_qual = cls.split(":", 1)[1] if cls else None
        local_locks: Dict[str, str] = {}
        for stmt in ast.walk(fn.node):
            if not (isinstance(stmt, ast.Assign) and
                    isinstance(stmt.value, ast.Call)):
                continue
            kind = _is_lock_ctor(stmt.value)
            if kind is None or len(stmt.targets) != 1:
                continue
            tgt = stmt.targets[0]
            mod = fn.module.dotted
            if isinstance(tgt, ast.Name):
                if kind == "Condition":
                    # Condition over an existing lock is an alias, a
                    # bare Condition() is its own (hidden RLock) node
                    node = None
                    args = stmt.value.args
                    if args and isinstance(args[0], ast.Name):
                        node = local_locks.get(args[0].id)
                    if node is None:
                        node = f"{mod}:{fn.qual}.{tgt.id}"
                        table._add(node, mod, None)
                    local_locks[tgt.id] = node
                else:
                    node = f"{mod}:{cls_qual}.{tgt.id}" if cls_qual \
                        else f"{mod}:{fn.qual}.{tgt.id}"
                    table._add(node, mod, None)
                    local_locks[tgt.id] = node
                table.fn_locals.setdefault(fn.fqn, {})[tgt.id] = node
            elif isinstance(tgt, ast.Attribute) and \
                    isinstance(tgt.value, ast.Name) and \
                    tgt.value.id == "self" and cls:
                attr = tgt.attr
                node = None
                if kind == "Condition":
                    args = stmt.value.args
                    if args and isinstance(args[0], ast.Name):
                        node = local_locks.get(args[0].id)
                    elif args and isinstance(args[0], ast.Attribute) and \
                            isinstance(args[0].value, ast.Name) and \
                            args[0].value.id == "self":
                        node = table.class_attrs.get(cls, {}).get(
                            args[0].attr)
                if node is None:
                    node = f"{mod}:{cls_qual}.{attr}"
                table.class_attrs.setdefault(cls, {})[attr] = node
                table._add(node, mod, attr)
    return table


def _class_lock_attr(project: Project, table: LockTable,
                     class_fqn: str, attr: str,
                     seen: Optional[set] = None) -> Optional[str]:
    seen = seen if seen is not None else set()
    if class_fqn in seen:
        return None
    seen.add(class_fqn)
    hit = table.class_attrs.get(class_fqn, {}).get(attr)
    if hit:
        return hit
    ci = project.classes.get(class_fqn)
    if ci is None:
        return None
    for base in ci.bases:
        bci = project._resolve_class(base.split(".")[-1], ci.module)
        if bci is not None:
            hit = _class_lock_attr(project, table, bci.fqn, attr, seen)
            if hit:
                return hit
    return None


def resolve_lock_expr(project: Project, table: LockTable,
                      fn: FunctionInfo, expr: ast.AST) -> Optional[str]:
    if isinstance(expr, ast.Name):
        qual_parts = fn.qual.split(".")
        for i in range(len(qual_parts), 0, -1):
            owner = f"{fn.module.dotted}:" + ".".join(qual_parts[:i])
            hit = table.fn_locals.get(owner, {}).get(expr.id)
            if hit:
                return hit
        return table.module_locks.get((fn.module.dotted, expr.id))
    if isinstance(expr, ast.Attribute):
        if isinstance(expr.value, ast.Name) and \
                expr.value.id in ("self", "cls") and fn.class_fqn:
            return _class_lock_attr(project, table, fn.class_fqn,
                                    expr.attr)
        cands = table.attr_index.get(expr.attr, ())
        if len(cands) == 1:
            return next(iter(cands))
    return None


class _FnLockFacts:
    __slots__ = ("direct_edges", "calls_under", "acquires")

    def __init__(self):
        #: (held_node, acquired_node, line)
        self.direct_edges: List[Tuple[str, str, int]] = []
        #: (held_nodes tuple, ast.Call)
        self.calls_under: List[Tuple[Tuple[str, ...], ast.Call]] = []
        self.acquires: Set[str] = set()


def _scan_function(project: Project, table: LockTable,
                   fn: FunctionInfo) -> _FnLockFacts:
    facts = _FnLockFacts()

    def rec(node: ast.AST, held: Tuple[str, ...]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            return    # separate scope / deferred execution
        if isinstance(node, (ast.With, ast.AsyncWith)):
            newly: List[str] = []
            for item in node.items:
                rec(item.context_expr, held)     # evaluated pre-acquire
                lk = resolve_lock_expr(project, table, fn,
                                       item.context_expr)
                if lk is not None:
                    for h in held + tuple(newly):
                        facts.direct_edges.append((h, lk, node.lineno))
                    newly.append(lk)
                    facts.acquires.add(lk)
            inner = held + tuple(newly)
            for stmt in node.body:
                rec(stmt, inner)
            return
        if isinstance(node, ast.Call):
            facts.calls_under.append((held, node))
        for child in ast.iter_child_nodes(node):
            rec(child, held)

    for stmt in fn.node.body:
        rec(stmt, ())
    return facts


def build_lock_graph(project: Project):
    """→ (edges, facts, acq_star): ``edges[(a, b)] = (file, line, via)``
    meaning lock ``b`` is (possibly transitively) acquired while ``a``
    is held, first witnessed at that site."""
    table = build_lock_table(project)
    facts: Dict[str, _FnLockFacts] = {
        fqn: _scan_function(project, table, fn)
        for fqn, fn in project.functions.items()}
    # transitive acquisitions per function
    acq_star: Dict[str, Set[str]] = {
        fqn: set(f.acquires) for fqn, f in facts.items()}
    changed = True
    while changed:
        changed = False
        for fqn in facts:
            cur = acq_star[fqn]
            before = len(cur)
            for tgt in project.call_targets(fqn):
                cur |= acq_star.get(tgt, set())
            if len(cur) != before:
                changed = True
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    for fqn, f in facts.items():
        fn = project.functions[fqn]
        for a, b, line in f.direct_edges:
            if a != b:
                edges.setdefault((a, b), (fn.module.relpath, line,
                                          fn.qual))
        for held, call in f.calls_under:
            if not held:
                continue
            for tgt in project.resolve_call(fn, call):
                for b in acq_star.get(tgt, ()):
                    for a in held:
                        if a != b:
                            edges.setdefault(
                                (a, b),
                                (fn.module.relpath, call.lineno,
                                 f"{fn.qual} -> "
                                 f"{tgt.split(':', 1)[1]}"))
    return edges, facts, acq_star, table


def find_cycles(edges: Dict[Tuple[str, str], Tuple]) -> List[List[str]]:
    """Elementary cycles in the lock graph (each reported once, rotated
    to start at its smallest node)."""
    adj: Dict[str, Set[str]] = {}
    for a, b in edges:
        adj.setdefault(a, set()).add(b)
    seen_cycles: Set[Tuple[str, ...]] = set()
    out: List[List[str]] = []

    def dfs(start: str, cur: str, path: List[str],
            on_path: Set[str]) -> None:
        for nxt in sorted(adj.get(cur, ())):
            if nxt == start:
                rot = min(range(len(path)),
                          key=lambda i: path[i])
                canon = tuple(path[rot:] + path[:rot])
                if canon not in seen_cycles:
                    seen_cycles.add(canon)
                    out.append(list(canon))
            elif nxt not in on_path and nxt > start:
                # only expand nodes > start: each cycle is found from
                # its smallest node exactly once
                path.append(nxt)
                on_path.add(nxt)
                dfs(start, nxt, path, on_path)
                on_path.discard(nxt)
                path.pop()

    for n in sorted(adj):
        dfs(n, n, [n], {n})
    return out


def _check_cycles(project: Project, edges) -> List[Finding]:
    findings = []
    for cycle in find_cycles(edges):
        hops = []
        first_site = None
        for i, a in enumerate(cycle):
            b = cycle[(i + 1) % len(cycle)]
            site = edges.get((a, b))
            if site and first_site is None:
                first_site = site
            hops.append(f"{a} -> {b}"
                        + (f" ({site[0]}:{site[1]} in {site[2]})"
                           if site else ""))
        file, line = (first_site[0], first_site[1]) if first_site \
            else ("<unknown>", 0)
        findings.append(Finding(
            RULE_L01, file, line, "lock-graph",
            "cycle: " + " ; ".join(f"{a} -> {cycle[(i + 1) % len(cycle)]}"
                                   for i, a in enumerate(cycle)),
            "lock-order cycle (deadlock under the right interleaving): "
            + " ; ".join(hops)))
    return findings


def _serving_locks(table: LockTable) -> Set[str]:
    out = set()
    for node, mod in table.node_module.items():
        attr = node.rsplit(".", 1)[-1]
        if SERVING_LOCK_MODULES.search(mod) and \
                not _NON_SERVING_ATTR.search(attr):
            out.add(node)
    return out


def _check_telemetry_under_lock(project: Project, facts,
                                table: LockTable) -> List[Finding]:
    # which functions (transitively) execute telemetry/tracing code
    in_telem = {fqn for fqn, fn in project.functions.items()
                if TELEMETRY_MODULES.search(fn.module.dotted)}
    reaches: Dict[str, bool] = {fqn: False for fqn in project.functions}
    changed = True
    while changed:
        changed = False
        for fqn in project.functions:
            if reaches[fqn]:
                continue
            for tgt in project.call_targets(fqn):
                if tgt in in_telem or reaches.get(tgt):
                    reaches[fqn] = True
                    changed = True
                    break
    serving = _serving_locks(table)
    findings = []
    seen = set()
    for fqn, f in facts.items():
        fn = project.functions[fqn]
        if TELEMETRY_MODULES.search(fn.module.dotted):
            continue        # telemetry's own internals are exempt
        for held, call in f.calls_under:
            s_held = [h for h in held if h in serving]
            if not s_held:
                continue
            for tgt in project.resolve_call(fn, call):
                if tgt in in_telem or reaches.get(tgt):
                    key = (fqn, call.lineno, s_held[0])
                    if key in seen:
                        continue
                    seen.add(key)
                    findings.append(Finding(
                        RULE_L02, fn.module.relpath, call.lineno,
                        fn.qual,
                        f"telemetry call [{_unparse(call.func)}] under "
                        f"serving lock [{s_held[0]}]",
                        f"telemetry/tracing executes while serving lock "
                        f"[{s_held[0]}] is held (via "
                        f"{tgt.split(':', 1)[1]}): a slow scrape or "
                        f"collector must never stall the dispatch path "
                        f"— move the call outside the critical "
                        f"section"))
                    break
    return findings


def check(project: Project) -> List[Finding]:
    edges, facts, _acq_star, table = build_lock_graph(project)
    return _check_cycles(project, edges) + \
        _check_telemetry_under_lock(project, facts, table)
