"""Rule family 1 — jit-boundary hygiene (ESTP-J*).

The serving hot path is a pipeline of host prep feeding jitted device
dispatches; its two recurring regressions are (a) an accidental host
synchronization (``.item()``, ``float()`` on a device array, a stray
``np.asarray`` or implicit ``__bool__``) serializing the pipeline from
inside the dispatch path, and (b) compile churn from static arguments
that bypass the shape-lattice bucketing helpers. Until now both were
caught only at runtime (the PR 3 compile-ratchet, stage timings); these
rules catch them at the AST.

- **ESTP-J01 host-sync-in-hot-path** — host-synchronizing constructs
  (``.item()``, ``jax.device_get``, ``jax.block_until_ready``, and
  ``float()/int()/bool()``/``np.asarray``/implicit-``bool`` branching on
  names assigned from a jitted step call) inside functions reachable
  from device hot-path roots (``build_*_step``, ``serve``/``serve_view``,
  the dispatcher loops). An *intentional* sync (the one batched result
  fetch; a stage-timing fence) belongs in the baseline with its
  justification.
- **ESTP-J02 impure-host-call-in-jit** — ``time.*``/``random.*``/
  ``np.random.*``/``datetime.*``/``print``/``open`` calls and host-sync
  constructs inside jit-compiled code (decorated, or wrapped via
  ``jax.jit(f)``): they burn into the trace as constants or crash on
  tracers.
- **ESTP-J03 mutable-default-in-jit** — list/dict/set defaults on a
  jit-compiled function: mutated state is invisible to the trace cache.
- **ESTP-J04 unbucketed-static-arg** — step call sites (``_get_step``,
  ``build_*_step``, jitted functions with ``static_argnames``) fed a raw
  data-dependent size (``len(...)``, ``x.shape[i]``) that never passed
  through a bucketing helper (``round_up_pow2``/``bucket_length``/
  ``_k_bucket``/``ladder_L``…): every distinct value is a fresh XLA
  compile.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set

from .analyzer import (Finding, FunctionInfo, Project, _unparse,
                       assign_target_names, scoped_walk)

RULE_J01 = "ESTP-J01"
RULE_J02 = "ESTP-J02"
RULE_J03 = "ESTP-J03"
RULE_J04 = "ESTP-J04"

#: device hot-path roots: plane serving entries + dispatcher loops
HOT_ROOT_NAMES = {"serve", "serve_view", "_dispatch_loop", "_run_batch"}
HOT_ROOT_RE = re.compile(r"^build_\w+_step$")

#: the shape-lattice bucketing helpers static shapes must flow through
BUCKET_HELPERS = {"round_up_pow2", "round_up_multiple", "bucket_length",
                  "ladder_L", "ladder_rungs", "_k_bucket", "min", "max"}

#: step-getter call targets whose arguments are compile-shape static
STEP_CALLEE_RE = re.compile(r"^(_?get_step|build_\w+_step)$")


def _short(node: ast.AST, cap: int = 64) -> str:
    s = _unparse(node)
    return s if len(s) <= cap else s[: cap - 1] + "…"


def _callee_name(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _assign_targets(node: ast.Assign) -> List[str]:
    out: List[str] = []
    for t in node.targets:
        out.extend(assign_target_names(t))
    return out


def _hot_reach(project: Project):
    """BFS from the hot roots, keeping one parent per reached function so
    findings can name their root chain."""
    roots = [fqn for fqn, fn in project.functions.items()
             if fn.name in HOT_ROOT_NAMES or HOT_ROOT_RE.match(fn.name)]
    parent: Dict[str, Optional[str]] = {r: None for r in roots}
    todo = list(roots)
    while todo:
        cur = todo.pop()
        for tgt in project.call_targets(cur):
            if tgt not in parent:
                parent[tgt] = cur
                todo.append(tgt)
    return parent


def _root_chain(parent: Dict[str, Optional[str]], fqn: str) -> str:
    chain = [fqn]
    while parent.get(chain[-1]) is not None:
        chain.append(parent[chain[-1]])
    names = [c.split(":", 1)[1] for c in reversed(chain)]
    return " -> ".join(names[:4] + (["…"] if len(names) > 4 else []))


def _mentions_tainted(expr: ast.AST, tainted: Set[str]) -> bool:
    """True when ``expr`` is a pure re-binding of tainted data: a
    tainted name, a subscript/starred of one, or a tuple/list of those
    (``scores, idx = out``; ``scores = out[0]``). Calls are deliberately
    excluded — ``len(out)`` yields a host int, not a device array."""
    if isinstance(expr, ast.Name):
        return expr.id in tainted
    if isinstance(expr, ast.Subscript):
        return _mentions_tainted(expr.value, tainted)
    if isinstance(expr, ast.Starred):
        return _mentions_tainted(expr.value, tainted)
    if isinstance(expr, (ast.Tuple, ast.List)):
        return any(_mentions_tainted(e, tainted) for e in expr.elts)
    return False


def _tainted_names(project: Project, fn: FunctionInfo) -> Set[str]:
    """Names in ``fn`` bound (directly or through a step-callable local)
    to the result of a jitted call — device-array-typed values whose
    host conversion is a sync. Taint flows through tuple/starred
    destructuring (including nested targets) and plain re-bindings:
    ``out = step(xs); scores, idx = out; s0 = scores[0]`` taints all
    four names."""
    step_locals: Set[str] = set()
    tainted: Set[str] = set()
    assigns = sorted(
        (n for n in scoped_walk(fn.node) if isinstance(n, ast.Assign)),
        key=lambda n: n.lineno)
    # pass 1: which locals hold a jitted callable (step getters)
    for node in assigns:
        if not isinstance(node.value, ast.Call):
            continue
        targets = _assign_targets(node)
        if targets and any(project.functions[t].returns_jitted
                           for t in project.resolve_call(fn, node.value)):
            step_locals.update(targets)
    # pass 2 (in program order): locals holding a jitted call's RESULT
    # (device arrays), plus re-bindings/destructurings of those
    for node in assigns:
        targets = _assign_targets(node)
        if not targets:
            continue
        val = node.value
        if isinstance(val, ast.Call):
            resolved = project.resolve_call(fn, val)
            if any(project.functions[t].returns_jitted for t in resolved):
                continue        # a step getter, not a step result
            is_jit_result = any(project.functions[t].jitted
                                for t in resolved)
            if not is_jit_result and isinstance(val.func, ast.Name) and \
                    val.func.id in step_locals:
                is_jit_result = True
            if is_jit_result:
                tainted.update(targets)
        elif _mentions_tainted(val, tainted):
            tainted.update(targets)
    return tainted


def _host_sync_detail(node: ast.AST, tainted: Set[str]) -> Optional[str]:
    """The host-sync classification of one AST node, or None."""
    if isinstance(node, ast.Call):
        name = _callee_name(node)
        if name == "item" and isinstance(node.func, ast.Attribute) \
                and not node.args:
            return f".item() [{_short(node)}]"
        if name in ("device_get", "block_until_ready"):
            return f"{name}() [{_short(node)}]"
        if name in ("float", "int", "bool") and len(node.args) == 1 and \
                _names_in(node.args[0]) & tainted:
            return f"{name}() on step output [{_short(node)}]"
        if name in ("asarray", "array") and node.args and \
                _names_in(node.args[0]) & tainted:
            return f"np.{name}() on step output [{_short(node)}]"
    if isinstance(node, (ast.If, ast.While)):
        test = node.test
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            test = test.operand
        if isinstance(test, ast.Name) and test.id in tainted:
            return f"implicit bool() on step output [{test.id}]"
    return None


def _check_hot_path(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    parent = _hot_reach(project)
    for fqn in parent:
        fn = project.functions.get(fqn)
        if fn is None or fn.jitted:
            continue      # inside-jit constructs are ESTP-J02's concern
        tainted = _tainted_names(project, fn)
        for node in scoped_walk(fn.node):
            detail = _host_sync_detail(node, tainted)
            if detail is None:
                continue
            findings.append(Finding(
                RULE_J01, fn.module.relpath, node.lineno, fn.qual, detail,
                f"host synchronization {detail} on the device hot path "
                f"(reached via {_root_chain(parent, fqn)}); a sync here "
                f"serializes the dispatch pipeline"))
    return findings


_IMPURE_MODULES = {"time", "random", "datetime", "os"}


def _check_in_jit(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for fn in project.functions.values():
        if not fn.jitted:
            continue
        # J03: mutable defaults
        args = fn.node.args
        for d in list(args.defaults) + [d for d in args.kw_defaults if d]:
            if isinstance(d, (ast.List, ast.Dict, ast.Set)) or (
                    isinstance(d, ast.Call) and
                    isinstance(d.func, ast.Name) and
                    d.func.id in ("list", "dict", "set")):
                findings.append(Finding(
                    RULE_J03, fn.module.relpath, d.lineno, fn.qual,
                    f"mutable default [{_short(d)}]",
                    "jit-compiled function carries a mutable default "
                    "argument — mutations are invisible to the trace "
                    "cache and resurrect stale state across calls"))
        # J02: impure host calls + host syncs inside the traced body
        for node in scoped_walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            impure = None
            if isinstance(f, ast.Attribute):
                base = f.value
                if isinstance(base, ast.Name) and \
                        base.id in _IMPURE_MODULES:
                    impure = f"{base.id}.{f.attr}()"
                elif isinstance(base, ast.Attribute) and \
                        base.attr == "random":
                    impure = f"np.random.{f.attr}()"
                elif f.attr == "item" and not node.args:
                    impure = ".item()"
                elif f.attr in ("device_get", "block_until_ready"):
                    impure = f"{f.attr}()"
                elif f.attr == "asarray" and isinstance(base, ast.Name) \
                        and base.id in ("np", "numpy"):
                    impure = "np.asarray()"
            elif isinstance(f, ast.Name) and f.id in ("print", "open"):
                impure = f"{f.id}()"
            if impure:
                findings.append(Finding(
                    RULE_J02, fn.module.relpath, node.lineno, fn.qual,
                    f"{impure} in jit [{_short(node)}]",
                    f"{impure} inside a jit-compiled function: traces to "
                    f"a burned-in constant (or crashes on a tracer) — "
                    f"hoist it to the host side of the boundary"))
    return findings


def _last_assignments(fn: FunctionInfo) -> Dict[str, ast.AST]:
    out: Dict[str, ast.AST] = {}
    for node in scoped_walk(fn.node):
        if isinstance(node, ast.Assign):
            for name in _assign_targets(node):
                out[name] = node.value
    return out


def _is_raw_size(expr: ast.AST, assigns: Dict[str, ast.AST],
                 depth: int = 0) -> bool:
    """True when ``expr`` is a data-dependent size that never passed a
    bucketing helper: ``len(...)``, ``x.shape[i]``, or a name whose last
    assignment is one of those. Anything passing through a helper — or
    not provably raw — is accepted (the rule under-approximates)."""
    if isinstance(expr, ast.Call):
        name = _callee_name(expr)
        if name in BUCKET_HELPERS:
            return False
    if isinstance(expr, ast.Name) and depth < 3:
        src = assigns.get(expr.id)
        return _is_raw_size(src, assigns, depth + 1) if src is not None \
            else False
    has_helper = any(isinstance(n, ast.Call) and
                     _callee_name(n) in BUCKET_HELPERS
                     for n in ast.walk(expr))
    if has_helper:
        return False
    for n in ast.walk(expr):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) and \
                n.func.id == "len":
            return True
        if isinstance(n, ast.Attribute) and n.attr == "shape":
            return True
    return False


def _is_opaque_call_size(expr: ast.AST, assigns: Dict[str, ast.AST],
                         depth: int = 0) -> bool:
    """True when ``expr`` is (or a name last assigned from) a call that
    is not a bucketing helper — a data-derived value with no visible
    shape-lattice provenance."""
    if isinstance(expr, ast.Name) and depth < 3:
        src = assigns.get(expr.id)
        return _is_opaque_call_size(src, assigns, depth + 1) \
            if src is not None else False
    if isinstance(expr, ast.Call):
        return _callee_name(expr) not in BUCKET_HELPERS
    return False


def _static_args_at(project: Project, fn: FunctionInfo, call: ast.Call):
    """(arg expr, display name, strict) triples that are compile-shape
    static at this call site. ``strict`` marks sites where the callee is
    *declared* jit-static (``static_argnames``) — there even an opaque
    data-derived provenance is flagged, not just provably-raw sizes."""
    name = _callee_name(call)
    if name and STEP_CALLEE_RE.match(name):
        out = [(a, f"arg{idx}", False) for idx, a in enumerate(call.args)]
        out += [(kw.value, kw.arg, False) for kw in call.keywords
                if kw.arg]
        return out
    resolved = project.resolve_call(fn, call)
    for tgt in resolved:
        tfn = project.functions[tgt]
        if tfn.jitted and tfn.static_argnames:
            statics = set(tfn.static_argnames)
            posnames = [a.arg for a in tfn.node.args.args]
            out = []
            for idx, a in enumerate(call.args):
                if idx < len(posnames) and posnames[idx] in statics:
                    out.append((a, posnames[idx], True))
            out += [(kw.value, kw.arg, True) for kw in call.keywords
                    if kw.arg in statics]
            return out
    return []


def _check_static_args(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for fn in project.functions.values():
        assigns = None
        for cs in fn.calls:
            pairs = _static_args_at(project, fn, cs.node)
            if not pairs:
                continue
            if assigns is None:
                assigns = _last_assignments(fn)
            for expr, argname, strict in pairs:
                raw = _is_raw_size(expr, assigns)
                if not raw and strict:
                    raw = _is_opaque_call_size(expr, assigns)
                if raw:
                    findings.append(Finding(
                        RULE_J04, fn.module.relpath, cs.line, fn.qual,
                        f"raw static arg {argname}=[{_short(expr)}] at "
                        f"{_short(cs.node.func)}()",
                        f"static argument [{argname}] at a jit step call "
                        f"site is a raw data-dependent size — route it "
                        f"through the shape-lattice helpers "
                        f"(utils/shapes.py) or every distinct value "
                        f"compiles a fresh XLA program"))
    return findings


def check(project: Project) -> List[Finding]:
    return (_check_hot_path(project) + _check_in_jit(project) +
            _check_static_args(project))
