"""SARIF 2.1.0 export for estpulint findings.

One run, one driver (``estpulint``), one result per finding. Baselined
findings are emitted with a ``suppressions`` entry (kind
``external``, justification attached) so CI annotators and editors show
them struck-through instead of hiding them — the reviewed-intentional
list stays visible at the line it covers.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence, Tuple

from .analyzer import Finding

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")

#: one-line rule help (the catalogue lives in STATIC_ANALYSIS.md)
RULE_HELP = {
    "ESTP-J01": "host synchronization on the device hot path",
    "ESTP-J02": "impure host call inside jit-compiled code",
    "ESTP-J03": "mutable default argument on a jit-compiled function",
    "ESTP-J04": "unbucketed data-dependent static shape at a step call",
    "ESTP-L01": "lock-order cycle (deadlock under some interleaving)",
    "ESTP-L02": "telemetry/tracing reachable under a serving lock",
    "ESTP-R01": "shared mutable state with empty lockset intersection",
    "ESTP-R02": "check-then-act on guarded state across a lock release",
    "ESTP-T01": "thread/executor started with no join/shutdown on close",
    "ESTP-C01": "runtime telemetry family without a TELEMETRY.md row",
    "ESTP-C02": "documented telemetry family never registered",
    "ESTP-C03": "health diagnosis references an undocumented family",
}


def to_sarif(findings: Sequence[Finding],
             baselined: Sequence[Finding],
             justifications: Optional[Dict[Tuple, str]] = None) -> dict:
    """``findings`` are NEW (gate-failing) results; ``baselined`` are
    matched-suppressed ones. Both are emitted — suppressed results carry
    their baseline justification."""
    rule_ids = sorted({f.rule for f in list(findings) + list(baselined)})
    rules = [{"id": rid,
              "shortDescription": {
                  "text": RULE_HELP.get(rid, rid)},
              "helpUri": "STATIC_ANALYSIS.md"}
             for rid in rule_ids]
    rule_index = {rid: i for i, rid in enumerate(rule_ids)}

    def result(f: Finding, suppressed: bool) -> dict:
        doc = {
            "ruleId": f.rule,
            "ruleIndex": rule_index[f.rule],
            "level": "warning" if suppressed else "error",
            "message": {"text": f"{f.symbol}: {f.message}"},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.file,
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": max(f.line, 1)},
                }}],
            "partialFingerprints": {
                # the baseline identity, so re-runs dedupe stably even
                # as line numbers drift
                "estpulint/v1": f"{f.rule}|{f.file}|{f.symbol}|{f.detail}",
            },
        }
        if suppressed:
            just = (justifications or {}).get(f.identity, "")
            sup = {"kind": "external", "status": "accepted"}
            if just:
                sup["justification"] = just
            doc["suppressions"] = [sup]
        return doc

    results = [result(f, False) for f in findings] + \
        [result(f, True) for f in baselined]
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "estpulint",
                "informationUri": "STATIC_ANALYSIS.md",
                "rules": rules,
            }},
            "originalUriBaseIds": {"SRCROOT": {"uri": "file:./"}},
            "results": results,
        }],
    }


def write_sarif(path: str, findings: Sequence[Finding],
                baselined: Sequence[Finding],
                justifications: Optional[Dict[Tuple, str]] = None) -> None:
    with open(path, "w") as f:
        json.dump(to_sarif(findings, baselined, justifications), f,
                  indent=1)
        f.write("\n")
