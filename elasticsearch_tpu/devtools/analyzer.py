"""estpulint core: project model, call graph, findings, baseline.

Everything here is plain ``ast`` — no imports of the analyzed modules
(the jit rules must be able to judge a file that would crash on import),
no third-party dependencies. The model is deliberately *resolution
conservative*: a call edge exists only when the callee can be named with
reasonable confidence (same-scope functions, ``self.``/``cls.`` methods
through the project MRO, imported names, or a project-unique private
method name whose defining module the caller imports). Unresolvable
calls simply contribute no edges — rules built on the graph
under-approximate rather than hallucinate.

Finding identity is (rule, file, symbol, detail) — line numbers are
reported but excluded from identity so the checked-in baseline
(``ESTPULINT_BASELINE.json``) survives unrelated edits to the same file.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

#: package source roots scanned by default (repo-relative)
DEFAULT_SCAN_DIRS = ("elasticsearch_tpu",)


def _unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:   # noqa: BLE001 — display-only fallback
        return f"<{type(node).__name__}>"


def assign_target_names(target: ast.AST) -> List[str]:
    """Every plain name bound by an assignment target, through
    arbitrarily nested tuple/list/starred destructuring
    (``(a, b), *rest = ...``)."""
    out: List[str] = []
    todo = [target]
    while todo:
        t = todo.pop()
        if isinstance(t, ast.Name):
            out.append(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            todo.extend(t.elts)
        elif isinstance(t, ast.Starred):
            todo.append(t.value)
    return out


def scoped_walk(node: ast.AST):
    """``ast.walk`` confined to one function's own execution scope:
    nested function/class bodies and lambda bodies are NOT descended
    into (they are separate FunctionInfos / deferred execution), while
    comprehensions — which execute inline — are."""
    todo = list(ast.iter_child_nodes(node))
    while todo:
        cur = todo.pop()
        yield cur
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef, ast.Lambda)):
            continue
        todo.extend(ast.iter_child_nodes(cur))


# ---------------------------------------------------------------------------
# Findings + baseline
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Finding:
    """One rule violation. ``detail`` is the stable machine-readable core
    (baseline identity); ``message`` is the human rendering."""

    rule: str
    file: str
    line: int
    symbol: str
    detail: str
    message: str

    @property
    def identity(self) -> Tuple[str, str, str, str]:
        return (self.rule, self.file, self.symbol, self.detail)

    def doc(self) -> dict:
        return {"rule": self.rule, "file": self.file, "symbol": self.symbol,
                "detail": self.detail}

    def render(self) -> str:
        return (f"{self.file}:{self.line}: [{self.rule}] {self.symbol}: "
                f"{self.message}")


def load_baseline(path: str) -> List[dict]:
    if not os.path.exists(path):
        return []
    with open(path) as f:
        doc = json.load(f)
    return list(doc.get("findings", ()))


def save_baseline(path: str, findings: Sequence[Finding],
                  justifications: Optional[Dict[Tuple, str]] = None) -> None:
    docs = []
    for f in sorted(findings, key=lambda x: (x.file, x.rule, x.symbol,
                                             x.detail)):
        d = f.doc()
        just = (justifications or {}).get(f.identity)
        d["justification"] = just or "TODO: justify or fix"
        docs.append(d)
    with open(path, "w") as fh:
        json.dump({"comment": "estpulint zero-new-findings baseline: every "
                              "entry is an intentionally-kept finding with "
                              "a one-line justification. Regenerate with "
                              "scripts/estpulint.py --update-baseline.",
                   "findings": docs}, fh, indent=1, sort_keys=False)
        fh.write("\n")


def compare_with_baseline(findings: Sequence[Finding],
                          baseline: Sequence[dict]):
    """→ (new_findings, matched_findings, stale_baseline_entries)."""
    base_keys = {(d.get("rule"), d.get("file"), d.get("symbol", ""),
                  d.get("detail", "")) for d in baseline}
    new = [f for f in findings if f.identity not in base_keys]
    matched = [f for f in findings if f.identity in base_keys]
    live = {f.identity for f in findings}
    stale = [d for d in baseline
             if (d.get("rule"), d.get("file"), d.get("symbol", ""),
                 d.get("detail", "")) not in live]
    return new, matched, stale


# ---------------------------------------------------------------------------
# Project model
# ---------------------------------------------------------------------------


class ModuleInfo:
    __slots__ = ("relpath", "dotted", "tree", "source",
                 "imports", "imported_modules")

    def __init__(self, relpath: str, dotted: str, tree: ast.Module,
                 source: str):
        self.relpath = relpath
        self.dotted = dotted
        self.tree = tree
        self.source = source
        #: local name -> fully dotted target ("pkg.mod" or "pkg.mod.attr")
        self.imports: Dict[str, str] = {}
        #: dotted module names this module imports anything from
        self.imported_modules: Set[str] = set()


class CallSite:
    __slots__ = ("node", "line", "text")

    def __init__(self, node: ast.Call):
        self.node = node
        self.line = node.lineno
        self.text = _unparse(node.func)


class FunctionInfo:
    __slots__ = ("fqn", "qual", "name", "node", "module", "class_fqn",
                 "jitted", "static_argnames", "returns_jitted", "calls")

    def __init__(self, fqn: str, qual: str, node, module: ModuleInfo,
                 class_fqn: Optional[str]):
        self.fqn = fqn
        self.qual = qual
        self.name = node.name
        self.node = node
        self.module = module
        self.class_fqn = class_fqn
        self.jitted = False
        self.static_argnames: Tuple[str, ...] = ()
        self.returns_jitted = False
        self.calls: List[CallSite] = []

    @property
    def line(self) -> int:
        return self.node.lineno


class ClassInfo:
    __slots__ = ("fqn", "name", "node", "module", "bases", "methods")

    def __init__(self, fqn: str, node: ast.ClassDef, module: ModuleInfo):
        self.fqn = fqn
        self.name = node.name
        self.node = node
        self.module = module
        self.bases: List[str] = [_unparse(b) for b in node.bases]
        #: method name -> function fqn
        self.methods: Dict[str, str] = {}


def _is_jit_expr(node: ast.AST) -> bool:
    """``jit`` / ``jax.jit`` (any attribute path ending in .jit)."""
    return (isinstance(node, ast.Name) and node.id == "jit") or \
        (isinstance(node, ast.Attribute) and node.attr == "jit")


def _static_argnames_of(call: ast.Call) -> Tuple[str, ...]:
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                return tuple(e.value for e in v.elts
                             if isinstance(e, ast.Constant))
    return ()


class _FunctionCollector(ast.NodeVisitor):
    """Collect functions/classes with qualified names; attach each Call
    to its *immediately* enclosing function (nested defs own their
    bodies; lambda bodies attach to the enclosing function)."""

    def __init__(self, project: "Project", module: ModuleInfo):
        self.project = project
        self.module = module
        self.qual_stack: List[str] = []
        self.class_stack: List[ClassInfo] = []
        self.fn_stack: List[FunctionInfo] = []

    # -- scoping -------------------------------------------------------------

    def _enter_function(self, node):
        qual = ".".join(self.qual_stack + [node.name])
        fqn = f"{self.module.dotted}:{qual}"
        cls = self.class_stack[-1] if self.class_stack else None
        # a method belongs to the class only when the class is the direct
        # parent scope (not a function nested inside a method)
        direct_method = bool(cls) and \
            ".".join(self.qual_stack) == cls.fqn.split(":", 1)[1]
        fn = FunctionInfo(fqn, qual, node, self.module,
                          cls.fqn if direct_method else None)
        self.project.functions[fqn] = fn
        if direct_method:
            cls.methods[node.name] = fqn
        self._mark_decorators(fn)
        self.qual_stack.append(node.name)
        self.fn_stack.append(fn)
        self.generic_visit(node)
        self.fn_stack.pop()
        self.qual_stack.pop()

    def visit_FunctionDef(self, node):     # noqa: N802 — ast API
        self._enter_function(node)

    def visit_AsyncFunctionDef(self, node):   # noqa: N802
        self._enter_function(node)

    def visit_ClassDef(self, node):        # noqa: N802
        qual = ".".join(self.qual_stack + [node.name])
        fqn = f"{self.module.dotted}:{qual}"
        ci = ClassInfo(fqn, node, self.module)
        self.project.classes[fqn] = ci
        self.class_stack.append(ci)
        self.qual_stack.append(node.name)
        self.generic_visit(node)
        self.qual_stack.pop()
        self.class_stack.pop()

    # -- per-function facts --------------------------------------------------

    def _mark_decorators(self, fn: FunctionInfo) -> None:
        for dec in fn.node.decorator_list:
            if _is_jit_expr(dec):
                fn.jitted = True
            elif isinstance(dec, ast.Call):
                if _is_jit_expr(dec.func):
                    fn.jitted = True
                    fn.static_argnames = _static_argnames_of(dec)
                elif isinstance(dec.func, (ast.Name, ast.Attribute)) and \
                        (getattr(dec.func, "id", None) == "partial" or
                         getattr(dec.func, "attr", None) == "partial") and \
                        dec.args and _is_jit_expr(dec.args[0]):
                    fn.jitted = True
                    fn.static_argnames = _static_argnames_of(dec)

    def visit_Call(self, node):            # noqa: N802
        if self.fn_stack:
            self.fn_stack[-1].calls.append(CallSite(node))
        self.generic_visit(node)

    def visit_Import(self, node):          # noqa: N802
        for alias in node.names:
            local = alias.asname or alias.name.split(".")[0]
            self.module.imports[local] = alias.name
            self.module.imported_modules.add(alias.name)

    def visit_ImportFrom(self, node):      # noqa: N802
        base = node.module or ""
        if node.level:
            parts = self.module.dotted.split(".")
            parts = parts[: -node.level] if node.level <= len(parts) else []
            base = ".".join(parts + ([node.module] if node.module else []))
        for alias in node.names:
            if alias.name == "*":
                continue
            local = alias.asname or alias.name
            self.module.imports[local] = f"{base}.{alias.name}" if base \
                else alias.name
            if base:
                self.module.imported_modules.add(base)


class Project:
    """Parsed project: modules, functions, classes, and a conservative
    call graph."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self.modules: Dict[str, ModuleInfo] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self._method_index: Optional[Dict[str, List[str]]] = None
        self._call_targets: Dict[str, Set[str]] = {}

    # -- construction --------------------------------------------------------

    @classmethod
    def from_root(cls, root: str,
                  files: Optional[Sequence[str]] = None,
                  cache=None) -> "Project":
        """``files``: repo-relative .py paths; default = every .py under
        :data:`DEFAULT_SCAN_DIRS`. ``cache``: an optional
        :class:`model_cache.ModelCache` — unchanged files (same
        mtime/size) skip re-parsing."""
        proj = cls(root)
        if files is None:
            files = []
            for d in DEFAULT_SCAN_DIRS:
                top = os.path.join(root, d)
                for dirpath, _dirnames, names in os.walk(top):
                    for n in sorted(names):
                        if n.endswith(".py"):
                            files.append(os.path.relpath(
                                os.path.join(dirpath, n), root))
        for rel in sorted(files):
            proj.add_file(rel, cache=cache)
        proj._link_jit_wrappers()
        return proj

    def add_file(self, relpath: str, cache=None) -> Optional[ModuleInfo]:
        path = os.path.join(self.root, relpath)
        cached = cache.load(self.root, relpath) if cache is not None \
            else None
        if cached is not None:
            source, tree = cached
        else:
            # stat BEFORE reading: a write landing mid-parse then keys
            # the entry to the old stat, which the next scan misses —
            # never a stale tree served under the new file's key
            stat = cache.stat_key(self.root, relpath) \
                if cache is not None else None
            try:
                with open(path) as f:
                    source = f.read()
                tree = ast.parse(source, filename=relpath)
            except (OSError, SyntaxError):
                return None
            if cache is not None and stat is not None:
                cache.store(self.root, relpath, source, tree, key=stat)
        dotted = relpath[:-3].replace(os.sep, "/").replace("/", ".")
        if dotted.endswith(".__init__"):
            dotted = dotted[: -len(".__init__")]
        mod = ModuleInfo(relpath, dotted, tree, source)
        self.modules[dotted] = mod
        _FunctionCollector(self, mod).visit(tree)
        return mod

    def _link_jit_wrappers(self) -> None:
        """``X = jax.jit(f)`` / ``return jax.jit(f)`` marks ``f`` jitted
        (the dominant pattern here: ``build_*_step`` closes over shapes
        and returns ``jax.jit(step)``)."""
        for fn in list(self.functions.values()):
            for stmt in ast.walk(fn.node):
                val = None
                if isinstance(stmt, (ast.Return, ast.Assign)):
                    val = stmt.value
                if not (isinstance(val, ast.Call) and _is_jit_expr(val.func)
                        and val.args and isinstance(val.args[0], ast.Name)):
                    continue
                inner = self.functions.get(
                    f"{fn.module.dotted}:{fn.qual}.{val.args[0].id}")
                if inner is not None:
                    inner.jitted = True
                    inner.static_argnames = inner.static_argnames or \
                        _static_argnames_of(val)
                if isinstance(stmt, ast.Return):
                    fn.returns_jitted = True
        for mod in self.modules.values():
            for stmt in mod.tree.body:
                if isinstance(stmt, ast.Assign) and \
                        isinstance(stmt.value, ast.Call) and \
                        _is_jit_expr(stmt.value.func) and stmt.value.args \
                        and isinstance(stmt.value.args[0], ast.Name):
                    inner = self.functions.get(
                        f"{mod.dotted}:{stmt.value.args[0].id}")
                    if inner is not None:
                        inner.jitted = True
        # step getters return cached jitted steps
        for fn in self.functions.values():
            if fn.name == "_get_step" or (
                    fn.name.startswith("build_") and
                    fn.name.endswith("_step")):
                fn.returns_jitted = True

    # -- resolution ----------------------------------------------------------

    @property
    def method_index(self) -> Dict[str, List[str]]:
        if self._method_index is None:
            idx: Dict[str, List[str]] = {}
            for ci in self.classes.values():
                for name, fqn in ci.methods.items():
                    idx.setdefault(name, []).append(fqn)
            self._method_index = idx
        return self._method_index

    def _resolve_class(self, name: str, mod: ModuleInfo) \
            -> Optional[ClassInfo]:
        ci = self.classes.get(f"{mod.dotted}:{name}")
        if ci is not None:
            return ci
        tgt = mod.imports.get(name)
        if tgt and "." in tgt:
            m, _, attr = tgt.rpartition(".")
            return self.classes.get(f"{m}:{attr}")
        return None

    def _mro_methods(self, ci: ClassInfo, seen=None) -> Dict[str, str]:
        """name -> fqn over the class and its project-resolvable bases."""
        seen = seen if seen is not None else set()
        if ci.fqn in seen:
            return {}
        seen.add(ci.fqn)
        out: Dict[str, str] = {}
        for base in ci.bases:
            bci = self._resolve_class(base.split(".")[-1], ci.module)
            if bci is not None:
                out.update(self._mro_methods(bci, seen))
        out.update(ci.methods)
        return out

    def resolve_call(self, fn: FunctionInfo, call: ast.Call) -> Set[str]:
        callee = call.func
        out: Set[str] = set()
        if isinstance(callee, ast.Name):
            name = callee.id
            parts = fn.qual.split(".")
            for i in range(len(parts), -1, -1):
                if i and f"{fn.module.dotted}:" + ".".join(parts[:i]) \
                        in self.classes:
                    continue      # class scope is invisible to bare names
                cand = f"{fn.module.dotted}:" + \
                    ".".join(parts[:i] + [name]) if i else \
                    f"{fn.module.dotted}:{name}"
                if cand in self.functions:
                    return {cand}
            ci = self._resolve_class(name, fn.module)
            if ci is not None:
                init = self._mro_methods(ci).get("__init__")
                return {init} if init else set()
            tgt = fn.module.imports.get(name)
            if tgt and "." in tgt:
                m, _, attr = tgt.rpartition(".")
                cand = f"{m}:{attr}"
                if cand in self.functions:
                    return {cand}
            return out
        if not isinstance(callee, ast.Attribute):
            return out
        base, attr = callee.value, callee.attr
        if isinstance(base, ast.Name) and base.id in ("self", "cls") \
                and fn.class_fqn:
            ci = self.classes.get(fn.class_fqn)
            if ci is not None:
                m = self._mro_methods(ci).get(attr)
                if m:
                    return {m}
            return out
        if isinstance(base, ast.Name):
            tgt = fn.module.imports.get(base.id)
            if tgt and tgt in self.modules:
                cand = f"{tgt}:{attr}"
                if cand in self.functions:
                    return {cand}
                ci = self.classes.get(f"{tgt}:{attr}")
                if ci is not None:
                    init = self._mro_methods(ci).get("__init__")
                    return {init} if init else set()
        # last resort: a project-unique method name, accepted only when
        # private-ish or defined in a module the caller imports — keeps
        # `t.start()` from resolving into an unrelated project `start`
        cands = self.method_index.get(attr, ())
        if len(cands) == 1:
            cand_fn = self.functions[cands[0]]
            if cand_fn.module is fn.module or \
                    cand_fn.module.dotted in fn.module.imported_modules:
                return {cands[0]}
        return out

    def call_targets(self, fqn: str) -> Set[str]:
        hit = self._call_targets.get(fqn)
        if hit is not None:
            return hit
        fn = self.functions[fqn]
        out: Set[str] = set()
        for cs in fn.calls:
            out |= self.resolve_call(fn, cs.node)
        self._call_targets[fqn] = out
        return out

    def reachable_from(self, roots: Iterable[str]) -> Set[str]:
        seen: Set[str] = set()
        todo = [r for r in roots if r in self.functions]
        while todo:
            cur = todo.pop()
            if cur in seen:
                continue
            seen.add(cur)
            todo.extend(t for t in self.call_targets(cur) if t not in seen)
        return seen


# ---------------------------------------------------------------------------
# Scan driver
# ---------------------------------------------------------------------------


def scan_project(root: str, files: Optional[Sequence[str]] = None,
                 rules: Optional[Iterable[str]] = None,
                 runtime: bool = True,
                 report_files: Optional[Set[str]] = None,
                 cache=None) -> List[Finding]:
    """Run every selected rule family over the project at ``root``.

    ``rules``: rule-id prefixes to keep (``{"ESTP-J"}``, ``{"ESTP-L01"}``;
    default all). ``runtime=False`` skips the catalogue family's live
    registry workload (its static cross-checks still run).
    ``report_files``: when given (``--diff`` mode), only findings in
    those repo-relative files are reported — the project model is still
    built whole so cross-module rules see the full graph. ``cache``: an
    optional :class:`model_cache.ModelCache` so unchanged files skip
    re-parsing (cached and cold scans are asserted identical in
    tests)."""
    from . import rules_catalogue, rules_jit, rules_locks, rules_races
    project = Project.from_root(root, files, cache=cache)
    prefixes = tuple(rules) if rules is not None else None
    if prefixes and not any(p.startswith("ESTP-C") or
                            "ESTP-C".startswith(p) for p in prefixes):
        runtime = False       # no C rule selected: skip the workload
    findings: List[Finding] = []
    findings += rules_jit.check(project)
    findings += rules_locks.check(project)
    findings += rules_races.check(project)
    findings += rules_catalogue.check(project, runtime=runtime)
    if prefixes is not None:
        findings = [f for f in findings if f.rule.startswith(prefixes)]
    if report_files is not None:
        findings = [f for f in findings if f.file in report_files]
    findings.sort(key=lambda f: (f.file, f.line, f.rule, f.detail))
    return findings
