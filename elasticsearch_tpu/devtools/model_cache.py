"""Parsed-model cache for estpulint: skip re-parsing unchanged files.

A full scan parses ~180 files; pre-commit ``--diff`` runs re-parse all
of them to rebuild the cross-module call graph even when two files
changed. This cache keys each file's parsed ``ast.Module`` (plus its
source text) on ``(mtime_ns, size)`` and stores it pickled under
``.estpulint_cache/`` — a warm scan re-parses only files whose stat
changed. Correctness is pinned by
``tests/test_static_analysis.py::test_model_cache_scan_identical``:
the cold and cached scans must produce identical findings.

The cache holds PARSE artifacts only — the project model (functions,
classes, call graph) is rebuilt from the trees every scan, so a rule or
model change never reads stale analysis through a warm cache; bumping
:data:`CACHE_VERSION` invalidates everything when the *parse* contract
itself changes. Unreadable/corrupt entries fall back to a plain parse.
"""

from __future__ import annotations

import ast
import hashlib
import os
import pickle
from typing import Optional, Tuple

#: bump to invalidate every cached entry (pickle layout / parse contract)
CACHE_VERSION = 1

CACHE_DIR_NAME = ".estpulint_cache"


class ModelCache:
    """One directory of ``<sha1(relpath)>.pkl`` entries, each
    ``(CACHE_VERSION, mtime_ns, size, source, tree)``."""

    def __init__(self, cache_dir: str):
        self.cache_dir = cache_dir
        self.hits = 0
        self.misses = 0

    def _entry_path(self, relpath: str) -> str:
        h = hashlib.sha1(relpath.encode()).hexdigest()
        return os.path.join(self.cache_dir, f"{h}.pkl")

    @staticmethod
    def _stat_key(path: str) -> Optional[Tuple[int, int]]:
        try:
            st = os.stat(path)
        except OSError:
            return None
        return (st.st_mtime_ns, st.st_size)

    def load(self, root: str, relpath: str) \
            -> Optional[Tuple[str, ast.Module]]:
        """(source, tree) when the cached entry matches the file's
        current stat, else None."""
        key = self._stat_key(os.path.join(root, relpath))
        if key is None:
            return None
        try:
            with open(self._entry_path(relpath), "rb") as f:
                ver, mtime_ns, size, source, tree = pickle.load(f)
        except Exception:   # noqa: BLE001 — any corrupt/absent entry
            self.misses += 1        # is just a cold parse
            return None
        if ver != CACHE_VERSION or (mtime_ns, size) != key:
            self.misses += 1
            return None
        self.hits += 1
        return source, tree

    def stat_key(self, root: str, relpath: str) -> Optional[Tuple[int, int]]:
        """The (mtime_ns, size) key for ``relpath`` NOW — callers grab it
        BEFORE reading the file and pass it to :meth:`store`, so a write
        landing between read and store can only produce a key mismatch
        (a harmless warm-scan miss), never a stale entry served under
        the new file's key."""
        return self._stat_key(os.path.join(root, relpath))

    def store(self, root: str, relpath: str, source: str,
              tree: ast.Module,
              key: Optional[Tuple[int, int]] = None) -> None:
        if key is None:
            key = self._stat_key(os.path.join(root, relpath))
        if key is None:
            return
        try:
            os.makedirs(self.cache_dir, exist_ok=True)
            tmp = self._entry_path(relpath) + ".tmp"
            with open(tmp, "wb") as f:
                pickle.dump((CACHE_VERSION, key[0], key[1], source, tree),
                            f, protocol=pickle.HIGHEST_PROTOCOL)
            os.replace(tmp, self._entry_path(relpath))
        except Exception:   # noqa: BLE001 — a read-only checkout must
            pass            # still scan; the cache is best-effort


def default_cache(root: str) -> ModelCache:
    return ModelCache(os.path.join(root, CACHE_DIR_NAME))
