"""Rule family 3 — telemetry-catalogue discipline (ESTP-C*).

Generalizes the old ``scripts/telemetry_lint.py`` (which survives as a
thin shim): registry families, TELEMETRY.md rows, and health-indicator
diagnoses must stay THREE-way consistent, so an operator paging through
a diagnosis ("watch ``es_plane_rebuild_total{mode="sync"}``") always
lands on a documented, actually-registered family.

- **ESTP-C01 undocumented-runtime-family** — a family the live engine
  registers (driven by the miniature real-stack workload below) has no
  TELEMETRY.md row.
- **ESTP-C02 stale-documented-family** — a documented family that the
  workload cannot produce and the CONDITIONAL allowlist cannot explain.
- **ESTP-C03 unknown-family-in-diagnosis** — an ``es_*`` token in
  ``common/health.py`` (indicator details, impacts, diagnosis prose)
  that TELEMETRY.md does not document: the health report would point
  operators at a metric that does not exist.

C01/C02 need a live registry (the workload imports jax and serves real
dispatches) — they run when ``runtime=True`` (the CLI default and the
tier-1 gate) and are skipped in pure-AST scans. C03 is static and
always runs.
"""

from __future__ import annotations

import json
import os
import re
import sys
import tempfile
from typing import List, Optional, Set

from .analyzer import Finding, Project

RULE_C01 = "ESTP-C01"
RULE_C02 = "ESTP-C02"
RULE_C03 = "ESTP-C03"

#: documented families the lint workload cannot produce, with the reason
#: they are still correct documentation
CONDITIONAL = {
    # registered only on cluster fronts (ARS EWMAs need peers)
    "es_adaptive_selection_response_seconds":
        "cluster fronts only (adaptive replica selection)",
    # cluster failover/recovery families: written by the multi-node
    # search fan-out, the master's failover update, and the
    # recovery:plane_* warm-handoff transfer — none of which exist in
    # the single-process lint workload (tests/test_chaos_failover.py
    # and tests/test_plane_handoff.py exercise them on real clusters)
    "es_search_retries_total":
        "cluster coordinators only (search copy failover)",
    "es_shard_failovers_total":
        "cluster masters only (dead-node primary promotion)",
    "es_recovery_bytes_total":
        "cluster recovery only (plane handoff / translog replay)",
    "es_plane_handoff_ms":
        "cluster recovery only (warm plane handoff import)",
}

_DOC_NAME_RE = re.compile(r"`(es_[a-z0-9_]+)`")
_REF_NAME_RE = re.compile(r"\bes_[a-z0-9_]+")

HEALTH_MODULE = re.compile(r"(^|\.)common\.health$")


def documented_families(path: str) -> Set[str]:
    """Every backticked ``es_*`` family name in TELEMETRY.md."""
    if not os.path.exists(path):
        return set()
    with open(path) as f:
        return set(_DOC_NAME_RE.findall(f.read()))


def runtime_families() -> Set[str]:
    """Register every producible family by exercising the real stack:
    REST + index + text/kNN plane dispatch + delta tier + sync repack +
    forced jitted dispatch + IVF tier + block-max tier + a lockdep
    witness pair (so the ``es_lockdep_*`` families land in the registry
    the same deterministic way)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from elasticsearch_tpu.common import lockdep, telemetry
    from elasticsearch_tpu.node.indices_service import IndicesService
    from elasticsearch_tpu.rest.api import RestAPI

    with tempfile.TemporaryDirectory() as d:
        api = RestAPI(IndicesService(d))
        api.handle("PUT", "/lint", "", json.dumps(
            {"mappings": {"properties": {
                "body": {"type": "text"},
                "tag": {"type": "keyword"},
                "price": {"type": "double"},
                "vec": {"type": "dense_vector", "dims": 4}}}}).encode())
        api.handle("PUT", "/lint/_doc/1", "refresh=true", json.dumps(
            {"body": "quick brown fox", "tag": "a", "price": 3.0,
             "vec": [1, 0, 0, 0]}).encode())
        # text plane dispatch (+ latency family with exemplar); the
        # X-Opaque-Id header registers the per-tenant es_tenant_*
        # attribution rollup the same deterministic way
        api.handle("POST", "/lint/_search", "", json.dumps(
            {"query": {"match": {"body": "quick"}}}).encode(),
            headers={"X-Opaque-Id": "lint-tenant"})
        # plane-path request cache hit/miss counters
        api.handle("POST", "/lint/_search", "", json.dumps(
            {"query": {"match": {"body": "quick"}}}).encode())
        # kNN plane dispatch
        api.handle("POST", "/lint/_search", "", json.dumps(
            {"knn": {"field": "vec", "query_vector": [1, 0, 0, 0],
                     "k": 1, "num_candidates": 5}}).encode())
        # fused one-dispatch planner: a lowerable hybrid RRF body runs
        # lexical + knn + fusion as ONE dispatch and registers the
        # es_planner_* families (lowered counter + stage histogram)
        api.handle("POST", "/lint/_search", "", json.dumps(
            {"query": {"match": {"body": "quick"}},
             "knn": {"field": "vec", "query_vector": [1, 0, 0, 0],
                     "k": 1, "num_candidates": 5},
             "rank": {"rrf": {"rank_window_size": 5}}}).encode())
        # fused AGG stages: an agg-carrying lowerable body rides the
        # same planner dispatch and registers the es_agg_* families
        # (stage histogram + sketch-merge kinds); DEVICE_MIN_PAIRS is
        # shrunk for the call so the device kernel call sites register
        # es_agg_device_pairs_total on this one-doc corpus too
        from elasticsearch_tpu.ops import aggs as _ops_aggs
        _mp = _ops_aggs.DEVICE_MIN_PAIRS
        _ops_aggs.DEVICE_MIN_PAIRS = 1
        try:
            api.handle("POST", "/lint/_search", "request_cache=false",
                       json.dumps(
                           {"query": {"match": {"body": "quick"}},
                            "size": 0, "aggs": {
                                "tags": {"terms": {"field": "tag"}},
                                "n": {"cardinality": {
                                    "field": "price"}}}}).encode())
        finally:
            _ops_aggs.DEVICE_MIN_PAIRS = _mp
        # delta tier + sync repack path (delta-serve + rebuild families)
        svc = api.indices.get("lint")
        svc.plane_cache.repack_mode = "sync"
        # force the block-max tier onto the repacked generation so the
        # es_lex_* families register: a pruned dispatch (track_total_hits
        # bounded → prune defaults on) and an explicit prune=off (the
        # drift counter the plane_serving health indicator reads)
        svc.plane_cache.lex_prune_min_docs = 1
        api.handle("PUT", "/lint/_doc/2", "refresh=true", json.dumps(
            {"body": "quick red fox"}).encode())
        api.handle("POST", "/lint/_search", "", json.dumps(
            {"query": {"match": {"body": "quick"}}}).encode())
        # second delta doc pushes past REPACK_DELTA_FRACTION: the sync
        # repack folds the delta into a fresh base that now carries the
        # block-max tier (lex_prune_min_docs=1 above)
        api.handle("PUT", "/lint/_doc/3", "refresh=true", json.dumps(
            {"body": "quick blue fox"}).encode())
        api.handle("POST", "/lint/_search", "request_cache=false",
                   json.dumps({"query": {"match": {"body": "quick"}},
                               "track_total_hits": 10}).encode())
        api.handle("POST", "/lint/_search", "request_cache=false",
                   json.dumps({"query": {"match": {"body": "quick"}},
                               "prune": False}).encode())
        # storage-tier cycle: demote the live text generation to warm
        # and promote it straight back — one round trip registers the
        # es_plane_tier_{promotions,demotions}_total counters (full
        # label space is pre-created on first transition) while the
        # es_plane_tier_bytes gauge rides the tier manager's object
        # collector
        _tgen = svc.plane_cache.generations()[0]
        svc.plane_cache.tiers.demote_to_warm(_tgen, reason="lint")
        svc.plane_cache.tiers._promote(_tgen)
        # forced jitted dispatch so the XLA compile/transfer families
        # register even on the CPU test backend (host-eager otherwise)
        import numpy as np
        from elasticsearch_tpu.parallel import (DistributedSearchPlane,
                                                make_search_mesh)
        from elasticsearch_tpu.utils.synth import synthetic_csr_corpus_fast
        import jax
        rng = np.random.RandomState(7)
        corpus = synthetic_csr_corpus_fast(rng, 128, 64, 8, zipf_s=1.2)
        corpus["term_ids"] = {f"t{t}": t for t in range(64)}
        mesh = make_search_mesh(n_shards=1, n_replicas=1,
                                devices=jax.devices()[:1])
        # register the serving-owner gauge family for the catalogue
        # cross-check (make_search_mesh itself deliberately doesn't
        # write it — only serving-mesh owners do)
        from elasticsearch_tpu.parallel.mesh import record_mesh_devices
        record_mesh_devices(1, 0)
        plane = DistributedSearchPlane(mesh, [corpus], field="body")
        plane._host_csr = None
        plane.serve([["t1"]], k=4, with_totals=True)
        # warm-tier streamed dispatch: demote the jitted plane's corpus
        # to host and re-serve — the per-dispatch device_put stream
        # registers es_plane_tier_stream_bytes_total and the *_streamed
        # roofline kernel family
        plane.demote_to_warm()
        plane.serve([["t1"]], k=4, with_totals=True)
        # IVF (cluster-pruned ANN) dispatch: registers the es_ann_*
        # families (clusters probed / candidates re-ranked / bytes per
        # tier), plus the nprobe-below-default drift counter the
        # plane_serving health indicator reads
        from elasticsearch_tpu.parallel.dist_search import \
            DistributedKnnPlane
        kvecs = rng.randn(256, 8).astype(np.float32)
        kplane = DistributedKnnPlane(
            mesh, [dict(vectors=kvecs)], similarity="cosine",
            ivf=dict(nlist=8, seed=0))
        kplane.serve(np.zeros((2, 8), np.float32), k=3)
        kplane.serve(np.zeros((1, 8), np.float32), k=3, nprobe=1)
        # lockdep witness: a nested acquisition through two witnessed
        # locks registers the es_lockdep_* families (depth, hold time,
        # inversions) without needing ES_TPU_LOCKDEP in the environment
        outer = lockdep.witness_lock("lint-outer")
        inner = lockdep.witness_lock("lint-inner")
        with outer:
            with inner:
                pass
        # racedep witness: register the es_racedep_* evidence families
        # the same deterministic way — collector + one tracked access
        # pair (single-threaded: records evidence, never a candidate)
        from elasticsearch_tpu.common import racedep
        racedep.ensure_collector()
        racedep.WITNESS.access(("lint-race-key", 0), write=True)
        racedep.WITNESS.access(("lint-race-key", 0), write=False)
        # flight recorder + SLO watchdog: the searches above already
        # journaled events (plane rebuilds); a thread-less watchdog
        # instance ticks once (burn gauges + capture counter label
        # space) and seeds one manual capture so es_flightrec_* /
        # es_watchdog_* / es_slo_burn_rate register deterministically
        from elasticsearch_tpu.common import flightrec
        flightrec.record("lint_probe", source="telemetry-lint")
        wd = flightrec.Watchdog()
        wd.tick()
        wd.capture("manual")
        wd.close()
        # continuous-profiler round: a thread-less sampler drives one
        # sampled window synchronously (es_contprof_* families register
        # deterministically — no cadence race) and the endpoint read
        # exercises the REST surface the same way as insights below
        from elasticsearch_tpu.common import contprof
        prof = contprof.ContinuousProfiler(interval_ms_=1.0)
        prof.sample_once()
        prof.sample_once()
        prof.top_doc(window="both")
        api.handle("GET", "/_profiler/flamegraph",
                   "window=both&limit=8", None)
        # query-insights round: the searches above already folded into
        # the heavy-hitter store (es_insight_* families); read both new
        # observability endpoints so the whole insight surface — store,
        # history ring (fed by the watchdog tick above:
        # es_history_samples_total / es_history_series), REST layer —
        # runs under the lint the same deterministic way
        api.handle("GET", "/_insights/top_queries",
                   "metric=device_ms", None)
        api.handle("GET", "/_telemetry/history",
                   "family=es_query_latency_ms&window=raw&rate=true",
                   None)
        # multi-tenant QoS round: the searches above were all ADMITTED
        # (es_qos_admitted_total / es_qos_tokens); drive both rejection
        # paths too — charge the lint tenant into token debt so its
        # next request throttles 429, then trip the shed state machine
        # so an analytics-class request sheds 429 — and reset the
        # process controller so the synthetic debt/engagement cannot
        # leak into other suites sharing this process
        from elasticsearch_tpu.common import qos as _qos
        ctl = _qos.controller()
        ctl.charge("lint-tenant", cpu_ms=0.0, device_ms=1e9, bytes_=0)
        api.handle("POST", "/lint/_search", "", json.dumps(
            {"query": {"match": {"body": "quick"}}}).encode(),
            headers={"X-Opaque-Id": "lint-tenant"})
        ctl.note_signals(queue_depth=10 ** 6, burn_status="red",
                         breaker_fraction=1.0)
        api.handle("POST", "/lint/_search", "", json.dumps(
            {"query": {"match": {"body": "quick"}},
             "size": 0}).encode(),
            headers={"X-Opaque-Id": "lint-shed-tenant"})
        _qos.reset_controller()

        snap = telemetry.DEFAULT.stats_doc()
        return {name for name in snap if name.startswith("es_")}


def referenced_families(project: Project):
    """(family, file, line) for every ``es_*`` token in a string literal
    of ``common/health.py`` — indicator details and diagnosis prose."""
    import ast
    out = []
    for mod in project.modules.values():
        if not HEALTH_MODULE.search(mod.dotted):
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str):
                for name in _REF_NAME_RE.findall(node.value):
                    out.append((name, mod.relpath, node.lineno))
    return out


def catalogue_drift(documented: Set[str], runtime_set: Set[str]):
    """The three-way comparison both the estpulint gate and the
    telemetry_lint shim render: (undocumented, stale, phantom) — one
    copy of the semantics so the two entry points can never diverge."""
    undocumented = sorted(runtime_set - documented)
    stale = sorted(documented - runtime_set - set(CONDITIONAL))
    phantom = sorted(set(CONDITIONAL) & runtime_set)
    return undocumented, stale, phantom


def check(project: Project, runtime: bool = True,
          telemetry_md: Optional[str] = None) -> List[Finding]:
    md_path = telemetry_md or os.path.join(project.root, "TELEMETRY.md")
    documented = documented_families(md_path)
    findings: List[Finding] = []
    md_rel = os.path.relpath(md_path, project.root)
    if runtime:
        undocumented, stale, _phantom = catalogue_drift(
            documented, runtime_families())
        for name in undocumented:
            findings.append(Finding(
                RULE_C01, md_rel, 0, "catalogue", f"undocumented {name}",
                f"runtime-registered family [{name}] has no TELEMETRY.md "
                f"row — add one (name, type, labels, meaning)"))
        for name in stale:
            findings.append(Finding(
                RULE_C02, md_rel, 0, "catalogue", f"stale {name}",
                f"documented family [{name}] is never registered by the "
                f"lint workload — remove the row or add a CONDITIONAL "
                f"entry with a reason"))
    seen: Set[str] = set()
    for name, relpath, line in referenced_families(project):
        if name in documented or name in seen:
            continue
        seen.add(name)
        findings.append(Finding(
            RULE_C03, relpath, line, "health-indicators",
            f"unknown family {name}",
            f"health-indicator text references [{name}], which "
            f"TELEMETRY.md does not document — operators would be "
            f"pointed at a metric that does not exist"))
    return findings


def main(repo_root: Optional[str] = None) -> int:
    """The ``scripts/telemetry_lint.py`` entry: same output contract as
    the original standalone lint (UNDOCUMENTED / STALE / note lines,
    rc 1 on drift)."""
    # .../repo/elasticsearch_tpu/devtools/rules_catalogue.py -> repo
    root = repo_root or os.path.abspath(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..", ".."))
    documented = documented_families(os.path.join(root, "TELEMETRY.md"))
    runtime = runtime_families()
    undocumented, stale, phantom = catalogue_drift(documented, runtime)
    rc = 0
    if undocumented:
        rc = 1
        print("UNDOCUMENTED runtime families (add TELEMETRY.md rows):",
              file=sys.stderr)
        for n in undocumented:
            print(f"  {n}", file=sys.stderr)
    if stale:
        rc = 1
        print("STALE documented families (never registered by the lint "
              "workload; remove the row or add a CONDITIONAL entry with "
              "a reason):", file=sys.stderr)
        for n in stale:
            print(f"  {n}", file=sys.stderr)
    if phantom:
        # informational only: the process-scoped registry may carry
        # families from OTHER stacks in this process (a cluster test
        # that ran earlier in the same pytest session) — documented +
        # registered is never drift
        print("note: CONDITIONAL families present in this process: "
              + ", ".join(phantom))
    if rc == 0:
        print(f"telemetry lint OK: {len(runtime)} runtime families "
              f"match TELEMETRY.md ({len(CONDITIONAL)} conditional)")
    return rc
