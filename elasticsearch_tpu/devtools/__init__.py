"""estpulint — project-wide static analysis for jit-boundary hygiene,
lock-order safety, and telemetry-catalogue discipline.

The engine is a heavily threaded serving system layered over jitted JAX
hot paths, and its two recurring failure modes — accidental host
synchronization inside the dispatch path, and compile churn from
unbucketed static shapes — were until now caught only after the fact by
the compile-ratchet and stage timings. This package machine-checks those
invariants before merge (the way Anserini ships rank-regression gates
instead of hoping reviewers notice), plus the lock discipline the
dispatcher/repack/ledger threads depend on.

Three rule families (see STATIC_ANALYSIS.md for the full catalogue):

- ``rules_jit`` (ESTP-J*) — host-sync constructs reachable from device
  hot paths, impure host calls inside jit-compiled code, mutable default
  captures, and unbucketed static-shape arguments at step call sites.
- ``rules_locks`` (ESTP-L*) — the package-wide lock-acquisition graph
  must be cycle-free, and telemetry/tracing must never execute under a
  serving lock. Cross-checked at runtime by the opt-in lockdep witness
  (``common/lockdep.py``, ``ES_TPU_LOCKDEP=1``).
- ``rules_catalogue`` (ESTP-C*) — registry families, TELEMETRY.md rows,
  and health-indicator diagnoses stay three-way consistent (the
  generalization of the old ``scripts/telemetry_lint.py``).

Entry point: ``scripts/estpulint.py`` (CLI with ``--diff`` and a
checked-in zero-new-findings baseline, ``ESTPULINT_BASELINE.json``);
the full-package scan rides tier-1 via ``tests/test_static_analysis.py``.
"""

from .analyzer import Finding, Project, scan_project  # noqa: F401
