"""Rule family 4 — lockset data-race analysis (ESTP-R*/T*).

PR 8's lock rules keep the acquisition graph cycle-free — lock
*ordering*. Nothing checked lock *coverage*: the package now has at
least six long-lived thread roots (micro-batch dispatcher threads, the
background repack/warmup threads, engine refresh listeners, the health
fan-in executor, the monitoring collector, REST handler threads)
sharing mutable plane/cache/stats state, and a data race there corrupts
results silently instead of deadlocking loudly. This family is the
Eraser-style static half (the runtime half is ``common/racedep.py``,
the happens-before witness under ``ES_TPU_RACEDEP=record|raise``):

- **ESTP-R01 unguarded-shared-state** — an attribute (``self.<attr>``
  with a declaration site, or a ``global``-declared module var)
  reachable from ≥2 distinct thread roots, written outside
  ``__init__``, whose access sites have an EMPTY lockset intersection:
  no single lock protects every access, so two roots can interleave
  mid-update.
- **ESTP-R02 check-then-act** — guarded state read under lock L inside
  one function, then written later in the same function after L was
  released: the decision made under the lock is stale by the time the
  write lands (the classic lost-update shape).
- **ESTP-T01 unjoined-thread-lifecycle** — a thread/executor started in
  ``__init__``/``start``/``open`` of a class that has no
  close/stop/shutdown/release-like method joining or shutting it down:
  the thread outlives its owner and keeps touching freed state.

Thread-root discovery walks the project model for
``threading.Thread(target=...)``, ``<executor>.submit(fn, ...)``,
listener registrations (``*listener*.append(self._cb)``) and telemetry
collector registrations (``register_collector``/
``register_object_collector``), plus the synthetic REQUEST root (every
function named ``handle`` — the REST edge, served by a thread pool).
Each root's reachable set comes from the shared conservative call graph.

Lockset inference reuses the ESTP-L lock table (declaration-site lock
nodes, ``module:Class.attr`` identity, Condition aliasing) and adds
entry-lockset propagation: the locks a function is guaranteed to hold
on entry are the INTERSECTION over all its static call sites of (locks
held at the site ∪ the caller's own entry set) — a lock counts as
covering an access only when it is held on EVERY path, so the rule
under-approximates coverage and over-approximates races; benign races
(monotonic flags, double-checked creation) are baselined with
justifications rather than silenced in code.

Known limits (conservative, documented): accesses through unresolvable
receivers contribute no site; lambdas are invisible roots; per-instance
disjointness (two instances never shared) is not modeled — instance
identity is the declaration site, same as the lock rules.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .analyzer import Finding, FunctionInfo, Project
from .rules_locks import LockTable, build_lock_table, resolve_lock_expr

RULE_R01 = "ESTP-R01"
RULE_R02 = "ESTP-R02"
RULE_T01 = "ESTP-T01"

#: the synthetic request root: REST handler threads all enter here
REQUEST_ROOT_NAMES = {"handle"}

#: spawn method names ESTP-T01 treats as owner lifecycle starts
_T01_SPAWN_METHODS = {"__init__", "start", "open"}

#: method-name prefixes that count as the owner's teardown surface
_T01_CLOSE_RE = re.compile(
    r"^(close|stop|shutdown|release|drain|join|__exit__|__del__|retire)")

#: attribute method calls that MUTATE the receiver (a write access)
_MUTATORS = {
    "append", "extend", "add", "update", "pop", "popitem", "clear",
    "remove", "discard", "insert", "setdefault", "move_to_end",
    "appendleft", "popleft", "sort", "reverse",
}

#: receiver attrs that look like listener/callback registries
_LISTENER_ATTR_RE = re.compile(r"listener|callback|hook")

_COLLECTOR_REG_NAMES = {"register_collector", "register_object_collector"}


# ---------------------------------------------------------------------------
# Shared-state table (mirror of rules_locks.LockTable for plain attrs)
# ---------------------------------------------------------------------------


class StateTable:
    """Every mutable-state declaration site: ``self.<attr> = ...``
    anywhere in a class (excluding lock/Condition attrs — those are the
    guards, not the guarded) and module globals rebound through a
    ``global`` statement."""

    def __init__(self):
        #: class_fqn -> {attr: state_id}
        self.class_attrs: Dict[str, Dict[str, str]] = {}
        #: (module_dotted, var) -> state_id
        self.module_vars: Dict[Tuple[str, str], str] = {}
        #: attr -> {state_id} (unique-name fallback for non-self receivers)
        self.attr_index: Dict[str, Set[str]] = {}


def owner_class(project: Project, fn: FunctionInfo) -> Optional[str]:
    """The class whose instance ``self`` names inside ``fn`` — the
    direct class for methods, the ENCLOSING method's class for closures
    nested in a method (``self`` is a closure cell there: the repack
    thread bodies, the warmup thunk)."""
    if fn.class_fqn:
        return fn.class_fqn
    parts = fn.qual.split(".")
    for i in range(len(parts) - 1, 0, -1):
        cand = f"{fn.module.dotted}:" + ".".join(parts[:i])
        if cand in project.classes:
            return cand
    return None


def build_state_table(project: Project, locks: LockTable) -> StateTable:
    table = StateTable()
    lock_ids: Set[str] = set(locks.node_module)
    for fn in project.functions.values():
        cls = owner_class(project, fn)
        if cls is None:
            continue
        cls_qual = cls.split(":", 1)[1]
        lock_attrs = locks.class_attrs.get(cls, {})
        for node in ast.walk(fn.node):
            tgt = None
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        tgt = t
                        break
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)) and \
                    isinstance(node.target, ast.Attribute) and \
                    isinstance(node.target.value, ast.Name) and \
                    node.target.value.id == "self":
                tgt = node.target
            if tgt is None:
                continue
            attr = tgt.attr
            if attr in lock_attrs:
                continue        # guards are not guarded state
            sid = f"{fn.module.dotted}:{cls_qual}.{attr}"
            if sid in lock_ids:
                continue
            table.class_attrs.setdefault(cls, {})[attr] = sid
            table.attr_index.setdefault(attr, set()).add(sid)
    for mod in project.modules.values():
        module_names = {
            s.targets[0].id for s in mod.tree.body
            if isinstance(s, ast.Assign) and len(s.targets) == 1 and
            isinstance(s.targets[0], ast.Name)}
        for fn in project.functions.values():
            if fn.module is not mod:
                continue
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Global):
                    for name in node.names:
                        if name in module_names and \
                                (mod.dotted, name) not in locks.module_locks:
                            sid = f"{mod.dotted}:{name}"
                            table.module_vars[(mod.dotted, name)] = sid
                            table.attr_index.setdefault(name, set()) \
                                .add(sid)
    return table


def _attr_of(node: ast.AST) -> Optional[Tuple[ast.AST, str]]:
    """(receiver expr, attr name) for an attribute access — plain
    ``x.attr`` or ``getattr(x, "attr"[, default])``."""
    if isinstance(node, ast.Attribute):
        return node.value, node.attr
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id == "getattr" and len(node.args) >= 2 and \
            isinstance(node.args[1], ast.Constant) and \
            isinstance(node.args[1].value, str):
        return node.args[0], node.args[1].value
    return None


def resolve_state_expr(project: Project, table: StateTable,
                       fn: FunctionInfo, receiver: ast.AST,
                       attr: str) -> Optional[str]:
    """State id of ``receiver.attr`` — ``self`` through the (possibly
    enclosing) class, everything else through the unique-attr fallback,
    mirroring lock resolution so the two tables line up."""
    if isinstance(receiver, ast.Name) and receiver.id in ("self", "cls"):
        cls = owner_class(project, fn)
        seen: Set[str] = set()
        while cls is not None and cls not in seen:
            seen.add(cls)
            hit = table.class_attrs.get(cls, {}).get(attr)
            if hit:
                return hit
            ci = project.classes.get(cls)
            if ci is None or not ci.bases:
                return None
            bci = project._resolve_class(ci.bases[0].split(".")[-1],
                                         ci.module)
            cls = bci.fqn if bci is not None else None
        return None
    # unique-attr fallback, PRIVATE attrs only: a public name like
    # ``used`` collides with foreign objects (shutil's disk_usage) and
    # would invent cross-class races
    if attr.startswith("_"):
        cands = table.attr_index.get(attr, ())
        if len(cands) == 1:
            return next(iter(cands))
    return None


# ---------------------------------------------------------------------------
# Thread-root discovery
# ---------------------------------------------------------------------------


def _resolve_func_ref(project: Project, fn: FunctionInfo,
                      expr: ast.AST) -> Optional[str]:
    """A function REFERENCE (not a call): ``target=_run``,
    ``pool.submit(self._apply)``, ``listeners.append(self._on_refresh)``."""
    if isinstance(expr, ast.Name):
        parts = fn.qual.split(".")
        for i in range(len(parts), -1, -1):
            cand = f"{fn.module.dotted}:" + \
                ".".join(parts[:i] + [expr.id]) if i else \
                f"{fn.module.dotted}:{expr.id}"
            if cand in project.functions:
                return cand
        tgt = fn.module.imports.get(expr.id)
        if tgt and "." in tgt:
            m, _, attr = tgt.rpartition(".")
            cand = f"{m}:{attr}"
            if cand in project.functions:
                return cand
        return None
    if isinstance(expr, ast.Attribute):
        base = expr.value
        if isinstance(base, ast.Name) and base.id in ("self", "cls"):
            cls = owner_class(project, fn)
            if cls is not None:
                m = project._mro_methods(project.classes[cls]) \
                    if cls in project.classes else {}
                return m.get(expr.attr)
            return None
        if isinstance(base, ast.Name):
            # Class.method (register_object_collector style) or module.fn
            ci = project._resolve_class(base.id, fn.module)
            if ci is not None:
                return project._mro_methods(ci).get(expr.attr)
            tgt = fn.module.imports.get(base.id)
            if tgt and tgt in project.modules:
                cand = f"{tgt}:{expr.attr}"
                if cand in project.functions:
                    return cand
    return None


class ThreadRoot:
    __slots__ = ("fqn", "kind", "site")

    def __init__(self, fqn: str, kind: str, site: str):
        self.fqn = fqn          # entry function
        self.kind = kind        # thread | executor | listener | request
        self.site = site        # "file:line" of the spawn/registration

    @property
    def display(self) -> str:
        return f"{self.kind}:{self.fqn.split(':', 1)[1]}"


def discover_thread_roots(project: Project) -> List[ThreadRoot]:
    roots: Dict[str, ThreadRoot] = {}

    def add(fqn: Optional[str], kind: str, fn: FunctionInfo,
            line: int) -> None:
        if fqn is None or fqn in roots:
            return
        roots[fqn] = ThreadRoot(fqn, kind,
                                f"{fn.module.relpath}:{line}")

    for fn in project.functions.values():
        for cs in fn.calls:
            call = cs.node
            callee = call.func
            name = callee.attr if isinstance(callee, ast.Attribute) else \
                callee.id if isinstance(callee, ast.Name) else None
            if name == "Thread":
                for kw in call.keywords:
                    if kw.arg == "target":
                        add(_resolve_func_ref(project, fn, kw.value),
                            "thread", fn, call.lineno)
            elif name == "submit" and isinstance(callee, ast.Attribute) \
                    and call.args:
                add(_resolve_func_ref(project, fn, call.args[0]),
                    "executor", fn, call.lineno)
            elif name == "append" and isinstance(callee, ast.Attribute) \
                    and isinstance(callee.value, ast.Attribute) and \
                    _LISTENER_ATTR_RE.search(callee.value.attr) and \
                    call.args:
                add(_resolve_func_ref(project, fn, call.args[0]),
                    "listener", fn, call.lineno)
            elif name in _COLLECTOR_REG_NAMES and call.args:
                # last arg is the producer (fn for register_collector,
                # Class.method for register_object_collector)
                add(_resolve_func_ref(project, fn, call.args[-1]),
                    "listener", fn, call.lineno)
    for fqn, fn in project.functions.items():
        if fn.name in REQUEST_ROOT_NAMES and fqn not in roots:
            roots[fqn] = ThreadRoot(fqn, "request",
                                    f"{fn.module.relpath}:{fn.line}")
    return list(roots.values())


def roots_reaching(project: Project, roots: List[ThreadRoot]) \
        -> Dict[str, Set[str]]:
    """fn fqn → set of root fqns whose reachable set contains it."""
    out: Dict[str, Set[str]] = {}
    for r in roots:
        for fqn in project.reachable_from([r.fqn]):
            out.setdefault(fqn, set()).add(r.fqn)
    return out


# ---------------------------------------------------------------------------
# Access-site scan + entry-lockset propagation
# ---------------------------------------------------------------------------


class AccessSite:
    __slots__ = ("state", "kind", "held", "fn", "line")

    def __init__(self, state: str, kind: str, held: Tuple[str, ...],
                 fn: FunctionInfo, line: int):
        self.state = state
        self.kind = kind        # "r" | "w"
        self.held = held        # locally-held lock nodes (static path)
        self.fn = fn
        self.line = line


class _FnRaceFacts:
    __slots__ = ("accesses", "calls")

    def __init__(self):
        self.accesses: List[AccessSite] = []
        #: (held lock tuple, ast.Call) — EVERY call, for entry-lockset
        #: propagation (unlike rules_locks, empty-held calls matter here)
        self.calls: List[Tuple[Tuple[str, ...], ast.Call]] = []


def _scan_accesses(project: Project, locks: LockTable, states: StateTable,
                   fn: FunctionInfo) -> _FnRaceFacts:
    facts = _FnRaceFacts()

    def state_of(expr: ast.AST) -> Optional[str]:
        pair = _attr_of(expr)
        if pair is None:
            if isinstance(expr, ast.Name):
                return states.module_vars.get(
                    (fn.module.dotted, expr.id))
            return None
        return resolve_state_expr(project, states, fn, pair[0], pair[1])

    def record(expr: ast.AST, kind: str, held: Tuple[str, ...],
               line: int) -> None:
        sid = state_of(expr)
        if sid is not None:
            facts.accesses.append(AccessSite(sid, kind, held, fn, line))

    def rec(node: ast.AST, held: Tuple[str, ...]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            newly: List[str] = []
            for item in node.items:
                rec(item.context_expr, held)
                lk = resolve_lock_expr(project, locks, fn,
                                       item.context_expr)
                if lk is not None:
                    newly.append(lk)
            inner = held + tuple(newly)
            for stmt in node.body:
                rec(stmt, inner)
            return
        if isinstance(node, ast.Assign):
            for t in node.targets:
                _walk_target(t, held, node.lineno)
            rec(node.value, held)
            return
        if isinstance(node, ast.AugAssign):
            record(node.target, "w", held, node.lineno)
            rec(node.value, held)
            return
        if isinstance(node, ast.Delete):
            for t in node.targets:
                tgt = t.value if isinstance(t, ast.Subscript) else t
                record(tgt, "w", held, node.lineno)
            return
        if isinstance(node, ast.Call):
            facts.calls.append((held, node))
            pair = _attr_of(node.func) if isinstance(node.func,
                                                     ast.Attribute) \
                else None
            if pair is not None and node.func.attr in _MUTATORS:
                # self.attr.append(x): mutates the attr's value
                record(pair[0], "w", held, node.lineno)
            elif isinstance(node.func, ast.Name) and \
                    node.func.id == "getattr":
                record(node, "r", held, node.lineno)
        elif isinstance(node, ast.Attribute) and \
                isinstance(node.ctx, ast.Load):
            record(node, "r", held, node.lineno)
        elif isinstance(node, ast.Name) and \
                isinstance(node.ctx, ast.Load):
            record(node, "r", held, node.lineno)
        for child in ast.iter_child_nodes(node):
            rec(child, held)

    def _walk_target(t: ast.AST, held: Tuple[str, ...],
                     line: int) -> None:
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                _walk_target(e, held, line)
            return
        if isinstance(t, ast.Starred):
            _walk_target(t.value, held, line)
            return
        if isinstance(t, ast.Subscript):
            # self.attr[k] = v mutates attr's value; also scan the index
            record(t.value, "w", held, line)
            rec(t.slice, held)
            return
        if isinstance(t, (ast.Attribute, ast.Name)):
            record(t, "w", held, line)

    for stmt in fn.node.body:
        rec(stmt, ())
    return facts


def entry_locksets(project: Project,
                   facts: Dict[str, _FnRaceFacts],
                   roots: List[ThreadRoot]) -> Dict[str, Set[str]]:
    """Locks guaranteed held on ENTRY to each function: the intersection
    over all static call sites of (site-held ∪ caller's entry set).
    Roots enter with nothing held. Fixpoint from ⊤ (None = not yet
    constrained)."""
    entry: Dict[str, Optional[Set[str]]] = {
        fqn: None for fqn in project.functions}
    for r in roots:
        entry[r.fqn] = set()
    # resolve each call once; the fixpoint then only re-walks tuples
    resolved: Dict[str, List[Tuple[Tuple[str, ...], Tuple[str, ...]]]] = {}
    for fqn, f in facts.items():
        fn = project.functions[fqn]
        rows = []
        for held, call in f.calls:
            tgts = tuple(project.resolve_call(fn, call))
            if tgts:
                rows.append((held, tgts))
        resolved[fqn] = rows
    changed = True
    while changed:
        changed = False
        for fqn, rows in resolved.items():
            base = entry.get(fqn)
            caller_entry = base if base is not None else set()
            for held, tgts in rows:
                eff = set(held) | caller_entry
                for tgt in tgts:
                    cur = entry.get(tgt)
                    new = eff if cur is None else (cur & eff)
                    if new != cur:
                        entry[tgt] = new
                        changed = True
    return {fqn: (s if s is not None else set())
            for fqn, s in entry.items()}


# ---------------------------------------------------------------------------
# ESTP-R01: empty lockset intersection on multi-root shared state
# ---------------------------------------------------------------------------


def _check_shared_state(project: Project, roots: List[ThreadRoot],
                        reach: Dict[str, Set[str]],
                        facts: Dict[str, _FnRaceFacts],
                        entry: Dict[str, Set[str]]) -> List[Finding]:
    by_root = {r.fqn: r for r in roots}
    per_state: Dict[str, List[Tuple[AccessSite, Set[str], Set[str]]]] = {}
    for fqn, f in facts.items():
        fn_roots = reach.get(fqn)
        if not fn_roots:
            continue
        fn_entry = entry.get(fqn, set())
        for a in f.accesses:
            if a.fn.name in ("__init__", "__new__"):
                continue        # pre-publication: the object isn't
            # shared until the constructor returns
            lockset = set(a.held) | fn_entry
            per_state.setdefault(a.state, []).append(
                (a, lockset, fn_roots))
    findings: List[Finding] = []
    for state, sites in sorted(per_state.items()):
        writes = [s for s in sites if s[0].kind == "w"]
        if not writes:
            continue
        all_roots: Set[str] = set()
        for _, _, rs in sites:
            all_roots |= rs
        if len(all_roots) < 2:
            continue
        # a race needs a write and another access from a DIFFERENT root
        write_roots: Set[str] = set()
        for _, _, rs in writes:
            write_roots |= rs
        if len(write_roots) < 2 and \
                not any(rs - write_roots for _, _, rs in sites):
            continue
        common = None
        for _, lockset, _ in sites:
            common = lockset if common is None else (common & lockset)
            if not common:
                break
        if common:
            continue            # every access shares ≥1 lock: guarded
        w = writes[0][0]
        unlocked = next((s for s in sites if not s[1]), None)
        witness = unlocked[0] if unlocked is not None else w
        root_names = sorted(by_root[r].display for r in all_roots)[:4]
        findings.append(Finding(
            RULE_R01, w.fn.module.relpath, w.line, state,
            "unguarded shared state (empty lockset intersection)",
            f"shared mutable state [{state}] is reachable from "
            f"{len(all_roots)} thread roots ({', '.join(root_names)}"
            f"{', …' if len(all_roots) > 4 else ''}) with ≥1 write but "
            f"no lock held across every access (e.g. "
            f"{witness.fn.qual}:{witness.line} accesses it "
            f"{'unlocked' if unlocked is not None else 'under a disjoint lockset'}) "
            f"— two roots can interleave mid-update and corrupt it "
            f"silently; guard every access with one lock or baseline "
            f"with a benign-race justification"))
    return findings


# ---------------------------------------------------------------------------
# ESTP-R02: check-then-act on guarded state
# ---------------------------------------------------------------------------


def _check_check_then_act(project: Project,
                          facts: Dict[str, _FnRaceFacts],
                          reach: Dict[str, Set[str]]) -> List[Finding]:
    findings: List[Finding] = []
    seen: Set[Tuple[str, str, str]] = set()
    for fqn, f in facts.items():
        if not reach.get(fqn):
            continue            # single-threaded helpers can't lose the race
        fn = project.functions[fqn]
        if fn.name in ("__init__", "__new__"):
            continue
        reads = [a for a in f.accesses if a.kind == "r" and a.held]
        if not reads:
            continue
        writes = [a for a in f.accesses if a.kind == "w"]
        for r in reads:
            for w in writes:
                if w.state != r.state or w.line <= r.line:
                    continue
                if any(lk in w.held for lk in r.held):
                    continue    # still holding (or re-holding) the guard
                key = (fqn, r.state, r.held[0])
                if key in seen:
                    continue
                seen.add(key)
                findings.append(Finding(
                    RULE_R02, fn.module.relpath, w.line, fn.qual,
                    f"check-then-act on [{r.state}] guarded by "
                    f"[{r.held[0]}]",
                    f"[{r.state}] is read under [{r.held[0]}] at line "
                    f"{r.line} but written at line {w.line} after the "
                    f"lock is released — the decision is stale by the "
                    f"time the write lands; widen the critical section "
                    f"or re-validate under the lock"))
                break
    return findings


# ---------------------------------------------------------------------------
# ESTP-T01: thread/executor lifecycle
# ---------------------------------------------------------------------------


def _class_teardown_joins(project: Project, ci) -> bool:
    """True when any close/stop/shutdown-like method of the class
    (transitively through same-class calls) calls ``.join()`` /
    ``.shutdown()`` / ``.cancel()`` or sets a retire/stop flag."""
    methods = project._mro_methods(ci)
    todo = [methods[name] for name in methods
            if _T01_CLOSE_RE.match(name)]
    seen: Set[str] = set()
    while todo:
        cur = todo.pop()
        if cur in seen:
            continue
        seen.add(cur)
        fn = project.functions.get(cur)
        if fn is None:
            continue
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("join", "shutdown", "cancel",
                                       "retire", "stop", "close"):
                return True
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and \
                            re.search(r"retired|stop|closed|shutdown",
                                      t.attr):
                        return True
        for tgt in project.call_targets(cur):
            tfn = project.functions.get(tgt)
            if tfn is not None and tfn.class_fqn == ci.fqn:
                todo.append(tgt)
    return False


def _check_lifecycle(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    seen: Set[Tuple[str, str]] = set()
    for fn in project.functions.values():
        if fn.name not in _T01_SPAWN_METHODS or not fn.class_fqn:
            continue
        spawn = None
        for cs in fn.calls:
            callee = cs.node.func
            name = callee.attr if isinstance(callee, ast.Attribute) else \
                callee.id if isinstance(callee, ast.Name) else None
            if name == "Thread" and any(kw.arg == "target"
                                        for kw in cs.node.keywords):
                spawn = ("thread", cs.line)
            elif name and name.endswith("PoolExecutor"):
                spawn = ("executor", cs.line)
            if spawn:
                break
        if spawn is None:
            continue
        ci = project.classes.get(fn.class_fqn)
        if ci is None or _class_teardown_joins(project, ci):
            continue
        key = (fn.class_fqn, spawn[0])
        if key in seen:
            continue
        seen.add(key)
        findings.append(Finding(
            RULE_T01, fn.module.relpath, spawn[1],
            fn.qual.rsplit(".", 1)[0] or fn.qual,
            f"{spawn[0]} started in {fn.name} with no join/shutdown "
            f"on close",
            f"{ci.name}.{fn.name} starts a {spawn[0]} but no "
            f"close/stop/shutdown-like method of the class joins or "
            f"shuts it down — the {spawn[0]} outlives its owner and "
            f"keeps touching released state; add a teardown that joins "
            f"(or signals and bounds) it"))
    return findings


def check(project: Project) -> List[Finding]:
    locks = build_lock_table(project)
    states = build_state_table(project, locks)
    roots = discover_thread_roots(project)
    reach = roots_reaching(project, roots)
    facts = {fqn: _scan_accesses(project, locks, states, fn)
             for fqn, fn in project.functions.items()}
    entry = entry_locksets(project, facts, roots)
    return (_check_shared_state(project, roots, reach, facts, entry) +
            _check_check_then_act(project, facts, reach) +
            _check_lifecycle(project))
