"""REST front for the multi-node cluster: every node serves the full API.

Reference parity target: every node hosts HTTP
(``http/AbstractHttpServerTransport.java:68``) and dispatches into the
distributed action layer (``rest/RestController.java:196``); metadata
mutations are master actions whose results replicate in cluster state,
document ops route to the owning shard, searches scatter-gather.

TPU-era re-design (NOT a port of the action-per-API class hierarchy):

- **Metadata surface = replicated state machine.** Each node hosts a full
  local :class:`IndicesService`/:class:`RestAPI`. A metadata mutation
  (index create/delete, mappings, settings, aliases, templates, ingest
  pipelines, stored scripts…) forwards the RAW REST request to the elected
  master, which executes it against ITS local service (full validation of
  the whole existing surface, for free) and, on success, appends the
  request to an op log in cluster state. Every node applies the log in
  order to its own local service — deterministic replay ≙ the reference's
  ``MasterService.submitStateUpdateTask`` + state publication, but generic
  over the entire metadata API instead of one action class per op.
- **Document ops** never special-case the REST layer: the local service's
  ``cluster_hooks`` seam routes each (index, shard) write/read through the
  node's replication group when locally primaried, or over the transport
  to the owner. Bulk/mget/update all inherit this by construction.
- **Search** routes through the same seam: an index whose shards are all
  locally primaried searches local engines (and the tiered TPU plane);
  anything else scatter-gathers over the cluster with cluster-wide DFS
  stats (``ClusterNode.search``).
- **Whole-request forwarding** covers stateful/segment-bound reads
  (scroll, explain, termvectors, validate, field_caps…): when one node
  primaries every shard of the referenced indices, the raw request
  executes there with full single-node fidelity.

Known gaps (documented, not hidden): segment-bound reads on indices spread
across nodes fall back to local best-effort; snapshots are node-local; the
op log keeps a bounded tail in state (nodes that fall further behind fetch
history from the master over RPC).
"""

from __future__ import annotations

import base64
import json
import os
import re
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..common import errors as _errors
from ..common.retry import TIMEOUTS, backoff_delays
from ..index.engine import DeleteResult, GetResult, IndexResult
from ..search.shard_search import ShardHit, ShardSearchResult
from ..transport.tcp import RemoteTransportError
from .indices_service import IndicesService

#: op-log tail length carried in cluster state; older history is fetched
#: from the master over RPC (meta:history)
OP_TAIL = 128

_META_SUFFIXES = {
    "_mapping", "_mappings", "_settings", "_alias", "_aliases",
    "_open", "_close", "_rollover", "_shrink", "_split", "_clone",
    "_block", "_freeze", "_unfreeze",
}
_META_ROOTS = ("/_aliases", "/_template", "/_index_template",
               "/_component_template", "/_ingest/pipeline", "/_scripts",
               "/_cluster/settings")
#: segment-bound reads that forward wholesale to a single-owner node
_FORWARD_SUFFIXES = {"_explain", "_termvectors", "_mtermvectors",
                     "_validate", "_field_caps", "_delete_by_query",
                     "_update_by_query"}
#: _refresh is NOT here: IndexService.refresh's cluster hook already
#: reaches every copy; broadcasting it too would fan out O(N^2)
_BROADCAST_SUFFIXES = {"_flush", "_forcemerge"}
#: doc-write routes that may auto-create their target index via master
_DOC_WRITE_SUFFIXES = {"_doc", "_create", "_update", "_bulk"}


def _b64(raw: bytes) -> str:
    return base64.b64encode(raw or b"").decode()


def _unb64(s: str) -> bytes:
    return base64.b64decode(s or "")


def _nodes_predicate(expr: str, n: int) -> bool:
    """wait_for_nodes expressions: "3", ">=2", "<=4", ">1", "<5"."""
    expr = str(expr)
    for op, fn in ((">=", lambda a, b: a >= b), ("<=", lambda a, b: a <= b),
                   (">", lambda a, b: a > b), ("<", lambda a, b: a < b)):
        if expr.startswith(op):
            try:
                return fn(n, int(expr[len(op):]))
            except ValueError:
                return True
    try:
        return n == int(expr)
    except ValueError:
        return True


def _parse_query(query: Optional[str]) -> Dict[str, str]:
    """Decoded query params; bare flags (?v) become "" like parse_qs
    with keep_blank_values can't express — shared by every cluster-front
    handler (rest/api.py has the same shape inline)."""
    from urllib.parse import parse_qs
    out = {k: v[-1] for k, v in parse_qs(
        query or "", keep_blank_values=True).items()}
    for part in (query or "").split("&"):
        if part and "=" not in part:
            out[part] = ""
    return out


def _remote_error(e: RemoteTransportError) -> Exception:
    """Map a remote exception back to its ES error class by name so the
    REST layer renders the same status/type it would for a local failure."""
    cls = getattr(_errors, e.remote_type or "", None)
    reason = getattr(e, "remote_reason", None) or str(e)
    mapped = None
    if isinstance(cls, type) and issubclass(cls, Exception):
        try:
            mapped = cls(reason)
        except Exception:   # noqa: BLE001 — ctor signature mismatch
            mapped = None
    if mapped is None:
        mapped = _errors.ElasticsearchError(str(e))
    if getattr(e, "caused_by", None):
        mapped.caused_by = e.caused_by
    return mapped


class LocalGroupWriter:
    """Doc ops for a locally-primaried shard: through the replication
    group (seq-no fan-out, fencing) — the same engine the local service
    owns."""

    def __init__(self, group):
        self.group = group

    def index(self, doc_id, source, *, routing=None, op_type="index",
              if_seq_no=None, if_primary_term=None):
        return self.group.index(
            doc_id, source, routing=routing, op_type=op_type,
            if_seq_no=if_seq_no, if_primary_term=if_primary_term).result

    def delete(self, doc_id, *, if_seq_no=None, if_primary_term=None):
        return self.group.delete(
            doc_id, if_seq_no=if_seq_no,
            if_primary_term=if_primary_term).result

    def get(self, doc_id):
        return self.group.engine.get(doc_id)


class RemoteShardProxy:
    """Doc ops for a shard primaried on another node (the routing phase of
    ``TransportReplicationAction``): RPC to the owner, rebuild the engine
    result dataclass from the wire dict."""

    def __init__(self, node, owner: str, index: str, shard: int):
        self.node = node
        self.owner = owner
        self.index_name = index
        self.shard = shard

    def _call(self, action: str, payload: dict) -> dict:
        payload = dict(payload, index=self.index_name, shard=self.shard)
        try:
            return self.node.rpc(self.owner, action, payload,
                                 timeout=TIMEOUTS.data)
        except RemoteTransportError as e:
            raise _remote_error(e) from e

    def index(self, doc_id, source, *, routing=None, op_type="index",
              if_seq_no=None, if_primary_term=None):
        r = self._call("doc2:index", {
            "id": doc_id, "source": source, "routing": routing,
            "op_type": op_type, "if_seq_no": if_seq_no,
            "if_primary_term": if_primary_term})
        meta_seq = r.pop("_meta_seq", None)
        if meta_seq:
            # a dynamic-mapping update rode this write: the front must
            # hold the REST ack until that metadata op is locally
            # applied, so the client's next request (field_caps, GET
            # _mapping) sees the new fields — the reference acks only
            # after the master publishes the mapping change. We run
            # UNDER the front's self.lock here, so only STASH the seq;
            # _local waits after releasing the lock (waiting here would
            # stall state application against the lock).
            tls = self.node.rest._pending_ack_seq_tls
            tls.value = max(getattr(tls, "value", None) or 0,
                            int(meta_seq))
        return IndexResult(**r)

    def delete(self, doc_id, *, if_seq_no=None, if_primary_term=None):
        r = self._call("doc2:delete", {
            "id": doc_id, "if_seq_no": if_seq_no,
            "if_primary_term": if_primary_term})
        return DeleteResult(**r)

    def get(self, doc_id):
        r = self._call("doc2:get", {"id": doc_id})
        return GetResult(**r)


class ClusterHooks:
    """The seam installed on every local IndexService (see
    ``IndicesService.cluster_hooks``)."""

    def __init__(self, rest: "ClusterRestService"):
        self.rest = rest

    def writer(self, index: str, shard: int, for_read: bool = False):
        node = self.rest.node
        st = node.applied_state
        if st is None:
            return None
        table = st.data.get("routing", {}).get(index)
        if table is None or str(shard) not in table:
            return None
        if not for_read:
            # a MUTATION fetched through this front invalidates its
            # cluster request-cache entries for the index (writes
            # through OTHER fronts are invisible here — front-scoped
            # cache, see search()); doc GETs share this handle and must
            # not invalidate
            gens = self.rest._front_write_gen
            gens[index] = gens.get(index, 0) + 1
        owner = table[str(shard)]["primary"]
        if owner == node.node_id:
            group = node.primaries.get((index, shard))
            # None (group not wired yet): the caller falls back to the
            # bare local engine — safe, because the group, when wired,
            # wraps the SAME engine object (cluster_node._apply_state
            # step 3), and replica channels are wired in that same pass
            # with ops-based recovery replaying the translog, so a write
            # landing before wiring still reaches every copy. Waiting
            # here would deadlock: this runs under rest.lock, which the
            # data worker needs (apply_ops) to do the wiring.
            return LocalGroupWriter(group) if group is not None else None
        return RemoteShardProxy(node, owner, index, shard)

    def search(self, index: str, body: dict, request_cache=None):
        """None → the caller's local engines are authoritative."""
        node = self.rest.node
        st = node.applied_state
        table = (st.data.get("routing", {}) if st else {}).get(index)
        if not table:
            return None
        owners = {e["primary"] for e in table.values()}
        if owners == {node.node_id}:
            return None
        # FRONT-scoped cluster request cache: the per-shard cache the
        # reference keeps on data nodes (IndicesRequestCache) maps here
        # to the coordinating node caching the merged size==0 result,
        # keyed on (cluster-state version, this front's write
        # generation for the index, body). Writes routed through OTHER
        # coordinating nodes do not bump this front's generation — a
        # disclosed narrowing; state-version changes (mappings, routing,
        # index recreation) invalidate everything.
        cache_key = None
        svc = self.rest.indices.indices.get(index)
        if svc is not None:
            blob = svc._request_cache_blob(dict(body), request_cache)
            if blob is not None:
                cache_key = (st.version,
                             self.rest._front_write_gen.get(index, 0),
                             blob)
                hit = svc.cache_get(cache_key)
                if hit is not None:
                    return hit
        try:
            out = node.search(index, dict(body))
        except RemoteTransportError as e:
            # semantic round-trip: the remote parse/shard error must
            # render with its real ES type, not a generic exception
            raise _remote_error(e) from e
        hits = []
        for h in out["hits"]:
            hits.append(ShardHit(
                doc_id=h["id"], score=h.get("score"), seg_idx=0,
                local_doc=0, source=h.get("source"),
                sort_values=h.get("sort"), seq_no=h.get("seq_no"),
                fields=h.get("fields"), highlight=h.get("highlight"),
                ignored=h.get("ignored"),
                inner_hits=h.get("inner_hits")))
        max_score = None
        sort_spec = body.get("sort")
        if not sort_spec or sort_spec in ("_score", ["_score"]):
            scores = [h.score for h in hits if h.score is not None]
            max_score = max(scores) if scores else None
        total = out["total"]
        relation = "eq"
        tth = body.get("track_total_hits", True)
        k = int(body.get("size", 10)) + int(body.get("from", 0))
        if tth is False:
            total = len(hits)
            relation = "gte" if total >= k else "eq"
        elif isinstance(tth, int) and not isinstance(tth, bool) \
                and total > tth:
            total = tth
            relation = "gte"
        result = ShardSearchResult(
            total=total, total_relation=relation, hits=hits,
            max_score=max_score, aggregations=out.get("aggregations"),
            suggest=out.get("suggest"), profile=out.get("profile"),
            shard_failures=out.get("failures"))
        if cache_key is not None and svc is not None \
                and not out.get("failures"):
            # responses carrying shard failures never enter the cache —
            # a transient degradation must not replay until the next
            # invalidation (the reference cache has the same rule)
            svc.cache_put(cache_key, result)
        return result

    def count(self, index: str, body: dict):
        node = self.rest.node
        st = node.applied_state
        table = (st.data.get("routing", {}) if st else {}).get(index)
        if not table:
            return None
        owners = {e["primary"] for e in table.values()}
        if owners == {node.node_id}:
            return None
        q = {"size": 0}
        if body.get("query"):
            q["query"] = body["query"]
        return node.search(index, q)["total"]

    def agg_partials(self, index: str, body: dict,
                     failures_out: Optional[List[dict]] = None):
        """Aggregation partials for one cluster-routed index, collected on
        the owning nodes and shipped for ONE shared reduce (the cross-
        index agg path). None → index is locally complete, collect here.

        A dead owner no longer raises out of the whole cross-node agg
        request (the old behavior: one unreachable node → 500): its
        shards fail over to in-sync replica copies with jittered
        backoff, and only shards whose EVERY copy is down land as
        ES-shaped per-shard failures in ``failures_out`` (the caller
        renders them under ``_shards.failures``) — the same
        partial-result contract the search fan-out honors."""
        node = self.rest.node
        st = node.applied_state
        table = (st.data.get("routing", {}) if st else {}).get(index)
        if not table:
            return None
        owners = {e["primary"] for e in table.values()}
        if owners == {node.node_id}:
            return None
        from ..common.datacodec import loads_b64
        by_node, copies_of = node._group_shards_by_copy(table)
        shard_body = {"size": 0,
                      "aggs": body.get("aggs") or body.get("aggregations")}
        if body.get("query"):
            shard_body["query"] = body["query"]

        def send(owner, sids, _ctx):
            return node.rpc_or_direct(owner, "search:shards",
                                      node._h_search_shards, {
                                          "index": index,
                                          "shards": sids,
                                          "body": shard_body,
                                          "want_agg_partials": True},
                                      timeout=TIMEOUTS.search,
                                      readonly=True)

        def exhausted(sid, owner, e):
            if failures_out is not None:
                failures_out.append({
                    "shard": int(sid), "node": owner,
                    "reason": {"type": type(e).__name__,
                               "reason": str(e)},
                    "status": 503})

        partials: Dict[str, list] = {}
        for _ctx, r in node._fanout_with_failover(
                [(owner, by_node[owner], None)
                 for owner in sorted(by_node)],
                copies_of, send, exhausted):
            got = loads_b64(r.get("agg_partials", ""))
            for name_, parts in got.items():
                partials.setdefault(name_, []).extend(parts)
            if failures_out is not None:
                failures_out.extend(r.get("failures") or ())
        return partials

    def can_match(self, index: str, bounds) -> Optional[bool]:
        """Cluster-wide can_match: OR of each owner node's verdict over
        its primaried segments (reference: ``TransportSearchAction``'s
        can-match phase fans out ``ShardSearchRequest``s). None → index
        not cluster-routed, caller evaluates locally."""
        node = self.rest.node
        st = node.applied_state
        table = (st.data.get("routing", {}) if st else {}).get(index)
        if table is None:
            return None
        owners = {e["primary"] for e in table.values() if e.get("primary")}
        for owner in sorted(owners):
            try:
                r = node.rpc_or_direct(
                    owner, "search:canmatch", node._h_can_match,
                    {"index": index, "bounds": bounds},
                    timeout=TIMEOUTS.data,
                    readonly=True)
                if r.get("can_match", True):
                    return True
            except Exception:   # noqa: BLE001 — unreachable owner: the
                return True     # skip heuristic must stay conservative
        return False

    def doc_visible(self, index: str, shard: int, doc_id: str):
        """Non-realtime GET visibility against the OWNING copy's searchable
        segments (None → not cluster-routed, caller scans locally)."""
        node = self.rest.node
        st = node.applied_state
        table = (st.data.get("routing", {}) if st else {}).get(index)
        if table is None or str(shard) not in table:
            return None
        owner = table[str(shard)]["primary"]
        if owner == node.node_id:
            g = node.primaries.get((index, shard))
            if g is None:
                return None
            return any(seg.find_doc(doc_id) is not None
                       for seg in g.engine.searchable_segments())
        try:
            r = node.rpc(owner, "doc2:visible",
                         {"index": index, "shard": shard, "id": doc_id},
                         timeout=TIMEOUTS.data)
            return bool(r["visible"])
        except RemoteTransportError as e:
            raise _remote_error(e) from e

    def h_doc2_visible(self, src, payload) -> dict:
        g = self.rest.node.primaries.get(
            (payload["index"], int(payload["shard"])))
        if g is None:
            return {"visible": False}
        return {"visible": any(
            seg.find_doc(payload["id"]) is not None
            for seg in g.engine.searchable_segments())}

    def refresh(self, index: str, shard: Optional[int] = None) -> bool:
        """Cluster-wide refresh of every copy of ``index`` — or of ONE
        shard when ``shard`` is given (the scope of a doc op's
        ``?refresh=true``: other shards' pending NRT deletes must stay
        invisible). True when the index is cluster-routed (the caller's
        local loop is skipped)."""
        node = self.rest.node
        st = node.applied_state
        if st is None or index not in st.data.get("routing", {}):
            return False
        gens = self.rest._front_write_gen
        gens[index] = gens.get(index, 0) + 1
        # the local service's own engines first: group wiring is async, so
        # right after index creation a locally-primaried engine may not be
        # wrapped yet — it still holds any direct writes
        svc = self.rest.indices.indices.get(index)
        if svc is not None:
            for sid, e in enumerate(svc.shards):
                if shard is None or sid == shard:
                    e.refresh()
        for (iname, sid), g in list(node.primaries.items()):
            if iname == index and (shard is None or sid == shard):
                g.engine.refresh()
        for (iname, sid), r in list(node.replicas.items()):
            if iname == index and (shard is None or sid == shard):
                r.engine.refresh()
        for n in node.node_ids:
            if n == node.node_id:
                continue
            try:
                node.rpc(n, "shard:refresh",
                         {"index": index, "shard": shard},
                         timeout=TIMEOUTS.fast)
            except Exception:   # noqa: BLE001 — dead nodes skip
                pass
        return True


class ClusterRestService:
    """Per-node REST stack: local IndicesService + RestAPI + the cluster
    dispatch described in the module docstring."""

    def __init__(self, node, data_path: str):
        import os
        from ..rest.api import RestAPI
        self.node = node
        self.indices = IndicesService(data_path)
        self.api = RestAPI(self.indices, node_name=node.node_id)
        # the front door (handle()) authenticates; internal dispatches
        # into the local api are then trusted
        self.api.enforce_security = False
        #: per-index generation of writes/refreshes routed through THIS
        #: front — the cluster request cache's invalidation signal
        self._front_write_gen: Dict[str, int] = {}
        self.api.adaptive_selection_provider = \
            node.adaptive_selection_stats
        # the local api's fabricated node id must BE this cluster node's
        # id: /_nodes responses feed allocation filters (include._id) and
        # test-captured $node_id round-trips into routing
        self.api.node_id = node.node_id
        # relative repo locations resolve to ONE shared directory across
        # the cluster (the reference's path.repo): owners upload shard
        # blobs where the master writes metadata. data_path is
        # <cluster-root>/<node>/local — path.repo sits at <cluster-root>.
        self.api.snapshots.path_repo = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(data_path))),
            "shared_repos")
        self.lock = threading.RLock()
        self.applied_seq = 0
        #: serializes op application: the data worker (state apply), the
        #: meta pool (h_meta_op catch-up), and write-ack waiters
        #: (wait_applied_seq) may all call apply_ops concurrently — an
        #: unguarded pair could double-execute the same op
        self._apply_ops_mutex = threading.RLock()
        #: last metadata-op seq this thread published (_meta_op writes,
        #: _after_local consumes)
        self._last_meta_seq_tls = threading.local()
        #: meta seq a routed write on this thread must see applied
        #: before its REST response leaves (_local drains it OUTSIDE
        #: self.lock — waiting inside would stall state application)
        self._pending_ack_seq_tls = threading.local()
        #: op history by seq, maintained on EVERY node as ops apply (not
        #: just the executing master) so history survives master changes;
        #: nodes behind the state tail fetch missing ranges from peers.
        #: Bounded: a node further behind than HISTORY_CAP meta ops is
        #: declared divergent rather than growing memory without limit.
        self.full_log: Dict[int, dict] = {}
        #: first-seen time per missing seq — a gap is only declared
        #: unrecoverable after GAP_GRACE seconds of failed fetches, so a
        #: healing partition never causes permanent divergence
        self._gap_since: Dict[int, float] = {}
        #: serializes execute→snapshot→publish across the direct-call and
        #: RPC entry points of h_meta_op (NOT self.lock: this one is never
        #: needed by the transport loop, so holding it across the blocking
        #: publish is safe)
        self._meta_mutex = threading.Lock()
        #: serializes master-side snapshot create vs delete (a delete's
        #: blob GC must not reap an in-flight create's uploads)
        self._snapshot_mutex = threading.Lock()
        #: set when this node skipped an unrecoverable op-log gap — its
        #: metadata surface may have diverged; surfaced in _cluster_state
        self.meta_divergent = False
        #: scroll/pit id -> owning node (forwarded stateful reads)
        self._sticky: Dict[str, str] = {}
        #: per-index last-propagated mapping fingerprint
        self._propagated: Dict[str, str] = {}
        #: seqs this node executed as master before publication (replay
        #: must not re-execute them when they arrive out of order)
        self._self_executed: set = set()
        #: master-side idempotency cache: a client that timed out and
        #: retried a non-idempotent op (index create...) must get the
        #: FIRST execution's response, not a duplicate execution
        self._op_cache: Dict[str, dict] = {}
        #: the front-door request's HTTP headers for the duration of its
        #: dispatch — _local forwards them into api.handle so
        #: X-Opaque-Id / traceparent reach the task + trace layer
        self._incoming_headers_tls = threading.local()

    # ------------------------------------------------------------------
    # op-log application (every node, on the data worker)
    # ------------------------------------------------------------------

    #: in-memory op history bound per node (≈ a few MB of meta ops)
    HISTORY_CAP = 4096
    #: seconds of failed history fetches before a gap is unrecoverable
    GAP_GRACE = 20.0

    def wait_applied_seq(self, seq: int, timeout: float = 3.0) -> bool:
        """Spin until this node has applied metadata op ``seq`` (or the
        timeout passes). Used to hold write acks that carried a dynamic
        mapping update until the change is locally visible — usually
        near-instant, as the op rode the publication already in flight.
        A pure spin ON PURPOSE: application belongs to the data worker
        (whose apply path holds self.lock before taking the apply
        mutex); applying from here would invert that lock order."""
        deadline = time.monotonic() + timeout
        while self.applied_seq < seq and time.monotonic() < deadline:
            time.sleep(0.01)
        return self.applied_seq >= seq

    def apply_ops(self, state) -> None:
        log = state.data.get("meta_ops")
        if not log:
            return
        seq = log["seq"]
        tail = log["tail"]
        if self.applied_seq >= seq:     # racy fast-path; re-checked below
            return
        with self._apply_ops_mutex:
            self._apply_ops_locked(seq, tail)

    def _apply_ops_locked(self, seq: int, tail) -> None:
        if self.applied_seq >= seq:
            return
        have = {op["seq"]: op for op in tail}
        missing = [s for s in range(self.applied_seq + 1, seq + 1)
                   if s not in have]
        if missing:
            # network fetch OUTSIDE self.lock: the REST plane (_local)
            # and op application contend on it, and peers may be slow
            ops = self._fetch_history(missing[0], missing[-1])
            have.update({op["seq"]: op for op in ops})
            # seed the gap clock for EVERY still-missing seq in one pass:
            # each would otherwise start its 20s grace only after the
            # previous one expired, stalling a far-behind node for
            # GAP_GRACE x gap-width instead of one grace window total
            now0 = time.monotonic()
            for s in missing:
                if s not in have:
                    self._gap_since.setdefault(s, now0)
        with self.lock:
            for s in range(self.applied_seq + 1, seq + 1):
                op = have.get(s)
                if op is None:
                    # gap beyond the state tail that no peer served. A
                    # transient fetch failure (partition healing) must NOT
                    # advance past the op — stop and retry on the next
                    # commit; only after GAP_GRACE seconds of failures is
                    # the gap declared unrecoverable and flagged loudly.
                    now = time.monotonic()
                    first = self._gap_since.setdefault(s, now)
                    if now - first < self.GAP_GRACE:
                        return
                    self._gap_since.pop(s, None)
                    if not self.meta_divergent:
                        self.meta_divergent = True
                        import sys
                        print(f"[{self.node.node_id}] metadata op-log gap "
                              f"at seq {s} (applied {self.applied_seq}, "
                              f"target {seq}): local metadata may have "
                              f"diverged", file=sys.stderr)
                    self.applied_seq = s
                    continue
                self._gap_since.pop(s, None)
                if op["src"] != self.node.node_id and \
                        s not in self._self_executed:
                    try:
                        self.api.handle(op["m"], op["p"], op["q"],
                                        _unb64(op["b"]))
                    except Exception:   # noqa: BLE001 — replay best-effort
                        pass
                self._self_executed.discard(s)
                self._log_append(op)
                self.applied_seq = s

    def _log_append(self, op: dict) -> None:
        # self.lock serializes the two writers (apply_ops on the data
        # worker already holds it; _publish_op on a request thread does
        # not) — an unguarded min()-while-insert would race
        with self.lock:
            self.full_log[op["seq"]] = op
            while len(self.full_log) > self.HISTORY_CAP:
                self.full_log.pop(min(self.full_log))

    def _fetch_history(self, lo: int, hi: int) -> List[dict]:
        """Fetch an op range beyond the state tail: the master first,
        then other peers — every node keeps the full log as it applies,
        so any node that was up for the range can serve it. Bounded by a
        shared deadline: this runs with rest.lock held on the data
        worker, so it must not stall the node for O(cluster) × timeout."""
        st = self.node.applied_state
        master = st.master_node if st else None
        candidates = [master] if master else []
        candidates += [n for n in self.node.node_ids if n != master]
        got: Dict[int, dict] = {}
        deadline = time.monotonic() + 6.0
        for target in candidates:
            if target == self.node.node_id or target is None:
                continue
            budget = deadline - time.monotonic()
            if budget <= 0:
                break
            try:
                r = self.node.rpc(target, "meta:history",
                                  {"from": lo, "to": hi},
                                  timeout=min(TIMEOUTS.fast, budget))
                for op in r.get("ops", []):
                    got.setdefault(op["seq"], op)
            except Exception:   # noqa: BLE001 — try the next peer
                continue
            if all(s in got for s in range(lo, hi + 1)):
                break
        return list(got.values())

    # ------------------------------------------------------------------
    # request entry
    # ------------------------------------------------------------------

    def handle(self, method: str, path: str, query: str, body: bytes,
               headers: Optional[dict] = None,
               resp_headers: Optional[dict] = None) \
            -> Tuple[int, str, bytes]:
        from ..rest.api import JSON_CT, _error_payload
        self.api._trace_tls.value = None
        try:
            if self.api.security.enabled:
                # authenticate at the front door; forwarded/replicated
                # internal hops stay inside the trusted transport
                self.api._principal_tls.value = \
                    self.api.security.authenticate(headers)
            self._incoming_headers_tls.value = headers
            try:
                out = self._dispatch(method, path, query or "",
                                     body or b"")
            finally:
                self._incoming_headers_tls.value = None
            if resp_headers is not None:
                # trace/opaque echo: _local dispatches run api.handle on
                # THIS thread, which stamps the pair into _trace_tls.
                # Disclosed narrowing: requests forwarded whole to
                # another node (_exec_on) echo nothing — the remote's
                # trace id stays queryable via the shared store only
                info = getattr(self.api._trace_tls, "value", None)
                if info:
                    tid, opaque = info
                    if tid:
                        resp_headers["Trace-Id"] = tid
                    if opaque:
                        resp_headers["X-Opaque-Id"] = opaque
            return out
        except RemoteTransportError as e:
            status, payload = _error_payload(_remote_error(e))
            # error replies echo a Trace-Id too (adopted or minted) — the
            # 4xx/5xx paths flow through the same out-param as success
            self.api._stamp_trace_echo(resp_headers, headers)
            return status, JSON_CT, json.dumps(payload).encode()
        except Exception as e:   # noqa: BLE001 — ES-shaped error replies
            status, payload = _error_payload(e)
            self.api._stamp_trace_echo(resp_headers, headers)
            return status, JSON_CT, json.dumps(payload).encode()

    def _dispatch(self, method, path, query, body):
        segs = [s for s in path.split("/") if s]
        # cluster-aware admin views
        if path.startswith("/_cluster/health"):
            return self._health(method, path, query, body)
        if path == "/_cluster/state" or path.startswith("/_cluster/state"):
            return self._cluster_state(method, path, query, body)
        if path.startswith("/_cluster/allocation/explain"):
            return self._alloc_explain(query, body)
        if path.startswith("/_cluster/reroute") and method == "POST":
            return self._reroute(query, body)
        if path == "/_tasks" or path.startswith("/_tasks/") or \
                path.startswith("/_tasks?"):
            return self._tasks_route(method, path, query, body)
        if path.startswith("/_health_report"):
            return self._health_report(method, path, query, body)
        if path.startswith("/_flight_recorder"):
            return self._flight_recorder(method, path, query, body, segs)
        if path.startswith("/_profiler/timeline"):
            return self._profiler_timeline(method, path, query, body)
        if path.startswith("/_profiler/flamegraph"):
            return self._profiler_flamegraph(method, path, query, body)
        if path.startswith("/_insights/top_queries"):
            return self._insights_top_queries(method, path, query, body)
        if segs and segs[0] == "_nodes" and segs[-1] == "hot_threads":
            return self._hot_threads(method, path, query, body, segs)
        if method == "GET" and segs and (
                segs[-1] == "_stats" or
                (len(segs) >= 2 and segs[-2] == "_stats") or
                (segs[0] == "_stats")):
            return self._indices_stats(method, path, query, body)
        if method == "GET" and len(segs) >= 2 and segs[0] == "_cat" \
                and segs[1] == "segments":
            return self._cat_segments(method, path, query, body)
        if method == "GET" and len(segs) >= 2 and segs[0] == "_cat" \
                and segs[1] == "shards":
            return self._cat_shards(method, path, query, body)
        if method == "GET" and len(segs) >= 2 and segs[0] == "_cat" \
                and segs[1] == "fielddata":
            return self._cat_fielddata(method, path, query, body, segs)
        if method == "GET" and segs and segs[-1] == "_segments":
            return self._segments(method, path, query, body)
        if segs and segs[-1].split("?")[0] == "_mtermvectors":
            return self._mtermvectors(method, path, query, body)
        if segs and segs[0] == "_snapshot":
            routed = self._snapshot_route(method, path, query, segs, body)
            if routed is not None:
                return routed
        if self._is_meta_mutation(method, path, segs):
            return self._meta_op(method, path, query, body)
        if segs and segs[-1].split("?")[0] in _BROADCAST_SUFFIXES \
                and method in ("POST", "GET"):
            return self._broadcast(method, path, query, body)
        if path.startswith("/_search/scroll"):
            return self._sticky_route(method, path, query, body)
        fwd = self._forward_target(method, path, query, segs)
        if fwd is not None:
            return self._exec_on(fwd, method, path, query, body)
        self._ensure_doc_indices(method, path, segs, body, query)
        return self._local(method, path, query, body)

    def _local(self, method, path, query, body):
        self._pending_ack_seq_tls.value = None
        hdrs = getattr(self._incoming_headers_tls, "value", None)
        with self.lock:
            out = self.api.handle(method, path, query, body,
                                  headers=hdrs)
        pending = getattr(self._pending_ack_seq_tls, "value", None)
        if pending:
            self._pending_ack_seq_tls.value = None
            self.wait_applied_seq(int(pending))
        self._after_local(method, path, body)
        return out

    # ------------------------------------------------------------------
    # classification
    # ------------------------------------------------------------------

    @staticmethod
    def _is_meta_mutation(method, path, segs) -> bool:
        if method not in ("PUT", "POST", "DELETE"):
            return False
        if any(path.startswith(r) for r in _META_ROOTS):
            return True
        if len(segs) == 1 and not segs[0].startswith("_") \
                and method in ("PUT", "DELETE"):
            return True                      # index create/delete
        if len(segs) >= 2 and not segs[0].startswith("_") and \
                any(s in _META_SUFFIXES for s in segs[1:]):
            return True
        return False

    def _forward_target(self, method, path, query, segs) -> Optional[str]:
        """Single-owner whole-request forwarding for segment-bound reads."""
        if not segs or segs[0].startswith("_"):
            return None
        is_scroll_search = (len(segs) >= 2 and segs[-1] == "_search"
                            and "scroll=" in query)
        tail = next((s for s in segs[1:] if s.startswith("_")), None)
        if not is_scroll_search and tail not in _FORWARD_SUFFIXES:
            return None
        owners = self._owners_of(segs[0])
        if owners is None or owners == {self.node.node_id}:
            return None
        if len(owners) == 1:
            return next(iter(owners))
        return None                          # spread: local best-effort

    def _owners_of(self, expression: str) -> Optional[set]:
        st = self.node.applied_state
        if st is None:
            return None
        routing = st.data.get("routing", {})
        try:
            with self.lock:
                names = self.indices.resolve(expression)
        except _errors.ElasticsearchError:
            return None
        owners = set()
        for n in names:
            table = routing.get(n)
            if table is None:
                return None                  # locally-known only
            owners.update(e["primary"] for e in table.values())
        return owners or None

    # ------------------------------------------------------------------
    # metadata ops through the master
    # ------------------------------------------------------------------

    def _meta_op(self, method, path, query, body):
        import uuid
        node = self.node
        payload = {"m": method, "p": path, "q": query, "b": _b64(body),
                   "op_id": uuid.uuid4().hex}
        deadline = time.monotonic() + 10.0
        resp = None
        last: Optional[Exception] = None
        while time.monotonic() < deadline and resp is None:
            leader = node.node_loop.sync(
                lambda: node.coordinator.known_leader)
            if leader is None:
                time.sleep(0.05)
                continue
            try:
                if leader == node.node_id:
                    # direct call — an RPC loopback from the data worker
                    # would deadlock behind itself (single-threaded pool)
                    resp = self.h_meta_op(node.node_id, payload)
                else:
                    resp = node.rpc(leader, "meta:op", payload,
                                    timeout=TIMEOUTS.meta)
            except Exception as e:   # noqa: BLE001 — catching-up master /
                last = e              # leader change: retry until deadline
                time.sleep(0.05)
        if resp is None:
            raise _errors.ElasticsearchError(
                f"no master acked [{method} {path}]: {last}")
        seq = resp.get("seq")
        # expose the op seq to the caller (thread-local: _meta_op's
        # return is the REST 3-tuple) — _after_local reads it to thread
        # mapping-update visibility through write acks
        self._last_meta_seq_tls.value = seq
        on_data_worker = threading.current_thread().name.startswith(
            f"es-data-{node.node_id}")
        if seq and not on_data_worker:
            # wait until locally applied so follow-up reads observe the op
            # (skip on the data worker: application is queued behind us)
            wait_deadline = time.monotonic() + 10.0
            while self.applied_seq < seq and \
                    time.monotonic() < wait_deadline:
                time.sleep(0.01)
        segs_ = [s for s in path.split("/") if s]
        if method == "PUT" and len(segs_) == 1 and \
                not segs_[0].startswith("_") and \
                resp.get("status", 500) < 300 and seq:
            # index create: ack only once THIS node's applied routing
            # covers the new index — otherwise an immediate write races
            # the routing publication, falls back to the bare local
            # engine, and orphans the doc on a shard that routes
            # elsewhere once the table lands
            from urllib.parse import unquote as _unq
            iname = _unq(segs_[0])
            wait_deadline = time.monotonic() + 10.0
            while time.monotonic() < wait_deadline:
                st_now = self.node.applied_state
                if st_now is not None and iname in \
                        st_now.data.get("routing", {}):
                    break
                time.sleep(0.01)
        return (resp["status"], resp.get("ct", "application/json"),
                _unb64(resp["out"]))

    # master side (registered as "meta:op" on every node; only the master
    # receives it in practice)
    def h_meta_op(self, src, payload) -> dict:
        # serialize with the direct-call path (leader == self skips the
        # RPC and its single-threaded meta pool): without this, op A's
        # local-service snapshot could interleave with op B's publish and
        # resurrect a just-deleted index in cluster metadata
        with self._meta_mutex:
            return self._h_meta_op_locked(payload)

    def _h_meta_op_locked(self, payload) -> dict:
        op_id = payload.get("op_id")
        if op_id and op_id in self._op_cache:
            return self._op_cache[op_id]
        # a freshly-elected master may hold unapplied ops from the previous
        # term: catch its local service up BEFORE executing the new op, or
        # its replay would be permanently cancelled by the seq bump below
        st = self.node.applied_state
        if st is not None:
            self.apply_ops(st)
            log = st.data.get("meta_ops") or {}
            if self.applied_seq < int(log.get("seq", 0)):
                # still behind (op-log gap pending retry): executing now
                # would publish with a stale local-service snapshot and
                # _sync_index_metadata would drop every index this node
                # hasn't caught up to — refuse retryably instead
                raise _errors.ElasticsearchError(
                    f"master [{self.node.node_id}] is catching up on "
                    f"metadata ops ({self.applied_seq}/"
                    f"{log.get('seq')}); retry")
        method, path = payload["m"], payload["p"]
        query, body = payload["q"], _unb64(payload["b"])
        with self.lock:
            status, ct, out = self.api.handle(method, path, query, body)
        seq = None
        if status < 400:
            entry = {"src": self.node.node_id, "m": method, "p": path,
                     "q": query, "b": payload["b"]}
            seq = self._publish_op(entry)
            with self.lock:
                if self.applied_seq == seq - 1:
                    self.applied_seq = seq
                else:
                    # non-contiguous (ops raced in): mark this seq as
                    # already executed so replay skips it
                    self._self_executed.add(seq)
        resp = {"status": status, "ct": ct, "out": _b64(out), "seq": seq}
        if op_id:
            while len(self._op_cache) > 512:
                # evict oldest only (insertion order): a full clear would
                # drop entries an in-flight client retry still needs
                self._op_cache.pop(next(iter(self._op_cache)))
            self._op_cache[op_id] = resp
        return resp

    def h_meta_history(self, src, payload) -> dict:
        lo, hi = int(payload["from"]), int(payload["to"])
        # iterate the bounded log, never the peer-supplied range (a
        # hostile {"from": 0, "to": 2**62} must not pin the meta pool)
        with self.lock:
            return {"ops": [self.full_log[s]
                            for s in sorted(self.full_log)
                            if lo <= s <= hi]}

    def _publish_op(self, entry: dict) -> int:
        box: Dict[str, int] = {}
        # liveness AND the local-service index snapshot resolve HERE
        # (worker thread) — the update function below runs on the
        # transport loop, which must never block on its own ping
        # responses NOR contend on self.lock (held across cross-node
        # RPCs inside api.handle): either would stall RPC delivery for
        # a full timeout and can churn the leader
        live = sorted(self.node.live_nodes())
        with self.lock:
            local = {
                n: (svc.num_shards, svc.num_replicas, dict(svc.settings))
                for n, svc in self.indices.indices.items()}

        def update(st):
            new = st.updated()
            log = dict(new.data.get("meta_ops")
                       or {"seq": 0, "tail": []})
            log["seq"] = int(log["seq"]) + 1
            op = dict(entry, seq=log["seq"])
            log["tail"] = (list(log["tail"]) + [op])[-OP_TAIL:]
            new.data["meta_ops"] = log
            box["seq"] = log["seq"]
            box["op"] = op
            self._sync_index_metadata(new, live, local)
            return new

        self.node._submit_and_wait(update)
        self._log_append(box["op"])
        return box["seq"]

    def _sync_index_metadata(self, new_state, live: List[str],
                             local: Dict[str, tuple]) -> None:
        """Reconcile cluster metadata/routing with the master's local
        service after an op: allocate routing for new indices (the
        balanced allocator), drop removed ones. Generic over every
        index-creating op (create, rollover, shrink/split/clone...).
        ``local`` is a lock-free snapshot taken on the worker thread —
        this runs on the transport loop and must not touch self.lock."""
        from ..cluster.allocation import (AllocationContext,
                                          BalancedAllocator)
        meta = new_state.metadata["indices"]
        routing = new_state.data.setdefault("routing", {})
        node = self.node
        allocator = BalancedAllocator()
        for n, (shards, replicas, settings) in local.items():
            if n in meta:
                continue
            meta[n] = {"num_shards": shards, "num_replicas": replicas,
                       "mappings": {}, "primary_term": 1,
                       "settings": settings}
            ctx = AllocationContext(
                live, routing, meta, node_attrs=node.node_attrs,
                disk_used=dict(getattr(node, "_disk_used", {})),
                plane_storms=dict(getattr(node, "_plane_storms", {})))
            allocator.allocate_index(n, shards, replicas, ctx)
        for n in list(meta):
            if n not in local:
                del meta[n]
                routing.pop(n, None)
        # reconcile: fill replica copies that earlier rounds could not
        # place (e.g. a node transiently unpingable at creation) — the
        # reference reroutes on every state change (AllocationService)
        if meta:
            ctx = AllocationContext(
                live, routing, meta, node_attrs=node.node_attrs,
                disk_used=dict(getattr(node, "_disk_used", {})),
                plane_storms=dict(getattr(node, "_plane_storms", {})))
            allocator.allocate_unassigned(ctx)

    # ------------------------------------------------------------------
    # auto-create + dynamic-mapping propagation for doc writes
    # ------------------------------------------------------------------

    def _ensure_doc_indices(self, method, path, segs, body,
                            query: str = "") -> None:
        if method not in ("PUT", "POST", "DELETE"):
            return
        tail = next((s for s in segs if s.startswith("_")), None)
        if tail not in _DOC_WRITE_SUFFIXES:
            return
        if "require_alias=true" in (query or ""):
            # the write must fail on a missing alias — auto-creating the
            # target as an INDEX would both mask the error and leak the
            # index into cluster metadata
            return
        targets = set()
        if segs and not segs[0].startswith("_"):
            targets.add(segs[0])
        if tail == "_bulk":
            default = segs[0] if segs and not segs[0].startswith("_") \
                else None
            for line in (body or b"").split(b"\n"):
                line = line.strip()
                if not line:
                    continue
                try:
                    op = json.loads(line)
                except ValueError:
                    continue
                if isinstance(op, dict) and len(op) == 1 and \
                        next(iter(op)) in ("index", "create", "update",
                                           "delete"):
                    spec = next(iter(op.values()))
                    if spec.get("require_alias"):
                        continue            # must resolve as an alias
                    idx = spec.get("_index", default)
                    if idx:
                        targets.add(idx)
        st = self.node.applied_state
        known = (st.metadata["indices"] if st else {})
        with self.lock:
            aliases = self.indices.all_aliases()
        for idx in targets:
            if idx in known or idx in aliases:
                continue
            try:
                self._meta_op("PUT", f"/{idx}", "", b"{}")
            except _errors.ElasticsearchError:
                pass                          # exists / races are fine

    def _after_local(self, method, path, body):
        """Propagate dynamic-mapping growth to the cluster (the
        reference's mapping-update master round-trip inside the bulk
        path, ``TransportShardBulkAction.java:233``). Only the indices the
        request targeted are fingerprinted — re-serializing every mapping
        per doc write would scale with total cluster mapping size.
        Returns the newest metadata-op seq this call published (None if
        nothing changed) so write acks can wait for cluster visibility —
        the reference acks a write only after the mapping update is
        published."""
        if method not in ("PUT", "POST", "DELETE"):
            return None
        segs = [s for s in path.split("/") if s]
        tail = next((s for s in segs if s.startswith("_")), None)
        if tail not in _DOC_WRITE_SUFFIXES:
            return None
        targets = set()
        if segs and not segs[0].startswith("_"):
            targets.add(segs[0])
        if tail == "_bulk":
            default = segs[0] if segs and not segs[0].startswith("_") \
                else None
            for line in (body or b"").split(b"\n"):
                line = line.strip()
                if not line:
                    continue
                try:
                    op = json.loads(line)
                except ValueError:
                    continue
                if isinstance(op, dict) and len(op) == 1 and \
                        next(iter(op)) in ("index", "create", "update",
                                           "delete"):
                    spec = next(iter(op.values()))
                    if spec.get("require_alias"):
                        continue            # must resolve as an alias
                    idx = spec.get("_index", default)
                    if idx:
                        targets.add(idx)
        st = self.node.applied_state
        known = st.metadata["indices"] if st else {}
        with self.lock:
            concrete = set()
            for t in targets:
                try:
                    concrete.update(self.indices.resolve(t))
                except _errors.ElasticsearchError:
                    pass
            items = [(n, svc) for n, svc in self.indices.indices.items()
                     if n in concrete]
        newest_seq = None
        for name, svc in items:
            if name not in known:
                continue
            try:
                m = svc.mapper.mapping_dict()
            except Exception:   # noqa: BLE001
                continue
            fp = json.dumps(m, sort_keys=True, default=str)
            if self._propagated.get(name) == fp:
                continue
            if not m.get("properties") and not m.get("runtime"):
                self._propagated[name] = fp
                continue
            try:
                self._last_meta_seq_tls.value = None
                self._meta_op("PUT", f"/{name}/_mapping", "",
                              json.dumps(m, default=str).encode())
                self._propagated[name] = fp
                seq = self._last_meta_seq_tls.value
                if seq:
                    newest_seq = max(newest_seq or 0, int(seq))
            except _errors.ElasticsearchError:
                pass
        return newest_seq

    # ------------------------------------------------------------------
    # cluster-wide shard stats (owner side + front merge)
    # ------------------------------------------------------------------

    def h_stats_shards(self, src, payload) -> dict:
        """Owner side: engine-level stats of THIS node's primary copies of
        the asked shards (reference: the per-shard halves of
        ``TransportIndicesStatsAction`` / ``IndicesService.stats``)."""
        index = payload["index"]
        sections = set(payload.get("sections") or ())   # empty → all
        def want(sec):
            return not sections or sec in sections
        out = {}
        svc = self.indices.indices.get(index)
        for sid in payload.get("shards", []):
            sid = int(sid)
            g = self.node.primaries.get((index, sid))
            engine = g.engine if g is not None else (
                svc.shards[sid] if svc is not None
                and sid < len(svc.shards) else None)
            if engine is None:
                continue
            store = 0
            if want("store"):
                for root, _dirs, files in os.walk(engine.path):
                    for f in files:
                        try:
                            store += os.path.getsize(
                                os.path.join(root, f))
                        except OSError:
                            pass
            segs = engine.searchable_segments()
            est = getattr(engine, "stats", {}) or {}
            # fielddata bytes of THIS engine's segments for fields the
            # owner's query path marked loaded (global-ordinals terms,
            # field sorts — mapper.fielddata_loaded)
            fd_fields: Dict[str, int] = {}
            loaded = (getattr(svc.mapper, "fielddata_loaded", set())
                      if svc is not None and want("fielddata") else set())
            for seg in segs:
                for fname, f in seg.keyword_fields.items():
                    if fname in loaded:
                        fd_fields[fname] = fd_fields.get(fname, 0) + int(
                            f.docs_host.nbytes + f.dv_ords_host.nbytes +
                            f.dv_docs_host.nbytes)
                for fname, f in seg.numeric_fields.items():
                    if fname in loaded:
                        fd_fields[fname] = fd_fields.get(fname, 0) + int(
                            f.vals_host.nbytes + f.docs_host.nbytes)
                for fname, f in seg.text_fields.items():
                    if fname in loaded:
                        fd_fields[fname] = fd_fields.get(fname, 0) + int(
                            f.docs_host.nbytes + f.tf_host.nbytes)
            out[str(sid)] = {
                "fielddata": sum(fd_fields.values()),
                "fielddata_fields": fd_fields,
                "docs": engine.doc_count,
                "deleted": engine.deleted_count,
                "store": store,
                "tl_ops": engine.translog.total_operations(),
                "tl_size": engine.translog.size_in_bytes(),
                "get_total": int(est.get("get_total", 0)),
                "index_total": int(est.get("index_total", 0)),
                "delete_total": int(est.get("delete_total", 0)),
                "segments": [
                    {"seg_id": s.seg_id,
                     "live": int(s.live.sum()),
                     "deleted": int((~s.live).sum())}
                    for s in segs],
            }
        return out

    def _remote_shard_stats(self, names, sections=None
                            ) -> Dict[str, Dict[str, dict]]:
        """index → shard-id → owner stats for every shard primaried on
        ANOTHER node (front-local shards are already in the local stats)."""
        st = self.node.applied_state
        routing = (st.data.get("routing", {}) if st else {})
        out: Dict[str, Dict[str, dict]] = {}
        for n in names:
            table = routing.get(n)
            if not table:
                continue
            by_owner: Dict[str, list] = {}
            ops_only: set = set()
            for sid, e in table.items():
                if e["primary"] == self.node.node_id:
                    continue             # local engine already counted
                if self.node.node_id in e.get("replicas", ()):
                    # the local replica carries the DATA (docs/store —
                    # fetching again would double-count), but ACTIVITY
                    # counters (get/index/delete totals) record where
                    # the ops EXECUTED: the primary. Fetch those alone.
                    ops_only.add(str(sid))
                by_owner.setdefault(e["primary"], []).append(sid)
            got: Dict[str, dict] = {}
            for owner, sids in sorted(by_owner.items()):
                try:
                    r = self.node.rpc(owner, "stats:shards",
                                      {"index": n, "shards": sids,
                                       "sections": sorted(sections or ())},
                                      timeout=TIMEOUTS.meta)
                except Exception:   # noqa: BLE001 — a dead owner's shard
                    continue        # stats degrade to the local zeros
                for sid_s, s in (r or {}).items():
                    if sid_s in ops_only:
                        s = {k: s.get(k, 0) for k in
                             ("get_total", "index_total",
                              "delete_total")}
                        s.update(docs=0, deleted=0, store=0, tl_ops=0,
                                 tl_size=0, segments=[], fielddata=0)
                    got[sid_s] = s
            if got:
                out[n] = got
        return out

    def _indices_stats(self, method, path, query, body):
        """Serve the local stats rendering, then add the engine-resident
        sections (docs/store/translog/segments) of remote-owned primary
        shards — the front's local engines for those shards are empty."""
        status, ct, out = self._local(method, path, query, body)
        if status != 200:
            return status, ct, out
        try:
            doc = json.loads(out)
        except ValueError:
            return status, ct, out
        indices = doc.get("indices")
        if not isinstance(indices, dict):
            return status, ct, out
        remote = self._remote_shard_stats(list(indices))
        if not remote:
            return status, ct, out

        def bump(section: dict, key: str, delta: int) -> None:
            if isinstance(section, dict) and key in section:
                section[key] = section[key] + delta

        params = _parse_query(query)
        include_unloaded = params.get("include_unloaded_segments") \
            in ("true", "")
        for n, shards in remote.items():
            entry = indices.get(n, {})
            svc = self.indices.indices.get(n)
            closed = svc is not None and svc.closed
            if closed and not include_unloaded:
                continue             # closed: local zeros are correct
            adds = {"docs": 0, "deleted": 0, "store": 0, "tl_ops": 0,
                    "tl_size": 0, "seg_count": 0, "get_total": 0,
                    "index_total": 0, "delete_total": 0, "fielddata": 0}
            for _sid, s in shards.items():
                adds["docs"] += s["docs"]
                adds["deleted"] += s["deleted"]
                adds["store"] += s["store"]
                adds["tl_ops"] += s["tl_ops"]
                adds["tl_size"] += s["tl_size"]
                adds["seg_count"] += len(s["segments"])
                adds["get_total"] += s.get("get_total", 0)
                adds["index_total"] += s.get("index_total", 0)
                adds["delete_total"] += s.get("delete_total", 0)
                adds["fielddata"] += s.get("fielddata", 0)
            targets = [entry.get("primaries"), entry.get("total"),
                       (doc.get("_all") or {}).get("primaries"),
                       (doc.get("_all") or {}).get("total")]
            for t in targets:
                if not isinstance(t, dict):
                    continue
                # a closed index reports only unloaded segments (the local
                # decorate zeroed translog and the engines are closed)
                bump(t.get("segments", {}), "count", adds["seg_count"])
                if closed:
                    continue
                bump(t.get("docs", {}), "count", adds["docs"])
                bump(t.get("docs", {}), "deleted", adds["deleted"])
                bump(t.get("store", {}), "size_in_bytes", adds["store"])
                bump(t.get("store", {}), "total_data_set_size_in_bytes",
                     adds["store"])
                tl = t.get("translog", {})
                bump(tl, "operations", adds["tl_ops"])
                bump(tl, "size_in_bytes", adds["tl_size"])
                bump(tl, "uncommitted_operations", adds["tl_ops"])
                bump(tl, "uncommitted_size_in_bytes", adds["tl_size"])
                bump(t.get("get", {}), "total", adds["get_total"])
                bump(t.get("fielddata", {}), "memory_size_in_bytes",
                     adds["fielddata"])
                ix = t.get("indexing", {})
                bump(ix, "index_total", adds["index_total"])
                bump(ix, "delete_total", adds["delete_total"])
        from ..rest.api import JSON_CT
        return 200, JSON_CT, json.dumps(doc).encode()

    def _segments(self, method, path, query, body):
        """GET /_segments on the cluster: remote-owned shards' segment
        lists come over ``stats:shards`` and patch into the local
        rendering (which covers front-held copies)."""
        status, ct, out = self._local(method, path, query, body)
        if status != 200:
            return status, ct, out
        try:
            doc = json.loads(out)
        except ValueError:
            return status, ct, out
        indices = doc.get("indices")
        st = self.node.applied_state
        routing = (st.data.get("routing", {}) if st else {})
        if not isinstance(indices, dict) or not routing:
            return status, ct, out
        remote = self._remote_shard_stats(
            [n for n in indices if n in routing], sections={"segments"})
        for n, shards in remote.items():
            shards_out = (indices.get(n) or {}).get("shards")
            if not isinstance(shards_out, dict):
                continue
            for sid, s in shards.items():
                seg_map = {
                    seg["seg_id"]: {
                        "generation": gi, "num_docs": seg["live"],
                        "deleted_docs": seg["deleted"],
                        "size_in_bytes": 0, "memory_in_bytes": 0,
                        "committed": True, "search": True,
                        "version": "9.0.0", "compound": False}
                    for gi, seg in enumerate(s.get("segments", []))}
                copies = shards_out.get(sid)
                if copies:
                    copies[0]["segments"] = seg_map
                    copies[0]["num_committed_segments"] = len(seg_map)
                    copies[0]["num_search_segments"] = len(seg_map)
        from ..rest.api import JSON_CT
        return 200, JSON_CT, json.dumps(doc).encode()

    def _cat_fielddata(self, method, path, query, body, segs):
        """Cluster cat fielddata: the owners hold the loaded columns —
        merge their per-field byte maps with the local rendering."""
        from urllib.parse import unquote
        from ..rest.api import _flag, _human_bytes
        want = None
        if len(segs) >= 3:
            want = set(unquote(segs[2]).split(","))
        with self.lock:
            names = sorted(self.api.indices.indices)
        fields: Dict[str, int] = {}
        with self.lock:
            for n in names:
                svc = self.indices.indices[n]
                loaded = sorted(getattr(svc.mapper, "fielddata_loaded",
                                        ()))
                if loaded:
                    fd, _comp = svc.field_bytes()
                    for f in loaded:
                        fields[f] = fields.get(f, 0) + int(fd.get(f, 0))
        # fielddata is NODE-LOCAL state: the loaded columns live on
        # whichever copy executed the sort/global-ordinals (primary OR
        # replica under adaptive replica selection), so ask every peer
        # for the shards IT holds — not just primary owners
        st = self.node.applied_state
        routing = (st.data.get("routing", {}) if st else {})
        live = self.node.live_nodes()
        by_node: Dict[str, Dict[str, list]] = {}
        for n in names:
            for sid, e in (routing.get(n) or {}).items():
                holders = [e["primary"]] + list(e.get("replicas", ()))
                for h in holders:
                    if h != self.node.node_id and h in live:
                        by_node.setdefault(h, {}).setdefault(
                            n, []).append(sid)
        for peer, per_index in sorted(by_node.items()):
            for n, sids in per_index.items():
                try:
                    r = self.node.rpc(peer, "stats:shards",
                                      {"index": n, "shards": sids,
                                       "sections": ["fielddata"]},
                                      timeout=TIMEOUTS.meta)
                except Exception:   # noqa: BLE001 — dead peer: skip
                    continue
                for _sid, s in (r or {}).items():
                    for f, b in (s.get("fielddata_fields")
                                 or {}).items():
                        fields[f] = fields.get(f, 0) + int(b)
        params = _parse_query(query)
        rows = [[self.node.node_id[:4], "127.0.0.1", "127.0.0.1",
                 self.node.node_id, f, _human_bytes(b)]
                for f, b in sorted(fields.items())
                if want is None or f in want]
        with self.lock:
            text = self.api._cat_table(
                rows, ["id", "host", "ip", "node", "field", "size"],
                _flag(params, "v"), params,
                aliases={"f": "field", "s": "size"})
        if isinstance(text, (dict, list)):
            from ..rest.api import JSON_CT
            return 200, JSON_CT, json.dumps(text).encode()
        return 200, "text/plain; charset=UTF-8", str(text).encode()

    def _cat_shards(self, method, path, query, body):
        """Cluster cat shards: per-shard docs/owner from the routing
        table + owner engine stats (``stats:shards``); falls back to the
        local rendering for unrouted indices."""
        from urllib.parse import unquote
        from ..rest.api import _flag
        segs = [s for s in path.split("/") if s]
        index_expr = unquote(segs[2]) if len(segs) >= 3 else None
        st = self.node.applied_state
        routing = (st.data.get("routing", {}) if st else {})
        with self.lock:
            try:
                names = sorted(self.api.indices.resolve(index_expr)) \
                    if index_expr else sorted(self.api.indices.indices)
            except _errors.ElasticsearchError:
                names = None
        if names is None or not any(n in routing for n in names):
            # local fallback OUTSIDE self.lock (ESTP-L01): _local runs
            # the full dispatcher (api.handle + _after_local, whose
            # write path takes _meta_mutex/_apply_ops_mutex) — calling
            # it under self.lock opposes the apply_ops/h_meta_op order
            # (mutex first, then self.lock) and closes a deadlock cycle
            return self._local(method, path, query, body)
        params = _parse_query(query)
        remote = self._remote_shard_stats(names, sections={"docs"})
        extra = ["" for _ in self.api._CAT_SHARDS_EXTRA]
        rows = []
        for n in names:
            svc = self.indices.indices.get(n)
            if svc is None:
                continue
            table = routing.get(n) or {}
            for sid in range(svc.num_shards):
                entry = table.get(str(sid)) or {}
                owner = entry.get("primary", self.node.node_id)
                if owner == self.node.node_id or \
                        self.node.node_id in entry.get("replicas", ()):
                    docs = svc.shards[sid].doc_count
                else:
                    docs = (remote.get(n, {}).get(str(sid), {})
                            .get("docs", 0))
                rows.append([n, sid, "p", "STARTED", docs, "0b",
                             "127.0.0.1", owner, owner] + list(extra))
                for rnode in entry.get("replicas", ()):
                    rows.append([n, sid, "r", "STARTED", docs, "0b",
                                 "127.0.0.1", rnode, rnode] + list(extra))
        with self.lock:
            text = self.api._cat_table(
                rows,
                ["index", "shard", "prirep", "state", "docs", "store",
                 "ip", "id", "node"] + self.api._CAT_SHARDS_EXTRA,
                _flag(params, "v"), params,
                default_columns=["index", "shard", "prirep", "state",
                                 "docs", "store", "ip", "id", "node"],
                aliases={"i": "index", "s": "shard", "p": "prirep",
                         "st": "state", "d": "docs", "sto": "store",
                         "n": "node"})
        if isinstance(text, (dict, list)):
            from ..rest.api import JSON_CT
            return 200, JSON_CT, json.dumps(text).encode()
        return 200, "text/plain; charset=UTF-8", str(text).encode()

    def _cat_segments(self, method, path, query, body):
        """Cluster cat segments: the local rows cover front-primaried
        shards; remote-owned shards' segment lists come over
        ``stats:shards`` and render in the same table."""
        from urllib.parse import unquote
        segs = [s for s in path.split("/") if s]
        index_expr = unquote(segs[2]) if len(segs) >= 3 else None
        st = self.node.applied_state
        routing = (st.data.get("routing", {}) if st else {})
        with self.lock:
            try:
                names = sorted(self.api.indices.resolve(index_expr)) \
                    if index_expr else sorted(self.api.indices.indices)
            except _errors.ElasticsearchError:
                names = None
        if names is None or not any(n in routing for n in names):
            # OUTSIDE self.lock — same lock-order reasoning as
            # _cat_shards (ESTP-L01)
            return self._local(method, path, query, body)
        params = _parse_query(query)
        rows = []
        remote = self._remote_shard_stats(names, sections={"segments"})
        for n in names:
            svc = self.indices.indices.get(n)
            if svc is None:
                continue
            if svc.closed:
                raise _errors.IndexClosedError(f"closed index [{n}]")
            table = routing.get(n) or {}
            for sid in range(svc.num_shards):
                owner = (table.get(str(sid)) or {}).get(
                    "primary", self.node.node_id)
                if owner == self.node.node_id:
                    engine = svc.shards[sid]
                    seg_list = [
                        {"seg_id": s.seg_id, "live": int(s.live.sum()),
                         "deleted": int((~s.live).sum())}
                        for s in engine.searchable_segments()]
                else:
                    seg_list = (remote.get(n, {}).get(str(sid), {})
                                .get("segments", []))
                for gi, s in enumerate(seg_list):
                    rows.append(self.api.cat_segment_row(
                        n, sid, owner[:4], s["seg_id"], gi, s["live"],
                        s["deleted"]))
        with self.lock:
            text = self.api.cat_segments_table(rows, params)
        # mirror RestAPI.handle's payload rendering (str → text/plain,
        # list → JSON for format=json)
        if isinstance(text, (dict, list)):
            from ..rest.api import JSON_CT
            return 200, JSON_CT, json.dumps(text).encode()
        return 200, "text/plain; charset=UTF-8", str(text).encode()

    # ------------------------------------------------------------------
    # forwarding / broadcast
    # ------------------------------------------------------------------

    def _exec_on(self, target: str, method, path, query, body):
        if target == self.node.node_id:
            return self._local(method, path, query, body)
        try:
            r = self.node.rpc(target, "rest:exec", {
                "m": method, "p": path, "q": query, "b": _b64(body)},
                timeout=30.0)
        except RemoteTransportError as e:
            raise _remote_error(e) from e
        out = _unb64(r["out"])
        self._remember_sticky(out, target)
        return r["status"], r.get("ct", "application/json"), out

    def h_rest_exec(self, src, payload) -> dict:
        status, ct, out = self._local(
            payload["m"], payload["p"], payload["q"],
            _unb64(payload["b"]))
        return {"status": status, "ct": ct, "out": _b64(out)}

    def _remember_sticky(self, out: bytes, target: str) -> None:
        try:
            doc = json.loads(out)
        except ValueError:
            return
        if isinstance(doc, dict):
            for k in ("_scroll_id", "id", "pit_id"):
                v = doc.get(k)
                if isinstance(v, str) and len(v) > 16:
                    self._sticky[v] = target

    def _sticky_route(self, method, path, query, body):
        sid = None
        try:
            doc = json.loads(body or b"{}")
            sid = doc.get("scroll_id") or doc.get("id")
            if isinstance(sid, list):
                sid = sid[0] if sid else None
        except ValueError:
            pass
        if sid is None and path.count("/") >= 3:
            sid = path.rsplit("/", 1)[-1]
        target = self._sticky.get(sid or "")
        if target and target != self.node.node_id:
            return self._exec_on(target, method, path, query, body)
        return self._local(method, path, query, body)

    def _snapshot_route(self, method, path, query, segs, body):
        """Master-coordinated snapshots (reference:
        ``snapshots/SnapshotsService.java:126``): repository CRUD
        replicates via the op log (every node can then read the SHARED
        fs repo); snapshot CREATE runs on the master, which asks each
        shard's owning node to upload that shard's files
        (``snap:shard`` — the reference's ``SnapshotShardsService``)
        and writes the snapshot metadata once; snapshot DELETE runs on
        the master (single metadata writer). Reads and restore stay
        local. Returns None for routes the normal dispatch should keep
        handling."""
        if len(segs) == 2 and method in ("PUT", "POST", "DELETE"):
            return self._meta_op(method, path, query, body)   # repo CRUD
        if len(segs) == 4 and segs[3] == "_restore" and \
                method in ("POST", "PUT"):
            # restore replicates like any metadata op: every node replays
            # it from the SHARED repo into its local service, so the
            # restored index exists cluster-wide (deterministic replay —
            # same blobs everywhere)
            return self._meta_op(method, path, query, body)
        is_data_op = len(segs) == 3 and not segs[2].startswith("_")
        if not is_data_op:
            return None
        node = self.node
        leader = node.node_loop.sync(lambda: node.coordinator.known_leader)
        if method == "DELETE":
            if leader == node.node_id:
                with self._snapshot_mutex:     # vs in-flight create's gc
                    return self._local(method, path, query, body)
            if leader is None:
                raise _errors.ElasticsearchError("no known master")
            return self._exec_on(leader, method, path, query, body)
        if method not in ("PUT", "POST"):
            return None                           # GET snapshot: local
        if leader != node.node_id:
            if leader is None:
                raise _errors.ElasticsearchError("no known master")
            return self._exec_on(leader, method, path, query, body)
        with self._snapshot_mutex:
            return self._snapshot_create_master(segs[1], segs[2], query,
                                                body)

    def _snapshot_create_master(self, repo, snap, query, body):
        from urllib.parse import unquote
        repo, snap = unquote(repo), unquote(snap)
        spec = {}
        try:
            spec = json.loads(body or b"{}") or {}
        except ValueError:
            pass
        node = self.node
        st = node.applied_state
        routing = st.data.get("routing", {}) if st else {}
        with self.lock:
            snaps = self.api.snapshots
            expr = spec.get("indices")
            if isinstance(expr, list):
                expr = ",".join(expr)
            try:
                names = self.indices.resolve(expr)
            except _errors.ElasticsearchError:
                if not spec.get("ignore_unavailable"):
                    raise
                names = []
            # fail fast on duplicates BEFORE any shard uploads
            ridx = snaps.get_repository(repo).read_index()
            if any(s["snapshot"] == snap for s in ridx["snapshots"]):
                raise _errors.ResourceAlreadyExistsError(
                    f"[{repo}:{snap}] snapshot with the same name "
                    f"already exists")
        import time as _time
        start = _time.time()
        indices_meta = {}
        total_files = total_bytes = 0
        for name in sorted(names):
            table = routing.get(name, {})
            with self.lock:
                svc = self.indices.indices[name]
                base = snaps.index_snapshot_meta(name)
            shards = {}
            for sid in range(svc.num_shards):
                entry = table.get(str(sid))
                if entry is None and table:
                    # an unassigned shard must FAIL the snapshot, not
                    # silently upload the master's empty local copy
                    raise _errors.SnapshotError(
                        f"shard [{name}][{sid}] has no assigned "
                        f"primary; cannot snapshot")
                owner = entry["primary"] if entry else node.node_id
                if owner == node.node_id:
                    holder = node.primaries.get((name, sid))
                    engine = holder.engine if holder is not None \
                        else svc.shards[sid]
                    with self.lock:
                        manifest, nf, nb = snaps.upload_shard(
                            repo, name, sid, engine)
                else:
                    r = node.rpc(owner, "snap:shard", {
                        "repo": repo, "index": name, "shard": sid},
                        timeout=30.0)
                    manifest, nf, nb = r["manifest"], r["files"], r["bytes"]
                shards[str(sid)] = manifest
                total_files += nf
                total_bytes += nb
            indices_meta[name] = dict(base, shards=shards)
        with self.lock:
            meta = snaps.create_from_manifests(
                repo, snap, indices_meta, total_files, total_bytes,
                include_global_state=spec.get("include_global_state",
                                              True),
                metadata=spec.get("metadata"), start=start)
            if "wait_for_completion=true" in (query or ""):
                doc = {"snapshot": self.api._snapshot_info(
                    meta, repository=repo)}
            else:
                doc = {"accepted": True}
        return 200, "application/json", json.dumps(doc).encode()

    def _mtermvectors(self, method, path, query, body):
        """Per-doc routing: each item's term vectors come from the node
        primarying its shard (the reference's per-item single-shard
        dispatch in ``TransportMultiTermVectorsAction``)."""
        segs = [s for s in path.split("/") if s]
        default_index = segs[0] if segs and not segs[0].startswith("_") \
            else None
        try:
            spec = json.loads(body or b"{}") or {}
        except ValueError:
            spec = {}
        _DOC_KEYS = {"_index", "_id", "_routing", "routing", "fields",
                     "field_statistics", "term_statistics", "offsets",
                     "payloads", "positions", "filter", "doc", "version",
                     "version_type"}
        docs = spec.get("docs")
        if isinstance(docs, list) and any(
                isinstance(d, dict) and any(k not in _DOC_KEYS
                                            for k in d)
                for d in docs):
            # unknown/deprecated doc keys (camelCase, _-prefixed): the
            # local api owns that validation and renders the 400
            return self._local(method, path, query, body)
        if not isinstance(docs, list):
            # the ids short form: ?ids=a,b (or body {"ids": [...]}) with
            # the index from the path
            ids = spec.get("ids")
            if ids is None:
                qp = _parse_query(query)
                from urllib.parse import unquote
                raw_ids = qp.get("ids")
                ids = [unquote(x) for x in raw_ids.split(",")] \
                    if raw_ids else None
            if ids and default_index:
                docs = [{"_id": i} for i in ids]
            else:
                return self._local(method, path, query, body)
        st = self.node.applied_state
        routing = st.data.get("routing", {}) if st else {}
        out_docs = []
        for d in docs:
            idx = (d or {}).get("_index", default_index)
            did = (d or {}).get("_id")
            one_path = f"/{idx}/_termvectors/{did}"
            one_body = json.dumps(
                {k: v for k, v in (d or {}).items()
                 if k not in ("_index", "_id")}).encode()
            target = self.node.node_id
            table = routing.get(idx)
            if table is not None and did is not None:
                meta = st.metadata["indices"].get(idx, {})
                from .cluster_node import shard_for
                droute = (d or {}).get("routing", (d or {}).get("_routing"))
                sid = shard_for(str(did), droute,
                                int(meta.get("num_shards", 1)))
                entry = table.get(str(sid))
                if entry is not None:
                    target = entry["primary"]
            status, _ct, raw = self._exec_on(target, "POST", one_path,
                                             query, one_body)
            try:
                doc_out = json.loads(raw)
            except ValueError:
                doc_out = {"_index": idx, "_id": did}
            if status >= 400:
                err = doc_out.get("error", doc_out)
                doc_out = {"_index": idx, "_id": did, "error":
                           err if isinstance(err, dict) else
                           {"type": "exception", "reason": str(err)}}
            out_docs.append(doc_out)
        return 200, "application/json", json.dumps(
            {"docs": out_docs}).encode()

    def _tasks_route(self, method, path, query, body):
        """Cluster task APIs: every node owns a task registry; list/cancel
        fan out and merge (the reference's ``TransportListTasksAction``
        nodes-operation), get/cancel-by-id find the owning node (the
        cancel broadcast IS the ban propagation — every node's manager
        cancels its local members of the task tree)."""
        local_status, ct, local_out = self._local(method, path, query, body)
        is_by_id = path != "/_tasks" and "_cancel" not in path
        merged = None
        try:
            merged = json.loads(local_out)
        except ValueError:
            return local_status, ct, local_out
        best = (local_status, merged)
        for n in self.node.node_ids:
            if n == self.node.node_id:
                continue
            try:
                # by-id gets may block remotely on wait_for_completion
                # (default 30s) — the RPC must outlive that wait
                r = self.node.rpc(n, "rest:exec", {
                    "m": method, "p": path, "q": query, "b": _b64(body)},
                    timeout=40.0 if is_by_id else TIMEOUTS.meta)
            except Exception:   # noqa: BLE001 — dead nodes skip
                continue
            try:
                doc = json.loads(_unb64(r["out"]))
            except ValueError:
                continue
            if is_by_id:
                # by-id get: the first node that knows the task wins
                if r["status"] < 400 and best[0] >= 400:
                    best = (r["status"], doc)
                continue
            if r["status"] >= 400 or not isinstance(doc, dict):
                continue
            if best[0] >= 400:
                best = (200, doc)
                continue
            tgt = best[1]
            if isinstance(doc.get("nodes"), dict):
                tgt.setdefault("nodes", {}).update(doc["nodes"])
            if isinstance(doc.get("tasks"), dict):
                tgt.setdefault("tasks", {}).update(doc["tasks"])
            elif isinstance(doc.get("tasks"), list):
                tgt.setdefault("tasks", []).extend(doc["tasks"])
        status, doc = best
        return status, "application/json", json.dumps(doc).encode()

    def _broadcast(self, method, path, query, body):
        for n in self.node.node_ids:
            if n == self.node.node_id:
                continue
            try:
                self.node.rpc(n, "rest:exec", {
                    "m": method, "p": path, "q": query, "b": _b64(body)},
                    timeout=TIMEOUTS.meta)
            except Exception:   # noqa: BLE001 — dead nodes skip
                pass
        return self._local(method, path, query, body)

    # ------------------------------------------------------------------
    # cluster-aware admin views
    # ------------------------------------------------------------------

    #: waits the cluster front resolves itself (against the CLUSTER node
    #: set and routing) instead of the local single-node view
    _WAIT_PARAMS = ("wait_for_status", "wait_for_nodes",
                    "wait_for_active_shards", "timeout")

    def _health(self, method, path, query, body):
        """Cluster health: the local api renders the full response shape
        (levels, per-index sections, closed-index semantics); the
        cluster-wide numbers and the wait_* semantics resolve here."""
        from ..common.settings import parse_time_millis
        params = _parse_query(query)
        want_status = params.get("wait_for_status")
        want_nodes = params.get("wait_for_nodes")
        want_active = params.get("wait_for_active_shards")
        try:
            timeout_s = parse_time_millis(
                params.get("timeout", "30s")) / 1e3
        except Exception:   # noqa: BLE001
            timeout_s = 30.0
        timeout_s = min(timeout_s, 30.0)
        base_q = "&".join(f"{k}={v}" for k, v in params.items()
                          if k not in self._WAIT_PARAMS)
        order = {"red": 0, "yellow": 1, "green": 2}
        deadline = time.monotonic() + timeout_s
        while True:
            status_code, ct, out = self._local(method, path, base_q, body)
            try:
                doc = json.loads(out)
            except ValueError:
                return status_code, ct, out
            if status_code != 200 or not isinstance(doc, dict):
                return status_code, ct, out
            st = self.node.applied_state
            nodes = sorted(st.nodes) if st else []
            doc["number_of_nodes"] = len(nodes)
            doc["number_of_data_nodes"] = len(nodes)
            # scope shard counting to the indices the request selected
            # (level/index-pattern health) — the local doc's indices
            # section names them; absent section = whole cluster
            from urllib.parse import unquote
            segs = [unquote(s) for s in path.split("/") if s]
            selected = None
            if len(segs) >= 3:                    # /_cluster/health/{idx}
                try:
                    with self.lock:
                        selected = set(self.indices.resolve(segs[2]))
                    # health defaults to lenient open+closed expansion
                    # (RestClusterHealthAction: lenientExpandHidden) —
                    # 7.2+ closed indices are replicated and count
                    ew = params.get("expand_wildcards", "open,closed")
                    with self.lock:
                        closed = {n for n in selected
                                  if self.indices.indices[n].closed}
                    # expand_wildcards filters WILDCARD expansions only;
                    # a concrete closed index name is always selected
                    # (the reference's IndicesOptions semantics)
                    is_pattern = any(c in segs[2] for c in "*?") or \
                        segs[2] in ("_all", "")
                    if is_pattern and "all" not in ew:
                        if "closed" not in ew:
                            selected -= closed
                        if "open" not in ew and ew:
                            selected &= closed
                except _errors.ElasticsearchError:
                    selected = set()
            cstatus, active, unassigned = self._cluster_shards_view(
                nodes, selected)
            if cstatus is not None:
                doc["status"] = cstatus
                doc["unassigned_shards"] = unassigned
                doc["active_shards"] = active
            ok = True
            if want_status is not None and order.get(
                    doc.get("status"), 0) < order.get(want_status, 0):
                ok = False
            if want_nodes is not None and \
                    not _nodes_predicate(want_nodes, len(nodes)):
                ok = False
            if want_active not in (None, "", "all"):
                try:
                    if int(want_active) > doc.get("active_shards", 0):
                        ok = False
                except ValueError:
                    pass
            if ok:
                return 200, "application/json", json.dumps(doc).encode()
            if time.monotonic() > deadline:
                doc["timed_out"] = True
                return 408, "application/json", json.dumps(doc).encode()
            time.sleep(0.05)

    def _health_report(self, method, path, query, body):
        """Cluster ``GET /_health_report``: every node evaluates its own
        registry-local indicators (rest:exec runs the LOCAL handler — no
        re-fan-out), the front folds them to the worst status per
        indicator (per-node status map in details) and replaces
        ``shards_availability`` with the authoritative routing-table
        view, where red is reachable."""
        status, ct, out = self._local(method, path, query, body)
        st = self.node.applied_state
        if status != 200 or st is None:
            return status, ct, out
        try:
            local_doc = json.loads(out)
        except ValueError:
            return status, ct, out
        if not isinstance(local_doc, dict) or \
                "indicators" not in local_doc:
            return status, ct, out
        docs = {self.node.node_id: local_doc}
        # concurrent fan-out (shared rest:exec helper): the "is this
        # node healthy" endpoint must not serialize per-node timeouts —
        # one dead peer costs one timeout window total, not one per peer
        peers = [n for n in self.node.node_ids if n != self.node.node_id]
        for n, (st_n, payload) in self._fanout_rest_exec(
                method, path, query, body, peers).items():
            if st_n != 200:
                continue            # a dead/degraded node reports
            try:                    # nothing; availability covers it
                docs[n] = json.loads(payload)
            except ValueError:
                continue
        from ..common.health import GREEN, merge_reports, worst_status
        merged = merge_reports(local_doc, docs)
        nodes = sorted(st.nodes)
        cstatus, active, unassigned = self._cluster_shards_view(nodes)
        ind = merged["indicators"].get("shards_availability")
        if cstatus is not None and ind is not None:
            ind["status"] = cstatus
            ind.setdefault("details", {}).update(
                active_shards=active, unassigned_shards=unassigned,
                number_of_nodes=len(nodes))
            if cstatus == GREEN:
                ind["symptom"] = "This cluster has all shards available."
                ind.pop("impacts", None)
                ind.pop("diagnosis", None)
            else:
                ind["symptom"] = (
                    f"This cluster has {unassigned} unassigned shard"
                    f"{'s' if unassigned != 1 else ''}.")
            merged["status"] = worst_status(
                d["status"] for d in merged["indicators"].values())
        return 200, "application/json", json.dumps(merged).encode()

    def _fanout_rest_exec(self, method, path, query, body, targets,
                          timeout=None):
        """The ONE concurrent rest:exec fan-out every cluster-merge view
        shares (health report, hot threads, flight recorder): fetch
        ``(status, bytes)`` from every target at once, so dead peers
        cost one timeout window TOTAL, not one per peer. Peers that
        error are absent from the result."""
        out: Dict[str, tuple] = {}
        if not targets:
            return out

        def fetch_one(n):
            r = self.node.rpc(n, "rest:exec", {
                "m": method, "p": path, "q": query, "b": _b64(body)},
                timeout=timeout if timeout is not None else TIMEOUTS.data)
            return n, r["status"], _unb64(r["out"])

        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=len(targets),
                                thread_name_prefix="es-rest-fanout"
                                ) as pool:
            for fut in [pool.submit(fetch_one, n) for n in targets]:
                try:
                    n, st, payload = fut.result()
                except Exception:   # noqa: BLE001 — a dead node
                    continue        # contributes nothing
                out[n] = (st, payload)
        return out

    def _flight_recorder(self, method, path, query, body, segs):
        """Cluster ``GET /_flight_recorder[...]``: every node answers
        from its local ring/capture store over ``rest:exec`` (the
        health-report fan-in pattern — concurrent, one timeout window
        total for dead peers) and the front merges. Events dedupe by
        their process-unique ``seq`` (in-process test clusters share one
        ring; production processes contribute disjoint events), sort by
        wall time, and re-apply the request's ``limit`` after the merge;
        captures dedupe by id. A capture fetched by id returns from
        whichever node holds it."""
        status, ct, out = self._local(method, path, query, body)
        peers = [n for n in self.node.node_ids if n != self.node.node_id]
        if not peers or method != "GET":
            return status, ct, out

        # capture-by-id: serve the first hit (local already checked;
        # peers probed concurrently — this endpoint matters most when
        # nodes are dead, so serial per-peer timeouts are unacceptable)
        if len(segs) == 3 and segs[1] == "captures":
            if status == 200:
                return status, ct, out
            for st_n, payload in self._fanout_rest_exec(
                    method, path, query, body, peers).values():
                if st_n == 200:
                    return 200, "application/json", payload
            return status, ct, out
        if status != 200:
            return status, ct, out
        try:
            local_doc = json.loads(out)
        except ValueError:
            return status, ct, out
        docs = [local_doc]
        for st_n, payload in self._fanout_rest_exec(
                method, path, query, body, peers).values():
            if st_n != 200:
                continue
            try:
                doc_n = json.loads(payload)
            except ValueError:
                continue
            if isinstance(doc_n, dict):
                docs.append(doc_n)
        if len(segs) == 2 and segs[1] == "captures":
            seen_caps = set()
            caps = []
            for d in docs:
                for c in d.get("captures", ()):
                    if c.get("id") in seen_caps:
                        continue
                    seen_caps.add(c.get("id"))
                    caps.append(c)
            caps.sort(key=lambda c: c.get("ts_ms", 0))
            merged = dict(local_doc, captures=caps)
            return 200, "application/json", json.dumps(merged).encode()
        seen_ev = set()
        events = []
        for d in docs:
            for ev in d.get("events", ()):
                # node joins the key: separate production processes
                # restart their seq counters, and two nodes' seq-N
                # events in the same millisecond must not conflate —
                # in-process clusters (shared ring, same node stamp
                # per event) still dedupe exactly
                key = (ev.get("seq"), ev.get("ts_ms"), ev.get("type"),
                       ev.get("node"))
                if key in seen_ev:
                    continue
                seen_ev.add(key)
                events.append(ev)
        events.sort(key=lambda ev: (ev.get("ts_ms", 0),
                                    ev.get("seq", 0)))
        # re-apply the request's limit AFTER the merge (each node
        # already truncated to its newest `limit`; without this the
        # client would receive up to n_nodes x limit events) — keep the
        # cluster-wide NEWEST slice
        from urllib.parse import parse_qs
        try:
            limit = int((parse_qs(query).get("limit") or [256])[-1])
        except ValueError:
            limit = 256
        if limit > 0:
            events = events[-limit:]
        merged = dict(local_doc, events=events,
                      nodes_reporting=len(docs))
        return 200, "application/json", json.dumps(merged).encode()

    def _profiler_timeline(self, method, path, query, body):
        """Cluster ``GET /_profiler/timeline``: every node renders its
        local dispatch-profile ring (the flight-recorder fan-in
        pattern — one concurrent ``rest:exec`` window, dead peers cost
        one timeout total) and the front merges the Chrome trace-event
        streams. Per-node dedup is by full event identity: in-process
        test clusters share one ring, so two nodes report byte-identical
        events (same deterministic pid from the (node, batcher) track
        key) which must appear exactly once; production processes
        contribute disjoint tracks."""
        status, ct, out = self._local(method, path, query, body)
        peers = [n for n in self.node.node_ids if n != self.node.node_id]
        if not peers or method != "GET" or status != 200:
            return status, ct, out
        try:
            local_doc = json.loads(out)
        except ValueError:
            return status, ct, out
        docs = [local_doc]
        for st_n, payload in self._fanout_rest_exec(
                method, path, query, body, peers).values():
            if st_n != 200:
                continue
            try:
                doc_n = json.loads(payload)
            except ValueError:
                continue
            if isinstance(doc_n, dict):
                docs.append(doc_n)
        seen = set()
        meta, spans = [], []
        for d in docs:
            for ev in d.get("traceEvents", ()):
                key = json.dumps(ev, sort_keys=True)
                if key in seen:
                    continue
                seen.add(key)
                (meta if ev.get("ph") == "M" else spans).append(ev)
        spans.sort(key=lambda ev: (ev.get("ts", 0), ev.get("pid", 0)))
        # re-apply the request's limit AFTER the merge, in RECORDS (the
        # flight-recorder merge's lesson): each node already truncated
        # to its newest `limit` records, so without this the client
        # gets up to n_nodes x limit — and not the cluster-wide newest
        # slice. A record's stage events share (pid, args.rec).
        from urllib.parse import parse_qs
        try:
            limit = int((parse_qs(query).get("limit") or [256])[-1])
        except ValueError:
            limit = 256
        if limit > 0:
            newest: Dict[tuple, float] = {}
            for ev in spans:
                key = (ev.get("pid"), (ev.get("args") or {}).get("rec"))
                newest[key] = max(newest.get(key, 0), ev.get("ts", 0))
            keep = set(sorted(newest, key=lambda k: newest[k])[-limit:])
            spans = [ev for ev in spans
                     if (ev.get("pid"),
                         (ev.get("args") or {}).get("rec")) in keep]
        merged = dict(local_doc, traceEvents=meta + spans,
                      nodes_reporting=len(docs))
        return 200, "application/json", json.dumps(merged).encode()

    def _insights_top_queries(self, method, path, query, body):
        """Cluster ``GET /_insights/top_queries``: every node answers
        from its own heavy-hitter store (per-node stores, unlike the
        shared flightrec/profile rings — no dedup needed) and the
        front MERGES the sketches: per-key SUM of estimates across
        nodes, re-rank by the requested metric, then re-apply the
        request ``limit`` AFTER the merge — never concatenate per-node
        top-N lists (the flight-recorder merge's n_nodes x limit
        lesson, applied on day one)."""
        status, ct, out = self._local(method, path, query, body)
        peers = [n for n in self.node.node_ids if n != self.node.node_id]
        if not peers or method != "GET" or status != 200:
            return status, ct, out
        try:
            local_doc = json.loads(out)
        except ValueError:
            return status, ct, out
        docs = [local_doc]
        for st_n, payload in self._fanout_rest_exec(
                method, path, query, body, peers).values():
            if st_n != 200:
                continue
            try:
                doc_n = json.loads(payload)
            except ValueError:
                continue
            if isinstance(doc_n, dict):
                docs.append(doc_n)
        from urllib.parse import parse_qs
        from ..search import query_insight as _qi
        qs = parse_qs(query)
        try:
            limit = int((qs.get("limit") or [_qi.topn()])[-1])
        except ValueError:
            limit = _qi.topn()
        metric = (qs.get("metric") or ["count"])[-1]
        merged = _qi.merge_top_docs(docs, limit=limit, metric=metric)
        merged["nodes_reporting"] = len(docs)
        return 200, "application/json", json.dumps(merged).encode()

    def _profiler_flamegraph(self, method, path, query, body):
        """Cluster ``GET /_profiler/flamegraph``: every node answers
        from its own sampler windows over ``rest:exec`` and the front
        MERGES rows — per-path SUM of self-samples across nodes, re-rank,
        then re-apply the request ``limit`` AFTER the merge (the
        insights limit-after-truncate lesson). ``format=collapsed``
        renders the MERGED rows at the front, so the fan-out always
        carries JSON."""
        from urllib.parse import parse_qs, urlencode
        qs = parse_qs(query)
        fmt = (qs.get("format") or ["json"])[-1]
        fan_query = urlencode([(k, v) for k, vs in qs.items()
                               if k != "format" for v in vs])
        status, ct, out = self._local(method, path, fan_query, body)
        peers = [n for n in self.node.node_ids if n != self.node.node_id]
        if method != "GET" or status != 200:
            return status, ct, out
        try:
            local_doc = json.loads(out)
        except ValueError:
            return status, ct, out
        docs = [local_doc]
        for st_n, payload in self._fanout_rest_exec(
                method, path, fan_query, body, peers).values():
            if st_n != 200:
                continue
            try:
                doc_n = json.loads(payload)
            except ValueError:
                continue
            if isinstance(doc_n, dict):
                docs.append(doc_n)
        from ..common import contprof as _contprof
        try:
            limit = int((qs.get("limit") or
                         [_contprof.DEFAULT_LIMIT])[-1])
        except ValueError:
            limit = _contprof.DEFAULT_LIMIT
        merged = _contprof.merge_docs(docs, limit=limit)
        merged["nodes_reporting"] = len(docs)
        merged["window"] = local_doc.get("window", "current")
        if fmt == "collapsed":
            return (200, "text/plain; charset=UTF-8",
                    _contprof.collapsed_text(merged["rows"]).encode())
        return 200, "application/json", json.dumps(merged).encode()

    def _hot_threads(self, method, path, query, body, segs):
        """Cluster ``GET /_nodes[/{node_id}]/hot_threads``: fan the
        sampler out to every selected node (each samples ITS process)
        and concatenate the per-node text blocks — instead of the old
        behavior of sampling only the front's process view."""
        import fnmatch
        node_filter = segs[1] if len(segs) == 3 else None

        def selected(nid: str) -> bool:
            # cluster node NAMES are their ids (ClusterRestService
            # passes node_id as the api's node_name), so id matching
            # covers the name form of RestAPI._node_id_matches too
            if node_filter is None:
                return True
            for part in str(node_filter).split(","):
                part = part.strip()
                if part in ("", "_all") or \
                        fnmatch.fnmatchcase(nid, part):
                    return True
                if part == "_local" and nid == self.node.node_id:
                    return True
            return False

        bare = "/_nodes/hot_threads"      # target already selected

        # concurrent sampling (shared rest:exec helper): each node's
        # sampler runs a wall-clock snapshot window — serialized, a
        # 3-node default request would take 3× the interval plus any
        # dead-node timeout
        targets = [nid for nid in sorted(self.node.node_ids)
                   if selected(nid)]
        results: Dict[str, tuple] = {}
        lt = None
        if self.node.node_id in targets:
            targets.remove(self.node.node_id)

            def _local_sample():
                try:
                    st, _ct, out = self._local(method, bare, query, body)
                    results[self.node.node_id] = (st, out)
                except Exception:   # noqa: BLE001 — sample nothing
                    pass

            # the local sampler's wall-clock window runs CONCURRENTLY
            # with the remote fan-out, like any other node's
            lt = threading.Thread(target=_local_sample,
                                  name="es-monitoring-hotthreads")
            lt.start()
        remote = self._fanout_rest_exec(
            method, bare, query, body, targets, timeout=30.0)
        if lt is not None:
            lt.join()
        results.update(remote)
        blocks: List[str] = []
        for nid in sorted(results):
            st, out = results[nid]
            if st == 200 and out:
                blocks.append(out.decode(errors="replace").rstrip("\n"))
        return (200, "text/plain; charset=UTF-8",
                ("\n".join(blocks) + "\n").encode())

    def _cluster_shards_view(self, nodes, selected=None):
        """(status, active_shards, unassigned) from the published routing
        table; (None, 0, 0) when no routing exists yet. ``selected``
        restricts to an index subset (index-pattern health)."""
        st = self.node.applied_state
        routing = st.data.get("routing", {}) if st else {}
        if selected is not None:
            routing = {n: t for n, t in routing.items() if n in selected}
        if not routing:
            return (None, 0, 0) if selected is None else ("green", 0, 0)
        active = unassigned = 0
        status = "green"
        for name, table in routing.items():
            meta = st.metadata["indices"].get(name, {})
            want = int(meta.get("num_replicas", 0))
            for entry in table.values():
                if entry["primary"] in nodes:
                    active += 1
                else:
                    status = "red"
                    unassigned += 1
                have = len([r for r in entry["replicas"] if r in nodes])
                active += have
                if have < want:
                    unassigned += want - have
                    if status != "red":
                        status = "yellow"
        return status, active, unassigned

    def _alloc_explain(self, query: str, body: bytes):
        """GET /_cluster/allocation/explain — per-node decider verdicts
        (``ClusterAllocationExplainAction``)."""
        from ..cluster.allocation import AllocationContext, explain
        node = self.node
        st = node.applied_state
        if st is None:
            raise _errors.ElasticsearchError("no cluster state")
        routing = st.data.get("routing", {})
        spec = {}
        try:
            spec = json.loads(body or b"{}") or {}
        except ValueError:
            pass
        index, sid = spec.get("index"), spec.get("shard")
        primary = bool(spec.get("primary", True))
        force_unassigned = False
        live = sorted(node.live_nodes())
        if index is None:
            # default: the first unassigned copy — a primary-less shard,
            # or a shard whose replica count is below the configured want
            # (the reference explains a random unassigned shard)
            for iname, table in sorted(routing.items()):
                want = int((st.metadata["indices"].get(iname) or {})
                           .get("num_replicas", 0))
                for sid_s, entry in sorted(
                        table.items(), key=lambda kv: int(kv[0])):
                    if not entry.get("primary"):
                        index, sid, primary = iname, int(sid_s), True
                        force_unassigned = True
                        break
                    if len(entry.get("replicas", ())) < want:
                        index, sid, primary = iname, int(sid_s), False
                        force_unassigned = True
                        break
                if index is not None:
                    break
        if index is None:
            raise _errors.IllegalArgumentError(
                "unable to find any unassigned shards to explain "
                "(pass index and shard)")
        ctx = AllocationContext(
            live, routing, st.metadata["indices"],
            node_attrs=node.node_attrs,
            disk_used=dict(getattr(node, "_disk_used", {})),
            plane_storms=dict(getattr(node, "_plane_storms", {})))
        doc = explain(index, int(sid or 0), ctx, primary=primary,
                      force_unassigned=force_unassigned)
        if "include_disk_info=true" in (query or ""):
            doc["cluster_info"] = {
                "nodes": {n: {
                    "node_name": n,
                    "least_available": {"path": "/", "total_bytes": 0,
                                        "used_bytes": 0,
                                        "free_bytes": 0},
                    "most_available": {"path": "/", "total_bytes": 0,
                                       "used_bytes": 0, "free_bytes": 0},
                } for n in live},
            }
        return 200, "application/json", json.dumps(doc).encode()

    def _reroute(self, query: str, body: bytes = b""):
        """POST /_cluster/reroute — explicit commands (explained under
        ``explain``/``dry_run``), retry counter clearing, and a triggered
        allocation round on the master
        (``TransportClusterRerouteAction`` + ``AllocationCommands``)."""
        params = _parse_query(query)
        retry = params.get("retry_failed") in ("true", "")
        explain = params.get("explain") in ("true", "")
        dry_run = params.get("dry_run") in ("true", "")
        node = self.node
        spec = {}
        try:
            spec = json.loads(body or b"{}") or {}
        except ValueError:
            pass
        explanations = self._reroute_commands(
            spec.get("commands") or [], explain, dry_run)

        if not dry_run:
            leader = node.node_loop.sync(
                lambda: node.coordinator.known_leader)
            if leader == node.node_id:
                node._h_alloc_reroute(None, {"retry_failed": retry})
            elif leader is not None:
                # single long-timeout RPC, no retry: a reroute is not
                # idempotent-cheap (each execution re-clears counters
                # and queues an allocation round)
                node.rpc(leader, "alloc:reroute",
                         {"retry_failed": retry}, timeout=20.0)
            else:
                raise _errors.ElasticsearchError("no known master")
        out: Dict[str, Any] = {"acknowledged": True}
        # state sections by metric (the reference returns the resulting
        # cluster state filtered by ?metric=, default excludes metadata)
        metric = params.get("metric")
        st = node.applied_state
        state: Dict[str, Any] = {
            "cluster_uuid": "_na_", "version": st.version if st else 0}
        wanted = {m.strip() for m in metric.split(",")} if metric else set()
        if "metadata" in wanted or "_all" in wanted:
            with self.lock:
                state["metadata"] = {"indices": {
                    n: {"state": "close" if svc.closed else "open"}
                    for n, svc in self.indices.indices.items()}}
        if "nodes" in wanted or "_all" in wanted:
            state["nodes"] = {
                n: {"name": n} for n in sorted(st.nodes)} if st else {}
        out["state"] = state
        if explain:
            out["explanations"] = explanations
        return 200, "application/json", json.dumps(out).encode()

    def _reroute_commands(self, commands, explain: bool,
                          dry_run: bool) -> list:
        """Validate explicit allocation commands; an explanation entry per
        command mirrors ``AllocationCommand`` naming. Non-dry-run illegal
        commands raise (the reference 400s)."""
        node = self.node
        st = node.applied_state
        routing = (st.data.get("routing", {}) if st else {})
        out = []
        for cmd in commands:
            if not isinstance(cmd, dict) or len(cmd) != 1:
                raise _errors.IllegalArgumentError(
                    f"malformed reroute command {cmd!r}")
            (kind, args), = cmd.items()
            args = args or {}
            index = args.get("index")
            sid = str(args.get("shard", 0))
            target = args.get("from_node") if kind == "move" \
                else args.get("node")
            entry = (routing.get(index) or {}).get(sid)
            decider = f"{kind}_allocation_command"
            decisions = []
            if kind in ("cancel", "move"):
                on_node = entry is not None and (
                    entry.get("primary") == target or
                    target in entry.get("replicas", ()))
                if entry is None or not on_node:
                    decisions.append({
                        "decider": decider, "decision": "NO",
                        "explanation": (
                            f"can't {kind} {index} [{sid}]: failed to "
                            f"find shard copy on node [{target}]")})
                else:
                    decisions.append({
                        "decider": decider, "decision": "YES",
                        "explanation": f"shard copy found on [{target}]"})
            elif kind in ("allocate_replica", "allocate_stale_primary",
                          "allocate_empty_primary"):
                if entry is None:
                    decisions.append({
                        "decider": decider, "decision": "NO",
                        "explanation": f"no such shard [{index}][{sid}]"})
                else:
                    decisions.append({
                        "decider": decider, "decision": "YES",
                        "explanation": "allocation is permitted"})
            else:
                raise _errors.IllegalArgumentError(
                    f"unknown reroute command [{kind}]")
            params_out = {"index": index, "shard": int(args.get("shard", 0)),
                          "node": target}
            if kind in ("cancel", "allocate_stale_primary",
                        "allocate_empty_primary"):
                params_out["allow_primary"] = bool(
                    args.get("allow_primary", False))
            if kind == "move":
                params_out = {"index": index,
                              "shard": int(args.get("shard", 0)),
                              "from_node": args.get("from_node"),
                              "to_node": args.get("to_node")}
            bad = any(d["decision"] == "NO" for d in decisions)
            if bad and not dry_run:
                raise _errors.IllegalArgumentError(
                    decisions[0]["explanation"])
            out.append({"command": kind, "parameters": params_out,
                        "decisions": decisions})
        return out

    def _cluster_state(self, method, path, query, body):
        """Serve the LOCAL api's full cluster-state rendering (metric
        filtering, blocks, voting exclusions, cluster_uuid — the local
        service holds all metadata via op-log replay) and patch in the
        cluster-wide sections: master, the real node set, version, and
        the published routing table."""
        status, ct, out = self._local(method, path, query, body)
        if status != 200:
            return status, ct, out
        try:
            doc = json.loads(out)
        except ValueError:
            return status, ct, out
        st = self.node.applied_state
        if st is None or not isinstance(doc, dict):
            return status, ct, out
        if "master_node" in doc:
            doc["master_node"] = st.master_node
        if "nodes" in doc:
            doc["nodes"] = {
                n: {"name": n, "ephemeral_id": n,
                    "transport_address": "127.0.0.1:9300",
                    "attributes": {}, "roles": ["data", "ingest",
                                                "master"]}
                for n in sorted(st.nodes)}
        if "version" in doc:
            doc["version"] = st.version
        if "routing_table" in doc and st.data.get("routing"):
            # respect the local handler's index filtering: only patch
            # the indices its rendering selected
            sel = doc["routing_table"].get("indices") \
                if isinstance(doc["routing_table"], dict) else None
            doc["routing_table"] = {
                "indices": {
                    n: {"shards": {
                        sid: [{"state": "STARTED", "primary": True,
                               "node": e["primary"], "index": n,
                               "shard": int(sid)}]
                        for sid, e in table.items()}}
                    for n, table in st.data["routing"].items()
                    if sel is None or n in sel}}
        if self.meta_divergent:
            doc["meta_divergent"] = True
        return 200, "application/json", json.dumps(doc).encode()

    # ------------------------------------------------------------------
    # doc2 handlers (owner side) — registered by ClusterNode
    # ------------------------------------------------------------------

    def h_doc2_index(self, src, payload) -> dict:
        w = self._local_writer(payload)
        r = w.index(payload["id"], payload["source"],
                    routing=payload.get("routing"),
                    op_type=payload.get("op_type", "index"),
                    if_seq_no=payload.get("if_seq_no"),
                    if_primary_term=payload.get("if_primary_term"))
        seq = self._after_local("POST", f"/{payload['index']}/_doc/x",
                                b"")
        out = dict(r.__dict__)
        if seq:
            out["_meta_seq"] = seq
        return out

    def h_doc2_delete(self, src, payload) -> dict:
        w = self._local_writer(payload)
        r = w.delete(payload["id"],
                     if_seq_no=payload.get("if_seq_no"),
                     if_primary_term=payload.get("if_primary_term"))
        return dict(r.__dict__)

    def h_doc2_get(self, src, payload) -> dict:
        w = self._local_writer(payload)
        return dict(w.get(payload["id"]).__dict__)

    def _local_writer(self, payload) -> LocalGroupWriter:
        key = (payload["index"], int(payload["shard"]))
        group = self.node.primaries.get(key)
        if group is None:
            raise _errors.ElasticsearchError(
                f"shard [{key}] is not primaried on [{self.node.node_id}]")
        return LocalGroupWriter(group)
