"""Node layer: index lifecycle, routing, and request execution on one node.

Re-design of the reference's node-level services
(``indices/IndicesService.java:176`` owning per-index ``IndexService`` →
``IndexShard`` instances; ``node/Node.java`` wiring). One process owns a
set of indices; each index has N shards (each an ``index.engine.Engine``),
docs route to shards by Murmur3, and searches fan out over every shard's
segments with global (DFS-quality) term statistics.
"""

from .indices_service import IndexService, IndicesService

__all__ = ["IndexService", "IndicesService"]
