"""Task registry with real cancellation (reference:
``tasks/TaskManager.java:76``, ``tasks/TaskCancellationService.java:47``).

Every REST request registers a task for its lifetime; long-running
actions (reindex, update/delete-by-query, scatter-gather search) register
*cancellable* tasks and poll :meth:`Task.check_cancelled` at batch
boundaries, so a runaway operation can be killed mid-flight via
``POST /_tasks/{id}/_cancel``. Cancelling a task also cancels its
children (the reference's ban propagation — here child tasks registered
under a ``parent_task_id``; the cluster layer additionally fans the
cancel out to other nodes' managers over the transport).

Async execution (``wait_for_completion=false``) runs the action on a
daemon thread and stores the result on the task, the analog of the
reference's task-result index (``TaskResultsService``).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from ..common.errors import ElasticsearchError


class TaskCancelledError(ElasticsearchError):
    status = 400
    error_type = "task_cancelled_exception"


class Task:
    def __init__(self, manager: "TaskManager", task_id: int, action: str,
                 description: str = "", cancellable: bool = False,
                 parent_task_id: Optional[str] = None,
                 headers: Optional[Dict[str, str]] = None):
        self.manager = manager
        self.id = task_id
        self.node = manager.node_id
        self.action = action
        self.description = description
        self.cancellable = cancellable
        self.parent_task_id = parent_task_id
        self.headers = dict(headers or {})
        self.start_time = time.time()
        self.running = True
        self.cancelled = threading.Event()
        self.cancel_reason: Optional[str] = None
        self.completed = threading.Event()
        self.result: Optional[dict] = None
        self.error: Optional[dict] = None
        #: live progress counters for _tasks status rendering (reindex &
        #: friends update these as they go)
        self.status: Dict[str, object] = {}

    @property
    def tid(self) -> str:
        return f"{self.node}:{self.id}"

    def check_cancelled(self) -> None:
        if self.cancelled.is_set():
            raise TaskCancelledError(
                f"task cancelled [{self.cancel_reason or 'by user request'}]")

    def to_dict(self) -> dict:
        now = time.time()
        doc = {
            "node": self.node,
            "id": self.id,
            "type": "transport",
            "action": self.action,
            "description": self.description,
            "start_time_in_millis": int(self.start_time * 1000),
            "running_time_in_nanos": int((now - self.start_time) * 1e9),
            "cancellable": self.cancellable,
            "cancelled": self.cancelled.is_set(),
            "headers": self.headers,
        }
        if self.status:
            doc["status"] = dict(self.status)
        if self.parent_task_id:
            doc["parent_task_id"] = self.parent_task_id
        return doc


class TaskManager:
    """Per-node registry. Completed async tasks are retained (bounded) so
    ``GET /_tasks/{id}`` can return their stored result."""

    RESULT_RETENTION = 256

    def __init__(self, node_id: str, node_name: str):
        self.node_id = node_id
        self.node_name = node_name
        self.lock = threading.Lock()
        self._next_id = 0
        self.tasks: Dict[int, Task] = {}
        self.finished: Dict[int, Task] = {}

    def register(self, action: str, description: str = "",
                 cancellable: bool = False,
                 parent_task_id: Optional[str] = None,
                 headers: Optional[Dict[str, str]] = None) -> Task:
        with self.lock:
            self._next_id += 1
            t = Task(self, self._next_id, action, description, cancellable,
                     parent_task_id, headers)
            self.tasks[t.id] = t
            return t

    def unregister(self, task: Task, *, retain: bool = False) -> None:
        task.running = False
        task.completed.set()
        with self.lock:
            self.tasks.pop(task.id, None)
            if retain:
                self.finished[task.id] = task
                while len(self.finished) > self.RESULT_RETENTION:
                    self.finished.pop(next(iter(self.finished)))

    def get(self, task_id: int) -> Optional[Task]:
        with self.lock:
            return self.tasks.get(task_id) or self.finished.get(task_id)

    def cancel(self, task: Task, reason: str = "by user request") -> None:
        """Cancel ``task`` and every registered descendant (ban
        propagation across the local parent/child tree)."""
        with self.lock:
            live = list(self.tasks.values())
        to_cancel = [task]
        frontier = {task.tid}
        # breadth-first over parent links
        while True:
            added = [t for t in live
                     if t.parent_task_id in frontier
                     and t not in to_cancel]
            if not added:
                break
            to_cancel.extend(added)
            frontier = {t.tid for t in added}
        for t in to_cancel:
            if t.cancellable:
                t.cancel_reason = reason
                t.cancelled.set()

    def cancel_matching(self, *, actions: Optional[List[str]] = None,
                        reason: str = "by user request") -> List[Task]:
        import fnmatch
        with self.lock:
            live = list(self.tasks.values())
        hit = []
        for t in live:
            if actions and not any(fnmatch.fnmatchcase(t.action, p)
                                   for p in actions):
                continue
            if not t.cancellable:
                continue
            hit.append(t)
        for t in hit:
            self.cancel(t, reason)
        return hit

    def list(self, *, actions: Optional[List[str]] = None,
             include_finished: bool = False) -> List[Task]:
        import fnmatch
        with self.lock:
            out = list(self.tasks.values())
            if include_finished:
                out += list(self.finished.values())
        if actions:
            out = [t for t in out
                   if any(fnmatch.fnmatchcase(t.action, p)
                          for p in actions)]
        return sorted(out, key=lambda t: t.id)

    def run_async(self, task: Task, fn: Callable[[], dict]) -> None:
        """Execute ``fn`` on a daemon thread; store its result/error on
        the task for later ``GET /_tasks/{id}`` retrieval."""
        task.async_detached = True      # request teardown must not unregister

        def runner():
            try:
                task.result = fn()
            except Exception as e:   # noqa: BLE001 — stored, not raised
                from ..rest.api import _error_payload
                status, payload = _error_payload(e)
                task.error = payload.get("error") if isinstance(
                    payload.get("error"), dict) else {
                        "type": "exception", "reason": str(payload)}
            finally:
                self.unregister(task, retain=True)

        threading.Thread(target=runner, daemon=True,
                         name=f"task-{task.tid}").start()
