"""Task registry with real cancellation (reference:
``tasks/TaskManager.java:76``, ``tasks/TaskCancellationService.java:47``).

Every REST request registers a task for its lifetime; long-running
actions (reindex, update/delete-by-query, scatter-gather search) register
*cancellable* tasks and poll :meth:`Task.check_cancelled` at batch
boundaries, so a runaway operation can be killed mid-flight via
``POST /_tasks/{id}/_cancel``. Cancelling a task also cancels its
children (the reference's ban propagation — here child tasks registered
under a ``parent_task_id``; the cluster layer additionally fans the
cancel out to other nodes' managers over the transport).

Async execution (``wait_for_completion=false``) runs the action on a
daemon thread and stores the result on the task, the analog of the
reference's task-result index (``TaskResultsService``).

Resource attribution (reference: ``tasks/TaskResourceTrackingService``
behind ``_tasks?detailed`` CPU/memory): every task carries a
:class:`TaskResources` ledger. The REST edge binds it into a
``contextvars`` context (:func:`bind_resources`) so any layer on the
request's call path — shard search, plane micro-batch fan-out, the
cluster coordinator — can charge work to the owning task without
argument plumbing:

- host CPU-ms via ``time.thread_time`` deltas at stage boundaries
  (:meth:`TaskResources.cpu_mark` / :meth:`cpu_checkpoint` — O(1) per
  boundary, one dict probe under a lock);
- device dispatch-ms, h2d/d2h transfer bytes and docs scanned (base
  corpus + delta tier) stamped by the serving path after each dispatch;
- cross-node roll-up: data nodes return their shard-phase ledger in the
  ``search:shards`` RPC response and the coordinator merges it
  (:meth:`TaskResources.merge_doc`), so a cluster search reports ONE
  total.

Completed tasks fold their ledger into per-action totals the manager
exposes as ``es_task_*`` telemetry families (in-flight tasks contribute
their live ledger at snapshot time, keeping the counters monotonic).
"""

from __future__ import annotations

import contextvars
import threading
import time
from typing import Callable, Dict, List, Optional

from ..common.errors import ElasticsearchError

#: the resource ledger charged by work on this context, or None
#: (maintenance paths stay free — mirrors tracing._CTX)
_RES_CTX: contextvars.ContextVar = contextvars.ContextVar(
    "es_task_resources", default=None)


def bind_resources(res: "TaskResources"):
    """Bind ``res`` as the context's charge target; returns the reset
    token."""
    return _RES_CTX.set(res)


def unbind_resources(token) -> None:
    _RES_CTX.reset(token)


def current_resources() -> Optional["TaskResources"]:
    return _RES_CTX.get()


class TaskResources:
    """Per-task resource ledger. All mutators are O(1) and lock-cheap —
    they run at stage boundaries on the serving hot path."""

    __slots__ = ("_lock", "cpu_ms", "device_ms", "h2d_bytes", "d2h_bytes",
                 "docs_scanned", "delta_docs_scanned", "dispatches",
                 "_cpu_marks", "shapes")

    #: retained distinct query shape ids per task — bounded: an msearch
    #: with hundreds of bodies keeps the first few, which is enough to
    #: join the ledger to /_insights/top_queries
    SHAPES_MAX = 8

    def __init__(self):
        self._lock = threading.Lock()
        self.cpu_ms = 0.0
        self.device_ms = 0.0
        self.h2d_bytes = 0
        self.d2h_bytes = 0
        self.docs_scanned = 0
        self.delta_docs_scanned = 0
        self.dispatches = 0
        #: query shape ids observed under this task, insertion-ordered
        self.shapes: List[str] = []
        #: thread ident -> last ``time.thread_time()`` mark — per-thread
        #: so an async task's worker and the request thread never mix
        self._cpu_marks: Dict[int, float] = {}

    # -- CPU boundaries ------------------------------------------------------

    def cpu_mark(self) -> None:
        """Start (or restart) this thread's CPU accounting window."""
        with self._lock:
            self._cpu_marks[threading.get_ident()] = time.thread_time()

    def cpu_checkpoint(self) -> None:
        """Fold this thread's CPU since its last mark into ``cpu_ms`` and
        advance the mark — called at stage boundaries so an in-flight
        task already shows the CPU its finished stages burned."""
        now = time.thread_time()
        tid = threading.get_ident()
        with self._lock:
            last = self._cpu_marks.get(tid)
            if last is not None:
                self.cpu_ms += (now - last) * 1e3
            self._cpu_marks[tid] = now

    def cpu_release(self) -> None:
        """Final checkpoint + drop this thread's mark (request teardown)."""
        self.cpu_checkpoint()
        with self._lock:
            self._cpu_marks.pop(threading.get_ident(), None)

    # -- device / scan accounting -------------------------------------------

    def add(self, *, device_ms: float = 0.0, h2d_bytes: int = 0,
            d2h_bytes: int = 0, docs_scanned: int = 0,
            delta_docs_scanned: int = 0, cpu_ms: float = 0.0,
            dispatches: int = 0) -> None:
        with self._lock:
            self.cpu_ms += cpu_ms
            self.device_ms += device_ms
            self.h2d_bytes += int(h2d_bytes)
            self.d2h_bytes += int(d2h_bytes)
            self.docs_scanned += int(docs_scanned)
            self.delta_docs_scanned += int(delta_docs_scanned)
            self.dispatches += int(dispatches)

    def merge_doc(self, doc: dict) -> None:
        """Coordinator-side roll-up of a data node's wire ledger
        (``search:shards`` response ``_resources``)."""
        if not isinstance(doc, dict):
            return
        xfer = doc.get("transfer_bytes") or {}
        self.add(cpu_ms=float(doc.get("cpu_time_ms", 0.0)),
                 device_ms=float(doc.get("device_time_ms", 0.0)),
                 h2d_bytes=int(xfer.get("h2d", 0)),
                 d2h_bytes=int(xfer.get("d2h", 0)),
                 docs_scanned=int(doc.get("docs_scanned", 0)),
                 delta_docs_scanned=int(doc.get("delta_docs_scanned", 0)),
                 dispatches=int(doc.get("dispatches", 0)))

    def note_shape(self, shape_id: str) -> None:
        """Record a query shape id served under this task (bounded,
        first-seen order)."""
        if not shape_id:
            return
        with self._lock:
            if shape_id not in self.shapes and \
                    len(self.shapes) < self.SHAPES_MAX:
                self.shapes.append(shape_id)

    def to_dict(self) -> dict:
        with self._lock:
            doc = {
                "cpu_time_ms": round(self.cpu_ms, 3),
                "device_time_ms": round(self.device_ms, 3),
                "transfer_bytes": {"h2d": self.h2d_bytes,
                                   "d2h": self.d2h_bytes},
                "docs_scanned": self.docs_scanned,
                "delta_docs_scanned": self.delta_docs_scanned,
                "dispatches": self.dispatches,
            }
            if self.shapes:
                doc["shapes"] = list(self.shapes)
            return doc


class TaskCancelledError(ElasticsearchError):
    status = 400
    error_type = "task_cancelled_exception"


class Task:
    def __init__(self, manager: "TaskManager", task_id: int, action: str,
                 description: str = "", cancellable: bool = False,
                 parent_task_id: Optional[str] = None,
                 headers: Optional[Dict[str, str]] = None):
        self.manager = manager
        self.id = task_id
        self.node = manager.node_id
        self.action = action
        self.description = description
        self.cancellable = cancellable
        self.parent_task_id = parent_task_id
        self.headers = dict(headers or {})
        self.start_time = time.time()
        self.running = True
        self.cancelled = threading.Event()
        self.cancel_reason: Optional[str] = None
        self.completed = threading.Event()
        self.result: Optional[dict] = None
        self.error: Optional[dict] = None
        #: live progress counters for _tasks status rendering (reindex &
        #: friends update these as they go)
        self.status: Dict[str, object] = {}
        #: per-task resource ledger (``_tasks?detailed`` resource_stats)
        self.resources = TaskResources()

    @property
    def tid(self) -> str:
        return f"{self.node}:{self.id}"

    def check_cancelled(self) -> None:
        if self.cancelled.is_set():
            raise TaskCancelledError(
                f"task cancelled [{self.cancel_reason or 'by user request'}]")

    def to_dict(self, detailed: bool = False) -> dict:
        now = time.time()
        doc = {
            "node": self.node,
            "id": self.id,
            "type": "transport",
            "action": self.action,
            "description": self.description,
            "start_time_in_millis": int(self.start_time * 1000),
            "running_time_in_nanos": int((now - self.start_time) * 1e9),
            "cancellable": self.cancellable,
            "cancelled": self.cancelled.is_set(),
            "headers": self.headers,
        }
        if detailed:
            # an in-flight task's ledger is live: CPU folds in at each
            # stage boundary, device/docs after each dispatch — so
            # _tasks?detailed already attributes a running plane search
            doc["resource_stats"] = self.resources.to_dict()
        if self.status:
            doc["status"] = dict(self.status)
        if self.parent_task_id:
            doc["parent_task_id"] = self.parent_task_id
        return doc


class TaskManager:
    """Per-node registry. Completed async tasks are retained (bounded) so
    ``GET /_tasks/{id}`` can return their stored result."""

    RESULT_RETENTION = 256

    def __init__(self, node_id: str, node_name: str):
        self.node_id = node_id
        self.node_name = node_name
        self.lock = threading.Lock()
        self._next_id = 0
        self.tasks: Dict[int, Task] = {}
        self.finished: Dict[int, Task] = {}
        #: action -> folded resource totals of completed tasks (the
        #: es_task_* registry families; live tasks add their in-flight
        #: ledger at snapshot time, so the counters stay monotonic)
        self._res_lock = threading.Lock()
        self._action_totals: Dict[str, Dict[str, float]] = {}
        #: X-Opaque-Id → folded per-tenant usage (the metering
        #: prerequisite for multi-tenant QoS: request count, wall
        #: latency, device-ms, docs scanned, cpu-ms). Bounded by the
        #: registry's series-cardinality cap — tenants past it collapse
        #: into one "overflow" row, the registry's own overflow shape.
        self._tenant_totals: Dict[str, Dict[str, float]] = {}
        from ..common import telemetry as _tm
        self.TENANT_MAX = _tm.TelemetryRegistry.MAX_SERIES
        _tm.DEFAULT.register_object_collector(
            f"tasks:{node_id}", self, TaskManager._task_families)

    _RES_KEYS = ("cpu_ms", "device_ms", "h2d_bytes", "d2h_bytes",
                 "docs_scanned", "delta_docs_scanned", "dispatches")

    _TENANT_KEYS = ("requests", "latency_ms", "device_ms",
                    "docs_scanned", "cpu_ms")

    def _fold_resources(self, task: Task) -> None:
        r = task.resources
        with r._lock:
            vals = {k: getattr(r, k) for k in self._RES_KEYS}
        tenant = task.headers.get("X-Opaque-Id")
        if tenant:
            self._fold_tenant(str(tenant), task, vals,
                              time.time() - task.start_time)
            # QoS charge point: the tenant's token bucket pays for the
            # task's ACTUAL cpu-ms / device-ms / transfer bytes (post-
            # paid — debt blocks the tenant's next admission), not a
            # flat per-request cost
            try:
                from ..common import qos as _qos
                _qos.controller().charge(
                    str(tenant), cpu_ms=vals.get("cpu_ms", 0.0),
                    device_ms=vals.get("device_ms", 0.0),
                    bytes_=vals.get("h2d_bytes", 0)
                    + vals.get("d2h_bytes", 0))
            except Exception:   # noqa: BLE001 — QoS must not fail
                pass            # task teardown
        if not any(vals.values()):
            return
        with self._res_lock:
            tot = self._action_totals.setdefault(
                task.action, {k: 0.0 for k in self._RES_KEYS})
            tot["count"] = tot.get("count", 0) + 1
            for k, v in vals.items():
                tot[k] += v

    def _fold_tenant(self, tenant: str, task: Task, vals: dict,
                     wall_s: float) -> None:
        with self._res_lock:
            if tenant not in self._tenant_totals and \
                    len(self._tenant_totals) >= self.TENANT_MAX:
                tenant = "overflow"
            tot = self._tenant_totals.setdefault(
                tenant, {k: 0.0 for k in self._TENANT_KEYS})
            tot["requests"] += 1
            tot["latency_ms"] += wall_s * 1e3
            tot["device_ms"] += vals.get("device_ms", 0.0)
            tot["docs_scanned"] += vals.get("docs_scanned", 0)
            tot["cpu_ms"] += vals.get("cpu_ms", 0.0)

    def tenant_totals(self) -> Dict[str, Dict[str, float]]:
        """Per-tenant (X-Opaque-Id) usage: completed tasks' folded
        rollups plus every live opaque-labeled task's current ledger at
        snapshot time (monotone, like :meth:`action_totals`)."""
        with self._res_lock:
            out = {t: dict(v) for t, v in self._tenant_totals.items()}
        now = time.time()
        with self.lock:
            live = list(self.tasks.values())
        for t in live:
            tenant = t.headers.get("X-Opaque-Id")
            if not tenant:
                continue
            tenant = str(tenant)
            if tenant not in out and len(out) >= self.TENANT_MAX:
                tenant = "overflow"
            r = t.resources
            with r._lock:
                dev, docs, cpu = r.device_ms, r.docs_scanned, r.cpu_ms
            tot = out.setdefault(
                tenant, {k: 0.0 for k in self._TENANT_KEYS})
            tot["requests"] += 1
            tot["latency_ms"] += (now - t.start_time) * 1e3
            tot["device_ms"] += dev
            tot["docs_scanned"] += docs
            tot["cpu_ms"] += cpu
        return out

    def action_totals(self) -> Dict[str, Dict[str, float]]:
        """Per-action resource totals: completed tasks' folded ledgers
        plus every live task's current ledger (tests / bench rollups)."""
        with self._res_lock:
            out = {a: dict(t) for a, t in self._action_totals.items()}
        with self.lock:
            live = list(self.tasks.values())
        for t in live:
            r = t.resources
            with r._lock:
                vals = {k: getattr(r, k) for k in self._RES_KEYS}
            if not any(vals.values()):
                continue
            tot = out.setdefault(t.action, {k: 0.0 for k in self._RES_KEYS})
            for k, v in vals.items():
                tot[k] = tot.get(k, 0) + v
        return out

    def _task_families(self) -> dict:
        """Registry collector: per-task resource attribution rolled up by
        action (``es_task_*`` — the per-request analog of the reference's
        ``_tasks?detailed`` CPU tracking, exported for scrapes)."""
        lbl = {"node": self.node_name}
        totals = self.action_totals()
        cpu, dev, xfer, docs, count = [], [], [], [], []
        for action, tot in sorted(totals.items()):
            alb = dict(lbl, action=action)
            cpu.append((alb, round(tot.get("cpu_ms", 0.0), 3)))
            dev.append((alb, round(tot.get("device_ms", 0.0), 3)))
            xfer.append((dict(alb, direction="h2d"),
                         int(tot.get("h2d_bytes", 0))))
            xfer.append((dict(alb, direction="d2h"),
                         int(tot.get("d2h_bytes", 0))))
            docs.append((alb, int(tot.get("docs_scanned", 0))))
            count.append((alb, int(tot.get("count", 0))))
        # per-tenant (X-Opaque-Id) rollup — the metering prerequisite
        # for multi-tenant QoS: who is burning the latency budget,
        # device time and scan volume (bounded: tenants past the
        # registry series cap fold into one "overflow" row)
        t_req, t_lat, t_dev, t_docs = [], [], [], []
        for tenant, tot in sorted(self.tenant_totals().items()):
            tlb = dict(lbl, tenant=tenant)
            t_req.append((tlb, int(tot.get("requests", 0))))
            t_lat.append((tlb, round(tot.get("latency_ms", 0.0), 3)))
            t_dev.append((tlb, round(tot.get("device_ms", 0.0), 3)))
            t_docs.append((tlb, int(tot.get("docs_scanned", 0))))
        out = {}
        if t_req:
            out.update({
                "es_tenant_requests_total": {
                    "type": "counter",
                    "help": "requests attributed to X-Opaque-Id tenants",
                    "samples": t_req},
                "es_tenant_latency_millis_total": {
                    "type": "counter",
                    "help": "wall latency attributed to tenants",
                    "samples": t_lat},
                "es_tenant_device_millis_total": {
                    "type": "counter",
                    "help": "device dispatch-ms attributed to tenants",
                    "samples": t_dev},
                "es_tenant_docs_scanned_total": {
                    "type": "counter",
                    "help": "docs scanned attributed to tenants",
                    "samples": t_docs},
            })
        out.update({
            "es_task_cpu_millis_total": {
                "type": "counter",
                "help": "host CPU-ms attributed to tasks by action",
                "samples": cpu},
            "es_task_device_millis_total": {
                "type": "counter",
                "help": "device dispatch-ms attributed to tasks by action",
                "samples": dev},
            "es_task_transfer_bytes_total": {
                "type": "counter",
                "help": "h2d/d2h bytes attributed to tasks by action",
                "samples": xfer},
            "es_task_docs_scanned_total": {
                "type": "counter",
                "help": "docs scanned (base + delta tier) by action",
                "samples": docs},
            "es_tasks_completed_total": {
                "type": "counter",
                "help": "tasks completed with non-zero resource usage",
                "samples": count},
        })
        return out

    def register(self, action: str, description: str = "",
                 cancellable: bool = False,
                 parent_task_id: Optional[str] = None,
                 headers: Optional[Dict[str, str]] = None) -> Task:
        with self.lock:
            self._next_id += 1
            t = Task(self, self._next_id, action, description, cancellable,
                     parent_task_id, headers)
            self.tasks[t.id] = t
            return t

    def unregister(self, task: Task, *, retain: bool = False) -> None:
        task.running = False
        task.completed.set()
        self._fold_resources(task)
        with self.lock:
            self.tasks.pop(task.id, None)
            if retain:
                self.finished[task.id] = task
                while len(self.finished) > self.RESULT_RETENTION:
                    self.finished.pop(next(iter(self.finished)))

    def get(self, task_id: int) -> Optional[Task]:
        with self.lock:
            return self.tasks.get(task_id) or self.finished.get(task_id)

    def cancel(self, task: Task, reason: str = "by user request") -> None:
        """Cancel ``task`` and every registered descendant (ban
        propagation across the local parent/child tree)."""
        with self.lock:
            live = list(self.tasks.values())
        to_cancel = [task]
        frontier = {task.tid}
        # breadth-first over parent links
        while True:
            added = [t for t in live
                     if t.parent_task_id in frontier
                     and t not in to_cancel]
            if not added:
                break
            to_cancel.extend(added)
            frontier = {t.tid for t in added}
        for t in to_cancel:
            if t.cancellable:
                t.cancel_reason = reason
                t.cancelled.set()

    def cancel_matching(self, *, actions: Optional[List[str]] = None,
                        reason: str = "by user request") -> List[Task]:
        import fnmatch
        with self.lock:
            live = list(self.tasks.values())
        hit = []
        for t in live:
            if actions and not any(fnmatch.fnmatchcase(t.action, p)
                                   for p in actions):
                continue
            if not t.cancellable:
                continue
            hit.append(t)
        for t in hit:
            self.cancel(t, reason)
        return hit

    def list(self, *, actions: Optional[List[str]] = None,
             include_finished: bool = False) -> List[Task]:
        import fnmatch
        with self.lock:
            out = list(self.tasks.values())
            if include_finished:
                out += list(self.finished.values())
        if actions:
            out = [t for t in out
                   if any(fnmatch.fnmatchcase(t.action, p)
                          for p in actions)]
        return sorted(out, key=lambda t: t.id)

    def run_async(self, task: Task, fn: Callable[[], dict]) -> None:
        """Execute ``fn`` on a daemon thread; store its result/error on
        the task for later ``GET /_tasks/{id}`` retrieval."""
        task.async_detached = True      # request teardown must not unregister

        def runner():
            # the worker thread charges the SAME task ledger the request
            # thread opened (per-thread CPU marks keep them separate)
            token = bind_resources(task.resources)
            task.resources.cpu_mark()
            try:
                task.result = fn()
            except Exception as e:   # noqa: BLE001 — stored, not raised
                from ..rest.api import _error_payload
                status, payload = _error_payload(e)
                task.error = payload.get("error") if isinstance(
                    payload.get("error"), dict) else {
                        "type": "exception", "reason": str(payload)}
            finally:
                task.resources.cpu_release()
                unbind_resources(token)
                self.unregister(task, retain=True)

        threading.Thread(target=runner, daemon=True,
                         name=f"es-task-{task.tid}").start()
