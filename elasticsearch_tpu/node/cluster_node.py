"""Multi-node cluster: coordination over TCP + routed data operations.

This is the multi-process tier the round-1 verdict called missing #1: the
same Coordinator that runs in the deterministic sim (``cluster/``) runs
here over :class:`~elasticsearch_tpu.transport.tcp.TcpTransport`, and the
committed cluster state drives shard allocation on every node
(``cluster/service/ClusterApplierService.java:68`` applying index
metadata + routing). The data plane on top:

- **Allocation**: the master assigns each shard's primary round-robin
  over live nodes and ``number_of_replicas`` replica copies to the next
  nodes (the reference's ``BalancedShardsAllocator``, reduced to its
  simplest deterministic policy).
- **Document ops** route by murmur3 (the same function the single-node
  path uses) and forward to the primary node
  (``TransportReplicationAction`` phase 1); the primary fans out through
  RPC-backed replica channels (phase 2) with primary-term fencing intact.
- **Search** scatters to one node per shard copy and merges exactly: hits
  through the coordinator comparator, aggregation PARTIALS (not reduced
  per node) shipped over the data-only wire codec
  (``common/datacodec.py`` — the reference's ``StreamOutput`` analog:
  structured data, never native object serialization) and reduced once —
  the same exactness contract as ``search/dist_query.py``.
- **Failure handling**: the elected master watches data nodes through its
  coordinator heartbeats; when a node leaves, it submits a routing update
  promoting in-sync replicas of every shard the dead node primaried
  (``FollowersChecker`` → shard-failed → ``RoutingNodes.failShard``).

Threading: each node is single-threaded on its transport loop; public
methods marshal onto it (``NodeLoop.sync``).
"""

from __future__ import annotations

import base64
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..cluster.coordination import Coordinator, NotLeaderError
from ..cluster.state import ClusterState
from ..common.datacodec import dumps_b64 as _data64
from ..common.datacodec import loads_b64 as _undata64
from ..common.retry import TIMEOUTS, backoff_delays
from ..common.errors import ElasticsearchError, IndexNotFoundError
from ..index.engine import Engine
from ..index.mapping import MapperService
from ..index.replication import (PrimaryShardGroup, ReplicaFencedError,
                                 ReplicaShard, promote_to_primary)
from ..search.dist_query import DistributedSearcher, merge_sort_key
from ..search.shard_search import ShardSearcher, normalize_sort
from ..transport.tcp import (AsyncTaskQueue, NodeLoop, RemoteTransportError,
                             TcpTransport)
from ..utils.murmur3 import shard_for as _murmur_shard


def shard_for(doc_id: str, routing: Optional[str], num_shards: int) -> int:
    return _murmur_shard(routing if routing is not None else doc_id,
                         num_shards)


class RpcReplicaChannel:
    """ReplicaChannel over the transport: the replica copy lives on
    another node (``TransportReplicationAction.ReplicaOperation``)."""

    def __init__(self, node: "ClusterNode", target_node: str, index: str,
                 shard_id: int, allocation_id: str):
        self.node = node
        self.target_node = target_node
        self.index_name = index          # NOT .index — that's the method
        self.shard_id = shard_id
        self.allocation_id = allocation_id

    def _call(self, action: str, payload: dict,
              timeout: Optional[float] = None):
        if timeout is None:
            timeout = TIMEOUTS.data
        payload = dict(payload, index=self.index_name, shard=self.shard_id)
        try:
            return self.node.rpc(self.target_node, action, payload,
                                 timeout=timeout)
        except RemoteTransportError as e:
            if e.remote_type == "ReplicaFencedError":
                # semantic round-trip: the remote copy is on a newer
                # primary term — the group-level deposed handling must see
                # the real exception type, not a generic replica failure
                raise ReplicaFencedError(str(e)) from e
            raise

    def index(self, primary_term, seq_no, version, doc_id, source, routing,
              global_checkpoint):
        return self._call("replica:index", {
            "primary_term": primary_term, "seq_no": seq_no,
            "version": version, "id": doc_id, "source": source,
            "routing": routing, "gcp": global_checkpoint})

    def delete(self, primary_term, seq_no, version, doc_id,
               global_checkpoint):
        return self._call("replica:delete", {
            "primary_term": primary_term, "seq_no": seq_no,
            "version": version, "id": doc_id, "gcp": global_checkpoint})

    def translog_op(self, primary_term, op):
        return self._call("replica:translog_op", {
            "primary_term": primary_term, "op": op.to_dict()})

    def sync_gcp(self, global_checkpoint):
        return self._call("replica:sync_gcp", {"gcp": global_checkpoint})


class ClusterNode:
    """One process-level node (in tests: one object per node, each with
    its own loop thread, port, and data directory)."""

    def __init__(self, node_id: str, host: str, port: int,
                 peers: Dict[str, Tuple[str, int]], data_path: str,
                 seed: int = 0,
                 node_attrs: Optional[Dict[str, dict]] = None,
                 shared_secret: Optional[str] = None,
                 transport_ssl: Optional[tuple] = None,
                 security=None):
        self.node_id = node_id
        self.data_path = data_path
        #: awareness/filter attributes for EVERY node (static membership)
        self.node_attrs = node_attrs or {}
        #: master-side liveness + disk usage learned from watch pings
        self._live_nodes: Optional[set] = None
        self._disk_used: Dict[str, float] = {}
        os.makedirs(data_path, exist_ok=True)
        self.node_loop = NodeLoop()
        all_peers = dict(peers)
        all_peers.pop(node_id, None)
        ssl_srv, ssl_cli = transport_ssl or (None, None)
        self.transport = TcpTransport(node_id, host, port, all_peers,
                                      self.node_loop.loop,
                                      shared_secret=shared_secret,
                                      ssl_server_ctx=ssl_srv,
                                      ssl_client_ctx=ssl_cli)
        self.queue = AsyncTaskQueue(self.node_loop.loop, seed=seed)
        self.node_ids = sorted(list(peers) + [node_id]) \
            if node_id not in peers else sorted(peers)
        # local data shards: (index, shard_id) -> PrimaryShardGroup | ReplicaShard
        self.primaries: Dict[Tuple[str, int], PrimaryShardGroup] = {}
        self.replicas: Dict[Tuple[str, int], ReplicaShard] = {}
        self.mappers: Dict[str, MapperService] = {}
        self.applied_state: Optional[ClusterState] = None
        # ALL data-plane work runs on this single worker: engine access is
        # serialized, and (unlike the transport loop) the worker may issue
        # synchronous RPCs — the loop stays free to deliver the responses
        self._data_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix=f"es-data-{node_id}")
        # separate single-thread lanes so one class of work never queues
        # behind another class blocked on a cross-node RPC (the reference
        # runs 17 purpose-specific pools — threadpool/ThreadPool.java):
        # replica-apply ops never wait behind a doc op fanning out to THIS
        # node's peer, and metadata ops never wait behind either.
        self._replica_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix=f"es-replica-{node_id}")
        # read-only metadata lane (search:stats / search:shards /
        # can_match / stats:shards): reads over immutable searcher
        # snapshots, safe off the single writer
        self._read_pool = ThreadPoolExecutor(
            max_workers=2, thread_name_prefix=f"es-read-{node_id}")
        # recovery lane: warm-handoff transfer/import + donor-side
        # bundle serialization are seconds-long — on the read lane they
        # would starve live search:shards RPCs through exactly the
        # recovery window serving must survive. Two workers so a pull
        # and a donor-side manifest/chunk handler can overlap.
        self._recovery_pool = ThreadPoolExecutor(
            max_workers=2, thread_name_prefix=f"es-recovery-{node_id}")
        #: allocation ids with a recovery task (incl. retry chain) in
        #: flight — state applications must not resubmit them
        self._recovering: set = set()
        #: warm plane handoff (recovery:plane_* RPCs): prepared exports
        #: by transfer id (chunked, resumable) + in-flight pulls, both
        #: under one lock; ES_TPU_PLANE_HANDOFF=0 disables (the chaos
        #: bench's repack baseline)
        self.plane_handoff_enabled = os.environ.get(
            "ES_TPU_PLANE_HANDOFF", "1").lower() not in ("0", "false")
        self._plane_exports: Dict[str, dict] = {}
        self._handoff_inflight: set = set()
        self._plane_export_lock = threading.Lock()
        self._meta_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix=f"es-meta-{node_id}")
        # full REST stack (node/cluster_rest.py): local IndicesService +
        # RestAPI + cluster dispatch; metadata replicates via the op log
        from .cluster_rest import ClusterHooks, ClusterRestService
        self.rest = ClusterRestService(self,
                                       os.path.join(data_path, "local"))
        if security is not None:
            # shared API-key store + REST enforcement at the front door
            self.rest.api.security = security
        self._hooks = ClusterHooks(self.rest)
        self.http = None
        self._http_pool: Optional[ThreadPoolExecutor] = None
        self._register_handlers()
        self.node_loop.call(self.transport.start())
        self.coordinator = self.node_loop.sync(lambda: Coordinator(
            node_id, self.queue, self.transport,
            ClusterState.initial(self.node_ids),
            on_commit=self._on_commit))
        self._watch_task = None
        self.node_loop.sync(self._schedule_node_watch)
        self.stopped = False

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def stop(self):
        self.stopped = True
        self.node_loop.sync(self.coordinator.stop)
        try:
            if self.http is not None:
                self.node_loop.call(self.http.stop())
        except Exception:   # noqa: BLE001
            pass
        try:
            self.node_loop.call(self.transport.stop())
        except Exception:   # noqa: BLE001
            pass
        # drain queued data work BEFORE closing engines: a pending
        # _apply_state/_recover_replica must not touch a closed engine or
        # mutate the shard maps mid-iteration
        self._data_pool.shutdown(wait=True, cancel_futures=True)
        self._replica_pool.shutdown(wait=True, cancel_futures=True)
        self._meta_pool.shutdown(wait=True, cancel_futures=True)
        self._read_pool.shutdown(wait=True, cancel_futures=True)
        self._recovery_pool.shutdown(wait=False, cancel_futures=True)
        if self._http_pool is not None:
            self._http_pool.shutdown(wait=False, cancel_futures=True)
        closed = set()
        for g in self.primaries.values():
            g.engine.close()
            closed.add(id(g.engine))
        for r in self.replicas.values():
            r.engine.close()
            closed.add(id(r.engine))
        # local-service engines not wrapped by any group (unassigned copies)
        for svc in self.rest.indices.indices.values():
            for e in svc.shards:
                if id(e) not in closed:
                    try:
                        e.close()
                    except Exception:   # noqa: BLE001
                        pass
        try:
            self.rest.api.close()
        except Exception:   # noqa: BLE001
            pass
        self.node_loop.stop()

    def start_http(self, port: int, host: str = "127.0.0.1") -> None:
        """Serve the full REST API over HTTP from this node (reference:
        every node binds 9200 — ``http/AbstractHttpServerTransport.java``).
        Requests execute on a small pool so blocking RPC fan-outs never
        stall the transport loop."""
        import asyncio
        from ..rest.http_server import HttpServer
        self._http_pool = ThreadPoolExecutor(
            max_workers=4, thread_name_prefix=f"es-rest-http-{self.node_id}")

        async def handler(method, path, query, body, headers=None):
            loop = asyncio.get_running_loop()
            # copy_context so context-bound request state (the
            # deprecation-warning accumulator, the trace context) follows
            # the request onto the worker thread
            import contextvars
            ctx = contextvars.copy_context()
            rh: dict = {}

            def run():
                status, ct, out = ctx.run(
                    self.rest.handle, method, path, query, body,
                    headers=headers, resp_headers=rh)
                return status, ct, out, rh

            return await loop.run_in_executor(self._http_pool, run)

        self.http = HttpServer(handler, host=host, port=port,
                               pass_headers=True)
        self.node_loop.call(self.http.start())

    def rpc_or_direct(self, dst: str, action: str, raw_fn, payload,
                      timeout: Optional[float] = None,
                      readonly: bool = False):
        """RPC — except self-calls that must not queue behind the data
        worker:

        - FROM the data worker, a loopback would deadlock behind itself
          (the handler queues on the same single-threaded pool) — invoke
          directly, we ARE the serialization point (same special case as
          ``ClusterRestService._meta_op``'s master loopback);
        - ``readonly`` self-calls (search/stats reads) go direct from ANY
          thread: the caller typically holds ``rest.lock`` while the data
          worker may be waiting for that same lock in ``_apply_state`` —
          queueing the read behind it deadlocks until the RPC timeout.
          Direct reads race engine refresh the same way the front's own
          ``_local`` searches of its primaried shards already do
          (segment lists swap atomically; segments are immutable)."""
        if dst == self.node_id and (
                readonly or threading.current_thread().name
                .startswith(f"es-data-{self.node_id}")):
            return raw_fn(self.node_id, payload)
        return self.rpc(dst, action, payload, timeout=timeout)

    def rpc(self, dst: str, action: str, payload,
            timeout: Optional[float] = None):
        """Synchronous RPC from any thread (test/client surface).
        ``timeout=None`` resolves to the settings-driven ``fast`` lane
        (``cluster.rpc.timeout.fast``)."""
        if timeout is None:
            timeout = TIMEOUTS.fast
        done = threading.Event()
        box: Dict[str, Any] = {}

        def ok(resp):
            box["v"] = resp
            done.set()

        def err(e):
            box["e"] = e
            done.set()

        self.transport.send(self.node_id, dst, action, payload,
                            on_response=ok, on_failure=err, timeout=timeout)
        if not done.wait(timeout + 1.0):
            raise TimeoutError(f"rpc [{action}] to [{dst}] timed out")
        if "e" in box:
            e = box["e"]
            raise e if isinstance(e, Exception) else RuntimeError(str(e))
        return box["v"]

    # ------------------------------------------------------------------
    # cluster admin (master-routed)
    # ------------------------------------------------------------------

    def create_index(self, name: str, *, num_shards: int = 1,
                     num_replicas: int = 0, mappings: Optional[dict] = None,
                     timeout: float = 5.0) -> None:
        import json as _json
        body = _json.dumps({
            "settings": {"number_of_shards": num_shards,
                         "number_of_replicas": num_replicas},
            "mappings": mappings or {}}).encode()
        status, _ct, out = self.rest._meta_op("PUT", f"/{name}", "", body)
        if status >= 400:
            raise ElasticsearchError(
                f"create index [{name}] failed: {out[:200]!r}")
        self._await_applied(lambda st: name in st.metadata["indices"],
                            timeout)

    def delete_index(self, name: str, timeout: float = 5.0) -> None:
        status, _ct, out = self.rest._meta_op("DELETE", f"/{name}", "", b"")
        if status >= 400:
            raise ElasticsearchError(
                f"delete index [{name}] failed: {out[:200]!r}")
        self._await_applied(lambda st: name not in st.metadata["indices"],
                            timeout)

    def _master_call(self, action: str, payload, timeout: float):
        deadline = time.monotonic() + timeout
        last: Optional[Exception] = None
        while time.monotonic() < deadline:
            leader = self.node_loop.sync(
                lambda: self.coordinator.known_leader)
            if leader is None:
                time.sleep(0.05)
                continue
            try:
                return self.rpc(leader, action, payload,
                                timeout=min(TIMEOUTS.fast, timeout))
            except Exception as e:      # noqa: BLE001 — retry via new leader
                last = e
                time.sleep(0.05)
        raise TimeoutError(f"[{action}] no master acked within {timeout}s: "
                           f"{last}")

    def _await_applied(self, pred: Callable[[ClusterState], bool],
                       timeout: float) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            st = self.applied_state
            if st is not None and pred(st):
                return
            time.sleep(0.02)
        raise TimeoutError("cluster state change was not applied in time")

    def _submit_and_wait(self, update, timeout: float = 5.0):
        done = threading.Event()
        box: Dict[str, Any] = {}

        def listener(st):
            box["v"] = st
            done.set()

        def submit():
            self.coordinator.submit_state_update(update, listener=listener)

        self.node_loop.sync(submit)
        if not done.wait(timeout):
            raise TimeoutError("cluster state update did not commit")
        if box.get("v") is None:
            raise ElasticsearchError("publication failed (no quorum)")
        return box["v"]

    # ------------------------------------------------------------------
    # state application (ClusterApplierService)
    # ------------------------------------------------------------------

    def _on_commit(self, state: ClusterState) -> None:
        # commits arrive on the transport loop; shard lifecycle (engine
        # creation, promotion, recovery kickoff) belongs on the data worker
        self.applied_state = state
        self._data_pool.submit(self._apply_state_safe, state)

    def _apply_state_safe(self, state: ClusterState) -> None:
        """State application must never silently die half-way: a later
        commit retries, and the failure is visible for debugging."""
        try:
            self._apply_state(state)
        except Exception as e:   # noqa: BLE001
            import traceback
            self.last_apply_error = (e, traceback.format_exc())

    def _apply_state(self, state: ClusterState) -> None:
        # 1. replay metadata ops into the local service (creates/deletes
        #    local IndexServices, mappings, aliases, templates, ...)
        self.rest.apply_ops(state)
        for svc in self.rest.indices.indices.values():
            if svc.cluster_hooks is None:
                svc.cluster_hooks = self._hooks
        indices = state.metadata["indices"]
        routing = state.data.get("routing", {})
        # 2. drop groups for deleted indices (engines are owned and closed
        #    by the local service's delete path)
        for (name, sid) in list(self.primaries):
            if name not in indices:
                self.primaries.pop((name, sid))
        for (name, sid) in list(self.replicas):
            if name not in indices:
                self.replicas.pop((name, sid))
        # 3. wire replication groups around the local service's engines
        for name, meta in indices.items():
            svc = self.rest.indices.indices.get(name)
            if svc is None:
                continue                 # op replay failed/lagging
            self.mappers[name] = svc.mapper
            table = routing.get(name, {})
            for sid_s, entry in table.items():
                sid = int(sid_s)
                if sid >= len(svc.shards):
                    continue
                key = (name, sid)
                engine = svc.shards[sid]
                term = int(meta.get("primary_term", 1))
                if entry["primary"] == self.node_id:
                    if key in self.primaries:
                        self._sync_replica_channels(key, entry, term)
                    elif key in self.replicas:
                        # promotion: replica -> primary. Refresh so docs
                        # the copy received through recovery/replication
                        # stay SEARCHABLE across the ownership change (the
                        # reference refreshes before marking started)
                        rep = self.replicas.pop(key)
                        group = promote_to_primary(
                            rep, max(term, rep.engine.primary_term + 1))
                        group.engine.refresh()
                        self.primaries[key] = group
                        self._sync_replica_channels(key, entry, term)
                        # promotion restores warm serving generations
                        # too: pull plane bundles from any live copy
                        # holder (off the data worker — recovery-class
                        # work must not stall doc ops)
                        if self.plane_handoff_enabled:
                            self._recovery_pool.submit(
                                self._request_plane_handoff, name)
                    else:
                        engine.primary_term = max(engine.primary_term, term)
                        group = PrimaryShardGroup(
                            f"{self.node_id}/{name}/{sid}", engine)
                        self.primaries[key] = group
                        self._sync_replica_channels(key, entry, term)
                elif self.node_id in entry["replicas"]:
                    if key in self.primaries:
                        # demoted (shouldn't happen without reassignment)
                        g = self.primaries.pop(key)
                        self.replicas[key] = ReplicaShard(
                            f"{self.node_id}/{name}/{sid}", g.engine)
                    elif key not in self.replicas:
                        engine.primary_term = max(engine.primary_term, term)
                        self.replicas[key] = ReplicaShard(
                            f"{self.node_id}/{name}/{sid}", engine)
                        # target-side warm-handoff trigger: this node
                        # just became a copy holder — pull the
                        # primary's packed planes (the donor's offer
                        # may have raced ahead of our metadata replay;
                        # the tracked pull dedupes)
                        if self.plane_handoff_enabled and \
                                entry.get("primary") and \
                                entry["primary"] != self.node_id:
                            self._recovery_pool.submit(
                                self._pull_plane_bundles_tracked,
                                name, entry["primary"])
                else:
                    # copy moved away from this node: drop the wrappers
                    # (the local service keeps its engine; reads route
                    # through the cluster hooks, so stale data is inert)
                    self.primaries.pop(key, None)
                    self.replicas.pop(key, None)

    def _sync_replica_channels(self, key, entry, term) -> None:
        """Attach RPC channels for this primary's replica set and trigger
        recovery for new copies (the primary-side of peer recovery)."""
        name, sid = key
        group = self.primaries[key]
        group.engine.primary_term = max(group.engine.primary_term, term)
        wanted = set(entry["replicas"])
        for aid in list(group.replicas):
            target = group.replicas[aid].target_node \
                if isinstance(group.replicas[aid], RpcReplicaChannel) \
                else None
            if target is not None and target not in wanted:
                group.replicas.pop(aid)
                group.tracker.remove_allocation(aid)
        have = {ch.target_node for ch in group.replicas.values()
                if isinstance(ch, RpcReplicaChannel)}
        # self-healing re-notify: a wired in-sync copy missing from the
        # published in_sync list (lost shard:started — master blip)
        # re-sends on the next state application
        published = set(entry.get("in_sync") or ())
        for ch in group.replicas.values():
            if isinstance(ch, RpcReplicaChannel) and \
                    ch.allocation_id in \
                    group.tracker.in_sync_allocation_ids() and \
                    ch.target_node not in published:
                self._notify_shard_started(name, sid, ch.target_node)
        for target in wanted - have:
            aid = f"{target}/{name}/{sid}"
            # every state application re-walks the wanted set; a
            # recovery already in flight (incl. its retry chain) must
            # not be resubmitted — duplicate tasks stack up on the data
            # worker and starve doc ops
            if aid in self._recovering:
                continue
            self._recovering.add(aid)
            ch = RpcReplicaChannel(self, target, name, sid, aid)
            # ops-based recovery runs on the data worker (it issues
            # synchronous RPCs; engine access stays serialized there)
            self._data_pool.submit(self._recover_replica, group, ch, aid)

    def _recover_replica(self, group: PrimaryShardGroup,
                         ch: RpcReplicaChannel, aid: str,
                         attempts: int = 20) -> None:
        try:
            remote_ckpt = ch._call("replica:checkpoint", {},
                                   timeout=TIMEOUTS.fast)["checkpoint"]
            group.tracker.init_tracking(aid)
            group.tracker.add_lease(f"peer_recovery/{aid}",
                                    max(remote_ckpt + 1, 0),
                                    source="peer recovery")
            ops = group.engine.translog.read_ops(from_seq_no=remote_ckpt + 1)
            ckpt = remote_ckpt
            import json as _json
            from ..common import telemetry as _tm
            for op in ops:
                ckpt = ch.translog_op(group.engine.primary_term, op)
                try:
                    _tm.record_recovery_bytes("segment", len(_json.dumps(
                        op.to_dict(), default=str)))
                except Exception:   # noqa: BLE001 — accounting only
                    pass
            group.replicas[aid] = ch
            group.tracker.mark_in_sync(aid, ckpt)
            group.tracker.remove_lease(f"peer_recovery/{aid}")
            # recovered docs must be searchable on the target immediately
            # (finalize-refresh, like the reference's recovery finalize)
            try:
                self.rpc(ch.target_node, "shard:refresh",
                         {"index": ch.index_name}, timeout=TIMEOUTS.fast)
            except Exception:   # noqa: BLE001
                pass
            # publish "shard started": until the master records the
            # copy in the routing entry's in_sync list, searches must
            # not read it (ShardRouting INITIALIZING→STARTED — a
            # recovering replica is invisible to ARS)
            self._notify_shard_started(ch.index_name, ch.shard_id,
                                       ch.target_node)
            # warm plane handoff: offer this node's packed serving
            # planes to the freshly recovered copy — it pulls the
            # bundles chunked and serves warm without re-packing
            # (reference ``indices/recovery/`` chunked file transfer,
            # but shipping plane tensors)
            if self.plane_handoff_enabled:
                try:
                    self.rpc(ch.target_node, "recovery:plane_offer",
                             {"index": ch.index_name,
                              "donor": self.node_id},
                             timeout=TIMEOUTS.fast)
                except Exception:   # noqa: BLE001 — the copy serves
                    pass            # cold; first search repacks
            self._recovering.discard(aid)
        except Exception:   # noqa: BLE001 — replica node not ready: retry
            group.tracker.remove_lease(f"peer_recovery/{aid}")
            if attempts > 0 and not self.stopped:
                self.queue.schedule(
                    0.25, lambda: self._data_pool.submit(
                        self._recover_replica, group, ch, aid,
                        attempts - 1))
            else:
                self._recovering.discard(aid)

    # ------------------------------------------------------------------
    # warm plane handoff (recovery:plane_* — chunked, resumable)
    # ------------------------------------------------------------------

    #: serialized-bundle chunk size per recovery frame (b64 chars; the
    #: transport's MAX_FRAME is 64 MiB)
    PLANE_CHUNK_BYTES = 4 << 20
    #: seconds a prepared export stays fetchable (the resume window)
    PLANE_EXPORT_TTL = 120.0

    def _h_recovery_plane_manifest(self, src, payload):
        """Donor side: serialize every live serving generation of the
        index into chunked, resumable transfers. Chunks are prepared
        ONCE and fetched by id — a retried chunk re-reads the prepared
        export instead of re-serializing the plane."""
        import uuid
        name = payload["index"]
        svc = self.rest.indices.indices.get(name)
        if svc is None or not self.plane_handoff_enabled:
            return {"bundles": []}
        now = time.monotonic()
        with self._plane_export_lock:
            for xid in [x for x, e in self._plane_exports.items()
                        if now - e["ts"] > self.PLANE_EXPORT_TTL]:
                self._plane_exports.pop(xid)
        entries = []
        # export_bundle_blobs ships pre-serialized payloads: live
        # generations serialize here, COLD-tier planes hand their pack
        # file's text over verbatim (the spilled plane IS the handoff
        # artifact — no re-serialization on the donor offer)
        for item in svc.plane_cache.export_bundle_blobs():
            blob = item["blob"]
            n = self.PLANE_CHUNK_BYTES
            chunks = [blob[i: i + n] for i in range(0, len(blob), n)]
            xid = uuid.uuid4().hex
            with self._plane_export_lock:
                self._plane_exports[xid] = {"chunks": chunks, "ts": now}
            entries.append({"xfer_id": xid, "kind": item["kind"],
                            "field": item["field"],
                            "n_chunks": len(chunks),
                            "nbytes": len(blob)})
        from ..common import flightrec as _fr
        _fr.record("handoff_manifest", node=self.node_id, index=name,
                   to=src, bundles=len(entries),
                   nbytes=sum(e["nbytes"] for e in entries))
        return {"bundles": entries}

    def _h_recovery_plane_chunk(self, src, payload):
        now = time.monotonic()
        with self._plane_export_lock:
            # sweep stale exports on every chunk fetch too: on a donor
            # that never receives another manifest request, the TTL
            # sweep there would never run and abandoned transfers
            # (puller died mid-pull) would pin serialized plane copies
            # on the heap forever
            for xid in [x for x, e in self._plane_exports.items()
                        if now - e["ts"] > self.PLANE_EXPORT_TTL]:
                self._plane_exports.pop(xid)
            e = self._plane_exports.get(payload["xfer_id"])
            if e is None:
                raise ElasticsearchError(
                    f"plane export [{payload['xfer_id']}] expired")
            e["ts"] = now
            return {"data": e["chunks"][int(payload["chunk"])]}

    def _h_recovery_plane_done(self, src, payload):
        """Puller-side completion ack: release the prepared export NOW
        instead of waiting for the TTL sweep — a completed handoff must
        not pin a serialized plane copy on the donor heap."""
        with self._plane_export_lock:
            self._plane_exports.pop(payload.get("xfer_id"), None)
        return {"ok": True}

    def _h_recovery_plane_offer(self, src, payload):
        """Target side: a donor finished recovering one of our copies
        and offers its warm planes — pull + import off this handler so
        the offer RPC acks immediately."""
        name, donor = payload["index"], payload.get("donor", src)
        if not self.plane_handoff_enabled:
            return {"accepted": False}
        self._recovery_pool.submit(self._pull_plane_bundles_tracked,
                                   name, donor)
        return {"accepted": True}

    def _pull_plane_bundles_tracked(self, name: str, donor: str
                                    ) -> Optional[int]:
        """Deduplicated pull: one in-flight transfer per (index, donor)
        — per-shard recovery offers and the replica-wiring trigger
        would otherwise race duplicate pulls of the same bundles.
        Returns bundles imported, or None when another pull for this
        (index, donor) was already in flight."""
        key = (name, donor)
        with self._plane_export_lock:
            if key in self._handoff_inflight:
                return None
            self._handoff_inflight.add(key)
        try:
            return self._pull_plane_bundles(name, donor)
        except Exception:   # noqa: BLE001 — cold serving still works
            return 0
        finally:
            with self._plane_export_lock:
                self._handoff_inflight.discard(key)

    def _pull_plane_bundles(self, name: str, donor: str,
                            import_deadline: float = 30.0) -> int:
        """Fetch + import every plane bundle the donor offers for
        ``name``. Chunk fetches retry with jittered backoff and RESUME:
        chunks already received are never re-shipped. The IMPORT
        retries against the local copies up to ``import_deadline``
        seconds: the offer lands as soon as the donor finalizes one
        shard's recovery, which can be before this node's metadata
        replay has even recreated the index service (a rejoining node
        replays the op log while recovery is already running). Returns
        bundles imported (0 → every bundle fell back to the repack
        path)."""
        from ..common import flightrec as _fr
        from ..common import telemetry as _tm
        from ..common import tracing as _tracing
        from ..common.datacodec import loads_b64
        from ..common.retry import retry_with_backoff
        t0 = time.perf_counter()
        # the whole pull runs inside its own recovery trace: journal
        # events carry its trace id, and es_plane_handoff_ms keeps it as
        # an exemplar — a slow handoff on a scrape links straight to
        # GET /_trace/{id} (the PR 5 exemplar pattern)
        with _tracing.span(f"recovery[plane_handoff:{name}]",
                           node=self.node_id, root=True,
                           attrs={"index": name, "donor": donor}) as sp:
            man = self.rpc(donor, "recovery:plane_manifest",
                           {"index": name}, timeout=TIMEOUTS.meta)
            imported = 0
            deadline = time.monotonic() + import_deadline
            for entry in man.get("bundles", ()):
                parts: List[Optional[str]] = [None] * int(entry["n_chunks"])
                for i in range(len(parts)):
                    parts[i] = retry_with_backoff(
                        lambda i=i: self.rpc(
                            donor, "recovery:plane_chunk",
                            {"xfer_id": entry["xfer_id"], "chunk": i},
                            timeout=TIMEOUTS.meta)["data"])
                    _tm.record_recovery_bytes("plane", len(parts[i]))
                    # journal chunk MILESTONES (first, every 64th,
                    # last), not every chunk: a multi-GB plane is
                    # thousands of 4 MiB chunks, and per-chunk events
                    # would evict the failure window this journal
                    # exists to preserve from the bounded ring
                    if i == 0 or i == len(parts) - 1 or i % 64 == 0:
                        _fr.record("handoff_chunk", node=self.node_id,
                                   index=name, donor=donor,
                                   kind=entry.get("kind"), chunk=i,
                                   n_chunks=len(parts),
                                   nbytes=len(parts[i]))
                blob = "".join(parts)
                # release the donor's prepared export immediately (fire
                # and forget; the TTL sweep backstops a lost ack)
                try:
                    self.rpc(donor, "recovery:plane_done",
                             {"xfer_id": entry["xfer_id"]},
                             timeout=TIMEOUTS.fast)
                except Exception:   # noqa: BLE001
                    pass
                bundle = loads_b64(blob)
                while not self.stopped:
                    if self._import_plane_bundle(name, bundle):
                        imported += 1
                        break
                    if time.monotonic() >= deadline:
                        break
                    time.sleep(0.25)
            handoff_ms = (time.perf_counter() - t0) * 1e3
            if imported:
                _tm.record_plane_handoff_ms(handoff_ms,
                                            exemplar=sp.trace_id)
            _fr.record("handoff_done", node=self.node_id, index=name,
                       donor=donor, imported=imported,
                       bundles=len(man.get("bundles", ())),
                       ms=round(handoff_ms, 3))
        return imported

    def _import_plane_bundle(self, name: str, bundle: dict) -> bool:
        svc = self.rest.indices.indices.get(name)
        if svc is None:
            return False
        segments = []
        for eng in svc.shards:
            segments.extend(eng.searchable_segments())
        return svc.plane_cache.import_bundle(bundle, segments, svc.mapper)

    def _request_plane_handoff(self, name: str) -> None:
        """Promotion path: pull warm plane bundles for ``name`` from any
        LIVE peer holding a copy — the deposed primary is usually dead
        (that is why we were promoted), and trying it anyway would burn
        a full manifest timeout before reaching a live donor."""
        st = self.applied_state
        table = (st.data.get("routing", {}) if st else {}).get(name) or {}
        peers = {e.get("primary") for e in table.values()} | {
            r for e in table.values() for r in e.get("replicas", ())}
        peers.discard(self.node_id)
        peers.discard(None)
        live = self.live_nodes()
        for donor in sorted(peers & live):
            got = self._pull_plane_bundles_tracked(name, donor)
            if got is None or got:
                # imported, or another pull for this donor is already
                # in flight — either way this trigger is done
                return

    # ------------------------------------------------------------------
    # node failure watch (master only) — FollowersChecker consequence
    # ------------------------------------------------------------------

    def _schedule_node_watch(self):
        self._watch_task = self.queue.schedule(0.5, self._node_watch_tick)

    def _node_watch_tick(self):
        """Master-side node watch: liveness + disk usage for EVERY peer
        (allocation needs both), shard failover for the dead, and a
        periodic allocation round. Runs ON the transport loop —
        everything here is callback-based (a blocking RPC would starve the
        loop that delivers its own response)."""
        if self.stopped:
            return
        if self.coordinator.mode != "LEADER":
            # a later re-election must not allocate from a stale snapshot:
            # liveness is only maintained while leading
            self._live_nodes = None
            self._schedule_node_watch()
            return
        self._plane_storms = getattr(self, "_plane_storms", {})
        self._plane_storms[self.node_id] = self._plane_storm_count()
        state = self.coordinator.applied
        routing = state.data.get("routing", {})
        referenced: set = set()
        for table in routing.values():
            for entry in table.values():
                referenced.add(entry["primary"])
                referenced.update(entry["replicas"])
        referenced.discard(self.node_id)
        targets = {n for n in self.node_ids if n != self.node_id}
        if not targets:
            self._schedule_node_watch()
            return
        alive = {self.node_id}
        self._disk_used[self.node_id] = _disk_used_frac(self.data_path)
        pending = {"n": len(targets)}

        def done():
            pending["n"] -= 1
            if pending["n"] == 0:
                prev_alive = getattr(self, "_prev_alive", None)
                self._prev_alive = set(alive)
                self._live_nodes = set(alive)
                # flap guard: a node must miss TWO consecutive rounds
                # before failover strips its shards — one lost ping during
                # election churn must not promote empty copies
                missed = targets - alive
                streaks = getattr(self, "_dead_streaks", {})
                self._dead_streaks = {
                    n: streaks.get(n, 0) + 1 for n in missed}
                dead = referenced & {n for n, c in
                                     self._dead_streaks.items() if c >= 2}
                if dead:
                    self._fail_over_dead_nodes(dead)
                # node (re)join: reset allocation retry counters — a
                # replica that exhausted MAX_RETRIES while NO eligible
                # node existed (the whole copy set was dead) must be
                # re-placed now that a holder is back, without a manual
                # reroute (the reference re-evaluates unassigned shards
                # on every node join)
                if prev_alive is not None and alive - prev_alive:
                    self._data_pool.submit(self._clear_failed_attempts)
                # allocation runs on the data worker (it issues blocking
                # in-sync RPCs for staged relocations); at most ONE round
                # queued — ticks fire every 0.5s but a round with probes
                # can take seconds, and backlog would starve doc ops
                if not getattr(self, "_alloc_pending", False):
                    self._alloc_pending = True
                    self._data_pool.submit(self._allocation_round)
                self._schedule_node_watch()

        def on_pong(r, n):
            alive.add(n)
            if isinstance(r, dict) and "disk_used_frac" in r:
                self._disk_used[n] = float(r["disk_used_frac"])
            if isinstance(r, dict) and "plane_storms" in r:
                # plane_serving health signature piggybacked the same
                # way disk usage is — the allocation round's
                # ServingStormDecider consumes it
                storms = getattr(self, "_plane_storms", None)
                if storms is None:
                    storms = self._plane_storms = {}
                storms[n] = int(r["plane_storms"])
            done()

        for n in sorted(targets):
            self.transport.send(
                self.node_id, n, "ping", {},
                on_response=lambda r, n=n: on_pong(r, n),
                on_failure=lambda e: done(), timeout=0.5)

    # ------------------------------------------------------------------
    # allocation round (master, data worker) — BalancedShardsAllocator +
    # deciders + staged relocations (cluster/allocation.py)
    # ------------------------------------------------------------------

    def _plane_storm_count(self) -> int:
        """Sync non-cold serving-plane rebuilds on THIS node (the
        plane_serving indicator's storm signature, from the same
        cache-owned counters) — piggybacked on ping responses so the
        master's allocation round can route copies away from storming
        nodes. Cheap: one counter-dict walk per cache."""
        total = 0
        try:
            for svc in list(self.rest.indices.indices.values()):
                rb = svc.plane_cache.rebuild_stats()
                total += max(rb.get("sync", 0) - rb.get("cold", 0), 0)
        except Exception:   # noqa: BLE001 — liveness never fails on
            pass            # a stats race
        return total

    def live_nodes(self) -> set:
        """Nodes believed alive. Before the first watch round completes
        (fresh election) this PINGS every peer synchronously — allocating
        shards to a down node points writes at nothing and silently drops
        data, so liveness must never be assumed."""
        if self._live_nodes is not None:
            return set(self._live_nodes) | {self.node_id}
        alive = {self.node_id}
        pending = threading.Event()
        left = {"n": 0}
        targets = [n for n in self.node_ids if n != self.node_id]
        if not targets:
            return alive
        left["n"] = len(targets)

        def done():
            left["n"] -= 1
            if left["n"] == 0:
                pending.set()

        for n in targets:
            self.transport.send(
                self.node_id, n, "ping", {},
                on_response=lambda r, n=n: (alive.add(n), done()),
                on_failure=lambda e: done(), timeout=0.5)
        pending.wait(1.5)
        self._live_nodes = set(alive)
        return alive

    def _allocation_round(self) -> None:
        self._alloc_pending = False
        if self.stopped or self.coordinator.mode != "LEADER":
            return
        st = self.applied_state
        if st is None:
            return
        from ..cluster.allocation import (AllocationContext,
                                          BalancedAllocator)
        live = sorted(self.live_nodes())
        routing = st.data.get("routing", {})
        # completion probes for staged relocations (blocking RPC is fine
        # here — we are on the data worker)
        completed: set = set()
        in_flight = 0
        for index, table in routing.items():
            for sid_s, entry in table.items():
                tgt = entry.get("relocating_to")
                if not tgt:
                    continue
                in_flight += 1
                owner = entry.get("primary")
                aid = f"{tgt}/{index}/{sid_s}"
                ok = False
                try:
                    if owner == self.node_id:
                        g = self.primaries.get((index, int(sid_s)))
                        ok = g is not None and \
                            aid in g.tracker.in_sync_allocation_ids()
                    elif owner is not None:
                        r = self.rpc(owner, "shard:insync",
                                     {"index": index, "shard": int(sid_s),
                                      "aid": aid}, timeout=TIMEOUTS.fast)
                        ok = bool(r.get("in_sync"))
                except Exception:   # noqa: BLE001 — probe later
                    ok = False
                if ok:
                    completed.add((index, sid_s))
        from ..cluster.allocation import MAX_RETRIES
        ctx = AllocationContext(
            live, routing, st.metadata["indices"],
            node_attrs=self.node_attrs, disk_used=dict(self._disk_used),
            moves_in_flight=in_flight - len(completed),
            plane_storms=dict(getattr(self, "_plane_storms", {})))
        allocator = BalancedAllocator()
        plan = [] if completed else allocator.plan_rebalance(ctx)
        # replica deficits only: red shards (no primary) wait for a copy
        # to return; retry-exhausted shards wait for a manual reroute
        needs_fill = any(
            ((e.get("primary") and
              len(e.get("replicas", ())) < min(
                  int((st.metadata["indices"].get(i) or {})
                      .get("num_replicas", 0)), len(live) - 1)) or
             (not e.get("primary") and e.get("fresh"))) and
            int(e.get("failed_attempts", 0)) < MAX_RETRIES
            for i, t in routing.items() for e in t.values())
        if not completed and not plan and not needs_fill:
            return

        def update(state: ClusterState) -> ClusterState:
            new = state.updated()
            r = new.data.setdefault("routing", {})
            meta = new.metadata["indices"]
            for index, sid_s in completed:
                entry = r.get(index, {}).get(sid_s)
                if entry is None or not entry.get("relocating_to"):
                    continue
                tgt = entry.pop("relocating_to")
                kind = entry.pop("relocating_kind", "replica")
                src = entry.pop("relocating_from", None)
                if kind == "primary":
                    if tgt in entry.get("replicas", ()):
                        entry["replicas"].remove(tgt)
                    entry["primary"] = tgt
                    m = meta.get(index)
                    if m is not None:
                        m["primary_term"] = \
                            int(m.get("primary_term", 1)) + 1
                else:
                    if src in entry.get("replicas", ()):
                        entry["replicas"].remove(src)
                # in_sync never outlives replica membership: a stale
                # entry would let a re-assigned, still-recovering copy
                # serve searches again
                if entry.get("in_sync"):
                    entry["in_sync"] = [
                        x for x in entry["in_sync"]
                        if x in entry.get("replicas", ())]
            actx = AllocationContext(
                live, r, meta, node_attrs=self.node_attrs,
                disk_used=dict(self._disk_used),
                plane_storms=dict(getattr(self, "_plane_storms", {})))
            allocator.allocate_unassigned(actx)
            for mv in plan:
                entry = r.get(mv["index"], {}).get(str(mv["sid"]))
                if entry is None or entry.get("relocating_to"):
                    continue
                if mv["to"] in entry.get("replicas", ()) or \
                        entry.get("primary") == mv["to"]:
                    continue
                entry.setdefault("replicas", []).append(mv["to"])
                entry["relocating_to"] = mv["to"]
                entry["relocating_kind"] = mv["kind"]
                entry["relocating_from"] = mv["from"]
            return new

        try:
            self._submit_and_wait(update, timeout=5.0)
        except (NotLeaderError, TimeoutError):
            pass
        except Exception:   # noqa: BLE001 — next tick retries
            pass

    def _clear_failed_attempts(self) -> None:
        """Master-side, on node join: clear per-shard allocation retry
        counters so the next allocation round re-places copies that ran
        out of retries while no eligible node existed."""
        if self.stopped or self.coordinator.mode != "LEADER":
            return
        st = self.applied_state
        if st is None or not any(
                entry.get("failed_attempts")
                for table in st.data.get("routing", {}).values()
                for entry in table.values()):
            return

        def update(state: ClusterState) -> ClusterState:
            new = state.updated()
            for table in new.data.get("routing", {}).values():
                for entry in table.values():
                    entry.pop("failed_attempts", None)
            return new

        try:
            self._submit_and_wait(update, timeout=5.0)
        except Exception:   # noqa: BLE001 — the next join/reroute retries
            pass

    def _fail_over_dead_nodes(self, dead: set) -> None:
        """Promote in-sync replicas of every shard primaried on a dead
        node and drop dead replicas from routing (RoutingNodes.failShard
        + primary-term bump for fencing)."""
        routing = self.coordinator.applied.data.get("routing", {})
        affected = any(
            entry["primary"] in dead or
            any(r in dead for r in entry["replicas"])
            for table in routing.values() for entry in table.values())
        if not affected:
            return
        promotions = sum(
            1 for table in routing.values() for entry in table.values()
            if entry["primary"] in dead and
            any(r not in dead for r in entry["replicas"]))
        if promotions:
            from ..common import flightrec as _fr
            from ..common import telemetry as _tm
            _tm.record_shard_failover(promotions)
            _fr.record("shard_failover", node=self.node_id,
                       dead=sorted(dead), promotions=promotions)

        def update(st: ClusterState) -> ClusterState:
            new = st.updated()
            for name, table in new.data.get("routing", {}).items():
                meta = new.metadata["indices"].get(name)
                for sid_s, entry in table.items():
                    if entry["primary"] in dead:
                        live = [r for r in entry["replicas"]
                                if r not in dead]
                        if live:
                            entry["primary"] = live[0]
                            entry["replicas"] = live[1:]
                            if meta is not None:
                                meta["primary_term"] = \
                                    int(meta.get("primary_term", 1)) + 1
                    else:
                        entry["replicas"] = [r for r in entry["replicas"]
                                             if r not in dead]
                    if entry.get("in_sync"):
                        entry["in_sync"] = [
                            r for r in entry["in_sync"]
                            if r not in dead
                            and r in entry.get("replicas", ())]
            return new

        try:
            self.coordinator.submit_state_update(update)
        except NotLeaderError:
            pass

    # ------------------------------------------------------------------
    # document ops (routed)
    # ------------------------------------------------------------------

    def _index_meta(self, index: str) -> Tuple[dict, dict]:
        st = self.applied_state
        if st is None or index not in st.metadata["indices"]:
            raise IndexNotFoundError(index)
        return (st.metadata["indices"][index],
                st.data.get("routing", {}).get(index, {}))

    def index_doc(self, index: str, doc_id: str, source: dict,
                  routing: Optional[str] = None) -> dict:
        meta, table = self._index_meta(index)
        sid = shard_for(doc_id, routing, meta["num_shards"])
        owner = table[str(sid)]["primary"]
        payload = {"index": index, "shard": sid, "id": doc_id,
                   "source": source, "routing": routing}
        # always through the transport (loopback for self): the data
        # worker serializes every engine touch
        return self.rpc(owner, "doc:index", payload, timeout=TIMEOUTS.data)

    def get_doc(self, index: str, doc_id: str,
                routing: Optional[str] = None) -> dict:
        meta, table = self._index_meta(index)
        sid = shard_for(doc_id, routing, meta["num_shards"])
        owner = table[str(sid)]["primary"]
        payload = {"index": index, "shard": sid, "id": doc_id}
        return self.rpc(owner, "doc:get", payload)

    def delete_doc(self, index: str, doc_id: str,
                   routing: Optional[str] = None) -> dict:
        meta, table = self._index_meta(index)
        sid = shard_for(doc_id, routing, meta["num_shards"])
        owner = table[str(sid)]["primary"]
        payload = {"index": index, "shard": sid, "id": doc_id}
        return self.rpc(owner, "doc:delete", payload, timeout=TIMEOUTS.data)

    def refresh(self, index: str) -> None:
        for n in self.node_ids:
            try:
                self.rpc(n, "shard:refresh", {"index": index},
                         timeout=TIMEOUTS.fast)
            except Exception:   # noqa: BLE001 — dead nodes skip refresh
                pass

    # ------------------------------------------------------------------
    # search (scatter-gather over nodes)
    # ------------------------------------------------------------------

    #: node-ordinal shift for cross-node cursor tiebreaks: clears the
    #: DistributedSearcher's shard<<48 | seg<<32 | doc encoding
    _NODE_ORD_SHIFT = 64

    #: adaptive-replica-selection EWMA smoothing (the reference's
    #: ResponseCollectorService uses alpha=0.3)
    _ARS_ALPHA = 0.3

    def _ars_rank(self, node_id: str) -> float:
        """Observed EWMA response seconds for ``node_id`` (0.0 when never
        measured — new nodes get tried)."""
        stats = getattr(self, "_ars_stats", None)
        if stats is None:
            return 0.0
        rec = stats.get(node_id)
        return rec["ewma_s"] if rec else 0.0

    def _ars_observe(self, node_id: str, seconds: float) -> None:
        stats = getattr(self, "_ars_stats", None)
        if stats is None:
            stats = self._ars_stats = {}
        rec = stats.setdefault(node_id,
                               {"ewma_s": 0.0, "searches": 0})
        rec["searches"] += 1
        rec["ewma_s"] = seconds if rec["searches"] == 1 else (
            self._ARS_ALPHA * seconds +
            (1 - self._ARS_ALPHA) * rec["ewma_s"])

    def adaptive_selection_stats(self) -> dict:
        """nodes-stats ``adaptive_selection`` section (reference:
        ``ResponseCollectorService.ComputedNodeStats``)."""
        return {n: {"outgoing_searches": rec["searches"],
                    "avg_response_time_ns": int(rec["ewma_s"] * 1e9),
                    "rank": f"{rec['ewma_s'] * 1e3:.1f}"}
                for n, rec in getattr(self, "_ars_stats", {}).items()}

    def _group_shards_by_copy(self, table: dict
                              ) -> Tuple[Dict[str, List[int]],
                                         Dict[int, List[str]]]:
        """(by_node, copies_of) for a fan-out over ``table`` — adaptive
        replica selection: each shard's copy set (primary + in-sync
        replicas) ranks by the EWMA response time this coordinator has
        observed per node (reference:
        ``cluster/routing/OperationRouting.java:42`` +
        ``node/ResponseCollectorService.java``); ties prefer the node
        with the fewest shards already assigned in this request
        (spreads load), then the primary. The FULL ranked copy list
        per shard is retained so :meth:`_fanout_with_failover` can
        re-route to the next copy when a node dies mid-request."""
        by_node: Dict[str, List[int]] = {}
        copies_of: Dict[int, List[str]] = {}
        live = self.live_nodes()
        for sid_s, entry in table.items():
            # only STARTED (recovery-complete) replicas serve reads: a
            # copy still replaying the translog would return stale or
            # empty results (the 230_composite index-sorted visibility
            # failure was exactly this)
            in_sync = set(entry.get("in_sync") or ())
            cands = [entry["primary"]] + [
                r for r in entry.get("replicas", ()) if r in in_sync]
            seen: set = set()
            cands = [c for c in cands
                     if not (c in seen or seen.add(c))]
            # a dead primary must not head the list while a live in-sync
            # copy exists — liveness outranks the EWMA (a freshly-dead
            # node's EWMA still looks fast)
            copies = [c for c in cands if c in live] or cands
            best = min(copies, key=lambda n: (
                self._ars_rank(n), len(by_node.get(n, ())),
                0 if n == entry["primary"] else 1))
            by_node.setdefault(best, []).append(int(sid_s))
            copies_of[int(sid_s)] = sorted(copies, key=lambda n: (
                self._ars_rank(n), 0 if n == entry["primary"] else 1, n))
        return by_node, copies_of

    def _fanout_with_failover(self, groups: List[tuple],
                              copies_of: Dict[int, List[str]],
                              send, on_exhausted) -> List[tuple]:
        """The ONE copy-failover wave loop every shard fan-out shares
        (search hits, DFS stats, agg partials). ``groups``: [(node,
        shards, ctx)]; ``send(node, shards, ctx)`` performs the RPC
        (raises on failure). A failed group re-routes each of its
        shards to the next-ranked in-sync copy — the fallback is asked
        ONLY for the shards it can serve — with one jittered pause per
        retry wave (not per group: the wave retries into SURVIVING
        nodes, and hammering them the same instant every coordinator
        does is the herd the jitter exists to break up).
        ``on_exhausted(sid, node, exc)`` fires per shard whose every
        copy failed. Returns [(ctx, result)] for the groups that
        answered."""
        from ..common import flightrec as _fr
        from ..common import telemetry as _tm
        results: List[tuple] = []
        queue = [(node, shards, ctx, frozenset())
                 for node, shards, ctx in groups]
        while queue:
            next_wave: List[tuple] = []
            for node_id, shards, ctx, tried in queue:
                try:
                    r = send(node_id, shards, ctx)
                except Exception as e:   # noqa: BLE001 — copy failover
                    _tm.record_search_retry("retried")
                    tried2 = tried | {node_id}
                    regroup: Dict[str, List[int]] = {}
                    for sid in shards:
                        nxt = next((c for c in copies_of.get(sid, ())
                                    if c not in tried2), None)
                        if nxt is None:
                            _tm.record_search_retry("exhausted")
                            _fr.record("copy_exhausted",
                                       node=self.node_id, failed=node_id,
                                       shard=sid,
                                       error=type(e).__name__)
                            on_exhausted(sid, node_id, e)
                        else:
                            regroup.setdefault(nxt, []).append(sid)
                    _fr.record("failover_wave", node=self.node_id,
                               failed=node_id, shards=list(shards),
                               wave=len(tried2),
                               rerouted={n: regroup[n]
                                         for n in sorted(regroup)},
                               error=type(e).__name__)
                    for n2 in sorted(regroup):
                        next_wave.append((n2, regroup[n2], ctx, tried2))
                    continue
                if tried:
                    _tm.record_search_retry("recovered")
                results.append((ctx, r))
            queue = next_wave
            if queue:
                time.sleep(next(iter(backoff_delays(1))))
        return results

    def search(self, index: str, body: Optional[dict] = None) -> dict:
        body = body or {}
        if "aggregations" in body and "aggs" not in body:
            body = dict(body)
            body["aggs"] = body.pop("aggregations")
        meta, table = self._index_meta(index)
        size = int(body.get("size", 10))
        from_ = int(body.get("from", 0))
        shard_body = dict(body, size=size + from_)
        shard_body["from"] = 0
        by_node, copies_of = self._group_shards_by_copy(table)
        node_order = sorted(by_node)
        # -- DFS stats round: cluster-wide term statistics. A node that
        # cannot answer in time degrades to partial stats (slightly-off
        # idf) instead of failing the whole search — the reference's DFS
        # phase likewise tolerates per-shard failures.
        # trace context crosses the wire in request payload headers: the
        # data-node handlers re-bind it so their spans join THIS request's
        # trace (coordinator → shard fan-out propagation)
        from ..common.tracing import wire_headers
        trace_hdrs = wire_headers()
        stats = {"total_docs": 0, "fields": {}, "terms": {}}

        def send_stats(node_id, shards, _ctx):
            return self.rpc_or_direct(
                node_id, "search:stats", self._h_search_stats, {
                    "index": index, "shards": shards,
                    "body": {"query": body.get("query")},
                    "_trace": trace_hdrs},
                timeout=TIMEOUTS.search, readonly=True)

        def stats_exhausted(sid, node_id, _e):
            # a shard whose every copy failed degrades to partial stats
            # (slightly-off idf), matching the reference's DFS-phase
            # tolerance — the hits phase reports the real failure
            import sys
            print(f"[{self.node_id}] search:stats for shard [{sid}] "
                  f"failed on every copy (last: [{node_id}]); degrading "
                  f"to partial stats", file=sys.stderr)

        for _ctx, s in self._fanout_with_failover(
                [(n, by_node[n], None) for n in node_order], copies_of,
                send_stats, stats_exhausted):
            stats["total_docs"] += s["total_docs"]
            for f, (sdl, dc) in s["fields"].items():
                cur = stats["fields"].setdefault(f, [0.0, 0])
                cur[0] += sdl
                cur[1] += dc
            for f, terms in s["terms"].items():
                tgt = stats["terms"].setdefault(f, {})
                for t, df in terms.items():
                    tgt[t] = tgt.get(t, 0) + df
        # -- rewrite an incoming cursor into each node's local space --------
        sort_spec = body.get("sort")
        clauses = normalize_sort(sort_spec) if sort_spec else None
        use_field_sort = bool(clauses) and clauses[0]["field"] != "_score"
        n_user = len(clauses) if clauses else 0
        search_after = body.get("search_after")
        shard_failures: List[dict] = []
        # groups carry (original node ordinal, node-local body): the
        # ordinal survives failover so cursor tiebreaks keep encoding
        # the node_order position the NEXT request's
        # ``_node_local_cursor`` translation decodes against — a
        # results-list position would shift whenever a group re-routed
        # mid-failure and corrupt cross-node pagination exactly in the
        # window failover exists for. A shard whose every copy failed
        # lands in the response's ES-shaped ``_shards.failures``
        # instead of 500ing the request (ShardSearchFailure semantics).
        groups = []
        for ni, node_id in enumerate(node_order):
            nb = shard_body
            if search_after is not None:
                nb = dict(shard_body)
                cursor = self._node_local_cursor(search_after, ni,
                                                 use_field_sort, n_user)
                if cursor is not None:
                    nb["search_after"] = cursor
                else:
                    nb.pop("search_after", None)
            groups.append((node_id, by_node[node_id], (ni, nb)))

        def send_shards(node_id, shards, ctx):
            _ni, nb = ctx
            payload = {"index": index, "shards": shards,
                       "body": nb, "global_stats": stats,
                       "want_agg_partials": bool(body.get("aggs")),
                       "_trace": trace_hdrs}
            t_rpc = time.monotonic()
            try:
                return self.rpc_or_direct(
                    node_id, "search:shards", self._h_search_shards,
                    payload, timeout=TIMEOUTS.search, readonly=True)
            finally:
                self._ars_observe(node_id, time.monotonic() - t_rpc)

        def shards_exhausted(sid, node_id, e):
            shard_failures.append({
                "shard": int(sid), "node": node_id,
                "reason": {"type": type(e).__name__, "reason": str(e)},
                "status": 503})

        tagged = self._fanout_with_failover(groups, copies_of,
                                            send_shards,
                                            shards_exhausted)
        ordinals = [ni for (ni, _nb), _r in tagged]
        results = [r for _ctx, r in tagged]
        # coordinator-side resource roll-up: every data node's shard-
        # phase ledger folds into THIS request's task, so a cluster
        # search reports one cpu/device/docs total across the fan-out
        from .task_manager import current_resources
        task_res = current_resources()
        if task_res is not None:
            for r in results:
                rd = r.get("_resources") if isinstance(r, dict) else None
                if rd:
                    task_res.merge_doc(rd)
        # merge (same comparator as the single-node coordinator), then
        # lift tiebreaks into the node-global cursor space — keyed by
        # each result's ORIGINAL group ordinal (failover-stable), never
        # its results-list position
        merged = []
        for ni, r in zip(ordinals, results):
            for h in r["hits"]:
                if use_field_sort:
                    key = (merge_sort_key(clauses, h["sort"] or []),
                           ni, h["sort"][-1] if h["sort"] else 0)
                else:
                    sd = (h["sort"][1] if h["sort"] and len(h["sort"]) > 1
                          else 0)
                    sc = h["score"] if h["score"] is not None \
                        else float("-inf")
                    key = (-sc, ni, sd)
                merged.append((key, ni, h))
        merged.sort(key=lambda t: t[0])
        collapse_field = (body.get("collapse") or {}).get("field")
        if collapse_field:
            from ..search.dist_query import collapse_first_by_key
            merged = collapse_first_by_key(
                merged, lambda t: (t[2].get("fields") or {}).get(
                    collapse_field, [None])[0])
        hits = []
        for _, ni, h in merged[from_: from_ + size]:
            if h.get("sort"):
                tail = h["sort"][-1]
                if isinstance(tail, int):
                    h["sort"] = h["sort"][:-1] + [
                        (ni << self._NODE_ORD_SHIFT) | tail]
            hits.append(h)
        total = sum(r["total"] for r in results)
        aggs_out = None
        if body.get("aggs"):
            # ONE shared reduce through the same entry point the single-
            # node coordinator uses (meta attachment, parent pipelines,
            # max-bucket checks — SearchPhaseController.java:211-219)
            from ..search.aggregations import (inject_mapper, parse_aggs,
                                               run_aggregations_multi)
            aggs = parse_aggs(body["aggs"])
            if index in self.mappers:
                inject_mapper(aggs, self.mappers[index])
            merged: Dict[str, list] = {}
            for r in results:
                for name, parts in _undata64(r["agg_partials"]).items():
                    merged.setdefault(name, []).extend(parts)
            aggs_out = run_aggregations_multi(aggs, [],
                                              extra_partials=merged)
        out = {"total": total, "hits": hits}
        all_failures = shard_failures + [
            f for r in results for f in (r.get("failures") or [])]
        if all_failures:
            def _has_partials(r):
                try:
                    return any(_undata64(r.get("agg_partials", ""))
                               .values())
                except Exception:   # noqa: BLE001
                    return False
            if not results or (
                    all(not r.get("hits") for r in results) and
                    not any(_has_partials(r) for r in results)):
                # every data shard cluster-wide failed (no surviving
                # copy answered anything): raise the cause —
                # SearchPhaseExecutionException carries its status
                f0 = all_failures[0]["reason"]
                err = ElasticsearchError(f0.get("reason", "shard failure"))
                err.error_type = f0.get("type", "exception")
                err.status = int(all_failures[0].get("status", 500))
                raise err
            out["failures"] = all_failures
        if aggs_out is not None:
            out["aggregations"] = aggs_out
        # suggest merges across nodes (options dedupe/re-rank; per-node
        # freq/df are node-local — documented approximation); profile
        # concatenates shard entries
        suggests = [r["suggest"] for r in results if r.get("suggest")]
        if suggests:
            from ..rest.api import _merge_suggest
            out["suggest"] = _merge_suggest(suggests)
        profiles = [r["profile"] for r in results if r.get("profile")]
        if profiles:
            shards_prof = [sh for p in profiles for sh in p["shards"]]
            if aggs_out is not None:
                # remote shards collected partials without reducing, so
                # their agg profile entries carry no debug payload —
                # rebuild them from the post-reduce aggregator state
                from ..search.shard_search import build_agg_profile
                prof_aggs = build_agg_profile(
                    aggs, aggs_out, self.mappers.get(index), [], 1)
                by_name = {e["description"]: e for e in prof_aggs}
                for sh in shards_prof:
                    for i, e in enumerate(sh.get("aggregations") or []):
                        fixed = by_name.get(e.get("description"))
                        if fixed is None:
                            continue
                        merged_e = dict(fixed)
                        merged_e["breakdown"] = e.get(
                            "breakdown", fixed["breakdown"])
                        # shard-local collect-time debug (e.g. ordinal
                        # stats) wins where non-zero; reduce-side debug
                        # fills what the shard couldn't know
                        dbg = dict(fixed.get("debug", {}))
                        for k, v in (e.get("debug") or {}).items():
                            if v:
                                dbg[k] = v
                        merged_e["debug"] = dbg
                        sh["aggregations"][i] = merged_e
            out["profile"] = {"shards": shards_prof}
        return out

    def _node_local_cursor(self, sa, node_ord: int, use_field_sort: bool,
                           n_user: int):
        """Cross-node cursor translation (same scheme as the REST layer's
        index-ordinal translation, one level up)."""
        shift = self._NODE_ORD_SHIFT
        if not use_field_sort:
            if len(sa) < 2:
                return list(sa)
            gsd = int(sa[1])
            a_ord = gsd >> shift
            local = gsd & ((1 << shift) - 1)
            if a_ord == node_ord:
                return [sa[0], local]
            if a_ord < node_ord:
                return [sa[0], -1]
            return [sa[0]]
        if len(sa) != n_user + 1:
            return list(sa)
        try:
            gsd = int(sa[-1])
        except (OverflowError, ValueError):
            return list(sa)
        if gsd < 0:
            return list(sa)
        a_ord = gsd >> shift
        local = gsd & ((1 << shift) - 1)
        prefix = list(sa[:-1])
        if a_ord == node_ord:
            return prefix + [local]
        if a_ord < node_ord:
            return prefix + [-1.0]
        return prefix + [float("inf")]

    # ------------------------------------------------------------------
    # transport handlers (data-node side)
    # ------------------------------------------------------------------

    def _register_handlers(self):
        t = self.transport
        nid = self.node_id

        def on_worker(handler, pool=None):
            # transport awaits the returned Future without blocking
            pool = pool or self._data_pool
            return lambda src, payload: pool.submit(handler, src, payload)

        def on_replica(handler):
            return on_worker(handler, self._replica_pool)

        def on_meta(handler):
            return on_worker(handler, self._meta_pool)

        def on_read(handler):
            return on_worker(handler, self._read_pool)

        t.register(nid, "ping", lambda s, p: {
            "ok": True, "disk_used_frac": _disk_used_frac(self.data_path),
            "plane_storms": self._plane_storm_count()})
        t.register(nid, "shard:insync", on_worker(self._h_shard_insync))
        t.register(nid, "shard:started", on_meta(self._h_shard_started))
        t.register(nid, "alloc:reroute", on_worker(self._h_alloc_reroute))
        t.register(nid, "meta:op", on_meta(self.rest.h_meta_op))
        t.register(nid, "meta:history",
                   on_meta(self.rest.h_meta_history))
        t.register(nid, "rest:exec", on_worker(self.rest.h_rest_exec))
        t.register(nid, "doc2:index", on_worker(self.rest.h_doc2_index))
        t.register(nid, "doc2:delete", on_worker(self.rest.h_doc2_delete))
        t.register(nid, "doc2:get", on_worker(self.rest.h_doc2_get))
        t.register(nid, "doc2:visible",
                   on_worker(self._hooks.h_doc2_visible))
        t.register(nid, "doc:index", on_worker(self._h_doc_index))
        t.register(nid, "doc:get", on_worker(self._h_doc_get))
        t.register(nid, "doc:delete", on_worker(self._h_doc_delete))
        t.register(nid, "shard:refresh", on_worker(self._h_refresh))
        # cheap read-only metadata RPCs get their own lane: a long
        # search/aggregation grinding on the data worker (left behind by
        # a client that already timed out) must not starve the term-
        # statistics round of the NEXT search into its 2x15s degrade
        # path — the same isolation the readonly self-RPC direct path
        # grants self-calls
        t.register(nid, "search:shards", on_read(self._h_search_shards))
        t.register(nid, "search:stats", on_read(self._h_search_stats))
        t.register(nid, "replica:index", on_replica(self._h_replica_index))
        t.register(nid, "replica:delete",
                   on_replica(self._h_replica_delete))
        t.register(nid, "replica:translog_op",
                   on_replica(self._h_replica_translog))
        t.register(nid, "replica:checkpoint",
                   on_replica(self._h_replica_checkpoint))
        t.register(nid, "replica:sync_gcp",
                   on_replica(self._h_replica_sync_gcp))
        t.register(nid, "snap:shard", on_worker(self._h_snap_shard))
        t.register(nid, "stats:shards", on_read(self.rest.h_stats_shards))
        t.register(nid, "search:canmatch", on_read(self._h_can_match))
        # warm plane handoff: manifest/chunk on the donor, offer/done
        # bookkeeping — all on the dedicated recovery lane (bundle
        # serialization and chunked transfer are seconds-long and must
        # never queue ahead of live search RPCs; the work itself reads
        # immutable segment snapshots, never engine write state)
        def on_recovery(handler):
            return on_worker(handler, self._recovery_pool)

        t.register(nid, "recovery:plane_manifest",
                   on_recovery(self._h_recovery_plane_manifest))
        t.register(nid, "recovery:plane_chunk",
                   on_recovery(self._h_recovery_plane_chunk))
        t.register(nid, "recovery:plane_offer",
                   on_recovery(self._h_recovery_plane_offer))
        t.register(nid, "recovery:plane_done",
                   on_recovery(self._h_recovery_plane_done))

    def _h_snap_shard(self, src, payload):
        """Upload this node's primary copy of one shard into the shared
        repo (the data-node half of master-coordinated snapshots —
        ``SnapshotShardsService``)."""
        name, sid = payload["index"], int(payload["shard"])
        holder = self.primaries.get((name, sid))
        if holder is not None:
            engine = holder.engine
        else:
            # fall back to the bare local engine ONLY when routing names
            # this node as the primary (group wiring can lag the routing
            # publish) — anything else would upload an empty copy
            st = self.applied_state
            entry = ((st.data.get("routing", {}) if st else {})
                     .get(name, {})).get(str(sid))
            svc = self.rest.indices.indices.get(name)
            if svc is None or sid >= len(svc.shards) or entry is None \
                    or entry.get("primary") != self.node_id:
                raise ElasticsearchError(
                    f"shard [{name}][{sid}] is not primaried on "
                    f"[{self.node_id}]")
            engine = svc.shards[sid]
        with self.rest.lock:
            manifest, nf, nb = self.rest.api.snapshots.upload_shard(
                payload["repo"], name, sid, engine)
        return {"manifest": manifest, "files": nf, "bytes": nb}

    def _primary(self, payload) -> PrimaryShardGroup:
        key = (payload["index"], int(payload["shard"]))
        g = self.primaries.get(key)
        if g is None:
            raise ElasticsearchError(
                f"shard [{key}] is not primaried on [{self.node_id}]")
        return g

    def _replica(self, payload) -> ReplicaShard:
        key = (payload["index"], int(payload["shard"]))
        r = self.replicas.get(key)
        if r is None:
            raise ElasticsearchError(
                f"shard [{key}] has no replica on [{self.node_id}]")
        return r

    def _h_doc_index(self, src, payload):
        g = self._primary(payload)
        resp = g.index(payload["id"], payload["source"],
                       routing=payload.get("routing"))
        return {"_id": payload["id"], "_version": resp.result.version,
                "_seq_no": resp.result.seq_no,
                "result": "created" if resp.result.created else "updated",
                "failed_copies": resp.failed}

    def _h_doc_get(self, src, payload):
        key = (payload["index"], int(payload["shard"]))
        holder = self.primaries.get(key) or self.replicas.get(key)
        if holder is None:
            raise ElasticsearchError(f"shard [{key}] not on this node")
        engine = holder.engine
        r = engine.get(payload["id"])
        return {"found": r.found, "_id": payload["id"],
                "_source": r.source if r.found else None,
                "_version": r.version if r.found else None}

    def _h_doc_delete(self, src, payload):
        g = self._primary(payload)
        resp = g.delete(payload["id"])
        return {"found": resp.result.found,
                "_version": resp.result.version}

    def _h_refresh(self, src, payload):
        name = payload["index"]
        shard = payload.get("shard")         # None → every shard
        svc = self.rest.indices.indices.get(name)
        if svc is not None:
            # group wiring is async: refresh the local service's engines
            # directly so just-written not-yet-wrapped copies are covered
            for sid, e in enumerate(svc.shards):
                if shard is None or sid == shard:
                    e.refresh()
        for (iname, sid), g in self.primaries.items():
            if iname == name and (shard is None or sid == shard):
                g.engine.refresh()
        for (iname, sid), r in self.replicas.items():
            if iname == name and (shard is None or sid == shard):
                r.engine.refresh()
        return {"ok": True}

    def _local_dist_searcher(self, name: str,
                             shards: List[int],
                             global_stats: Optional[dict] = None
                             ) -> DistributedSearcher:
        from ..search.dist_query import FixedStatsContext
        mapper = self.mappers[name]
        seg_lists = []
        for sid in shards:
            key = (name, sid)
            holder = self.primaries.get(key) or self.replicas.get(key)
            if holder is None:
                raise ElasticsearchError(f"shard [{key}] not on this node")
            seg_lists.append(holder.engine.searchable_segments())
        dist = DistributedSearcher(seg_lists, mapper)
        # per-index search settings travel with the replicated metadata,
        # not the engine: apply them to the remote shard searchers too
        svc = self.rest.indices.indices.get(name)
        if svc is not None:
            mao = svc.settings.get("index.highlight.max_analyzed_offset")
            if mao is not None:
                for shard in dist.shards:
                    shard.max_analyzed_offset = int(mao)
        if global_stats is not None:
            # cluster-wide DFS stats replace the node-local union stats —
            # scores must be comparable across nodes at the merge
            for shard in dist.shards:
                shard.ctx = FixedStatsContext(shard.segments, mapper,
                                              global_stats)
        return dist

    def _h_search_stats(self, src, payload):
        """DFS stats phase: this node's contribution to cluster-wide term
        statistics for the query's terms (``search/dfs/DfsPhase.java``).
        The span re-binds the coordinator's trace context from the
        payload's wire headers — cross-node propagation."""
        from ..common.tracing import span
        with span(f"shard_stats[{payload['index']}]", node=self.node_id,
                  headers=payload.get("_trace"),
                  attrs={"shards": list(payload["shards"])}):
            return self._h_search_stats_traced(src, payload)

    def _h_search_stats_traced(self, src, payload):
        from ..search.query_dsl import MatchAllQuery, parse_query
        name = payload["index"]
        dist = self._local_dist_searcher(name, payload["shards"])
        query_spec = (payload.get("body") or {}).get("query")
        query = parse_query(query_spec) if query_spec else MatchAllQuery()
        fields: Dict[str, list] = {}
        terms: Dict[str, Dict[str, int]] = {}
        total_docs = 0
        per_field_terms: Dict[str, set] = {}
        for shard in dist.shards:
            total_docs += sum(s.n_docs for s in shard.segments)
            query.collect_highlight_terms(shard.ctx, per_field_terms)
        for shard in dist.shards:
            for f, ts in per_field_terms.items():
                cur = fields.setdefault(f, [0.0, 0])
                for seg in shard.segments:
                    sdl, dc = seg.field_stats(f)
                    cur[0] += sdl
                    cur[1] += dc
                tgt = terms.setdefault(f, {})
                for t in ts:
                    tgt[t] = tgt.get(t, 0) + sum(
                        seg.term_df(f, t) for seg in shard.segments)
        return {"total_docs": total_docs, "fields": fields, "terms": terms}

    def _h_can_match(self, src, payload):
        """can_match verdict over THIS node's segments of the index: its
        local service engines hold data only for locally-primaried
        shards; empty engines contribute nothing (conservative)."""
        from ..search.dist_query import _shard_can_match
        svc = self.rest.indices.indices.get(payload["index"])
        if svc is None:
            return {"can_match": True}
        bounds = [tuple(b) for b in payload.get("bounds") or []]
        return {"can_match": _shard_can_match(svc.searcher(), bounds)}

    def _h_search_shards(self, src, payload):
        """Query phase over this node's copies of the listed shards. The
        span adopts the coordinator's trace (payload ``_trace`` wire
        headers), so a front-node request's ``GET /_trace/{id}`` tree
        spans the data nodes it fanned out to.

        Resource attribution: the shard phase runs under a FRESH ledger
        (shadowing any task bound on this thread — on the coordinator's
        own direct-call shard, the work must not double-charge its
        task), and the ledger rides the response as ``_resources`` for
        the coordinator's roll-up — a cluster search reports ONE total
        across the fan-out."""
        from ..common.tracing import span
        from .task_manager import (TaskResources, bind_resources,
                                   current_resources, unbind_resources)
        outer = current_resources()
        if outer is not None:
            # direct-call shard on the coordinator's own request thread:
            # fold the coordinator's CPU up to here, then skip the shard
            # window on the outer ledger (it arrives via _resources — a
            # stale outer mark would double-count it at cpu_release)
            outer.cpu_checkpoint()
        res = TaskResources()
        token = bind_resources(res)
        res.cpu_mark()
        try:
            with span(f"shard_search[{payload['index']}]",
                      node=self.node_id,
                      headers=payload.get("_trace"),
                      attrs={"shards": list(payload["shards"])}):
                out = self._h_search_shards_traced(src, payload)
        finally:
            res.cpu_release()
            unbind_resources(token)
            if outer is not None:
                outer.cpu_mark()
        if isinstance(out, dict):
            out["_resources"] = res.to_dict()
        return out

    def _h_search_shards_traced(self, src, payload):
        name = payload["index"]
        body = payload["body"]
        dist = self._local_dist_searcher(name, payload["shards"],
                                         payload.get("global_stats"))
        want_partials = payload.get("want_agg_partials")
        r = dist.search(dict(body), collect_agg_inputs=want_partials)
        hits = [{"id": h.doc_id, "score": h.score, "sort": h.sort_values,
                 "source": h.source, "fields": h.fields,
                 "highlight": h.highlight, "seq_no": h.seq_no,
                 "ignored": h.ignored,
                 "inner_hits": h.inner_hits} for h in r.hits]
        out = {"total": r.total, "hits": hits}
        if r.suggest is not None:
            out["suggest"] = r.suggest
        if r.profile is not None:
            out["profile"] = r.profile
        aggs_spec = body.get("aggs") or body.get("aggregations")
        if want_partials and aggs_spec:
            from ..search.aggregations import (AggregationContext,
                                               PipelineAggregator,
                                               _collect_fn, parse_aggs)
            from ..search.shard_search import _tree_needs_scores
            aggs = parse_aggs(aggs_spec)
            need_scores = _tree_needs_scores(aggs)
            partials: Dict[str, list] = {}
            failures: List[dict] = []
            failed_pos: List[int] = []
            for pos, (shard_searcher, agg_inputs) in enumerate(
                    r.agg_inputs_by_shard or []):
                seg_scores = {seg.seg_id: sc for seg, _, sc in agg_inputs
                              if sc is not None} if need_scores else {}
                # wire=True: aggregators (at ANY tree depth) whose local
                # partials embed live segment refs use their data-only
                # collect_wire form — the partials cross the transport
                ctx = AggregationContext(self.mappers[name],
                                         shard_ctx=shard_searcher.ctx,
                                         seg_scores=seg_scores,
                                         wire=True)
                got: Dict[str, list] = {}
                try:
                    for name_, agg in aggs.items():
                        if isinstance(agg, PipelineAggregator):
                            continue
                        got[name_] = [
                            _collect_fn(agg, ctx)(ctx, seg, mask)
                            for seg, mask, _ in agg_inputs]
                except ElasticsearchError as e:
                    # per-shard failure scope (ShardSearchFailure): this
                    # shard's hits drop below; the request survives
                    failed_pos.append(pos)
                    failures.append({
                        "shard": int(payload["shards"][pos]),
                        "node": self.node_id,
                        "reason": {"type": e.error_type,
                                   "reason": str(e)},
                        "status": e.status})
                    continue
                for name_, parts in got.items():
                    partials.setdefault(name_, []).extend(parts)
            if failed_pos:
                if not any(partials.values()):
                    # every data-bearing shard here failed (empty shards
                    # are vacuous): surface the cause — the coordinator
                    # decides whether OTHER nodes survived
                    out["all_failed"] = True
                surviving = [sid for i, sid in
                             enumerate(payload["shards"])
                             if i not in failed_pos]
                if surviving:
                    # recompute hits over the surviving shard subset
                    # (failure path only — correctness over cost)
                    body2 = {k: v for k, v in body.items()
                             if k not in ("aggs", "aggregations")}
                    r2 = self._local_dist_searcher(
                        name, surviving,
                        payload.get("global_stats")).search(body2)
                    out["total"] = r2.total
                    out["hits"] = [
                        {"id": h.doc_id, "score": h.score,
                         "sort": h.sort_values, "source": h.source,
                         "fields": h.fields, "highlight": h.highlight,
                         "seq_no": h.seq_no, "ignored": h.ignored,
                         "inner_hits": h.inner_hits} for h in r2.hits]
                else:
                    out["total"] = 0
                    out["hits"] = []
                out["failures"] = failures
            out["agg_partials"] = _data64(partials)
        return out

    def _h_replica_index(self, src, payload):
        r = self._replica(payload)
        return r.apply_index(payload["primary_term"], payload["seq_no"],
                             payload["version"], payload["id"],
                             payload["source"], payload.get("routing"),
                             payload["gcp"])

    def _h_replica_delete(self, src, payload):
        r = self._replica(payload)
        return r.apply_delete(payload["primary_term"], payload["seq_no"],
                              payload["version"], payload["id"],
                              payload["gcp"])

    def _h_replica_translog(self, src, payload):
        from ..index.translog import TranslogOp
        r = self._replica(payload)
        return r.apply_translog_op(payload["primary_term"],
                                   TranslogOp.from_dict(payload["op"]))

    def _h_replica_checkpoint(self, src, payload):
        r = self._replica(payload)
        return {"checkpoint": r.local_checkpoint}

    def _h_replica_sync_gcp(self, src, payload):
        r = self._replica(payload)
        r._update_gcp(payload["gcp"])
        return {"ok": True}

    def _h_alloc_reroute(self, src, payload):
        if payload.get("retry_failed"):
            def update(st):
                new = st.updated()
                for table in new.data.get("routing", {}).values():
                    for entry in table.values():
                        entry.pop("failed_attempts", None)
                return new
            self._submit_and_wait(update)
        self._allocation_round()
        return {"acknowledged": True}

    def _h_shard_insync(self, src, payload):
        g = self.primaries.get((payload["index"], int(payload["shard"])))
        return {"in_sync": g is not None and
                payload["aid"] in g.tracker.in_sync_allocation_ids()}

    def _notify_shard_started(self, index: str, shard: int,
                              node: str) -> None:
        """Primary-side: tell the master a replica copy finished
        recovery (``ShardStateAction.shardStarted``)."""
        st = self.applied_state
        master = st.master_node if st else None
        payload = {"index": index, "shard": int(shard), "node": node}

        def notify():
            try:
                if master == self.node_id:
                    self._h_shard_started(self.node_id, payload)
                elif master is not None:
                    self.rpc(master, "shard:started", payload,
                             timeout=TIMEOUTS.data)
            except Exception:   # noqa: BLE001 — reads stay on the
                pass            # primary until a retry re-notifies

        # off the data worker: the notify RPC must never delay doc ops
        self._read_pool.submit(notify)

    def _h_shard_started(self, src, payload):
        """Master-side: record the copy in the routing entry's in_sync
        list; searches route to in_sync replicas only."""
        index, sid = payload["index"], str(payload["shard"])
        node = payload["node"]

        def update(st):
            new = st.updated()
            entry = (new.data.get("routing", {}).get(index) or {}).get(
                sid)
            if entry is not None and node in entry.get("replicas", ()) \
                    and node not in (entry.get("in_sync") or ()):
                entry.setdefault("in_sync", []).append(node)
            return new

        # fire-and-forget: waiting for publication here would block the
        # calling lane (the data worker when primary == master) on a
        # publish that itself needs that lane to apply state
        try:
            self.coordinator.submit_state_update(update)
        except Exception:   # noqa: BLE001 — not leader anymore: the
            pass            # new master re-learns from re-notification
        return {"acknowledged": True}


def _disk_used_frac(path: str) -> float:
    """Used fraction of the filesystem holding ``path`` (the reference's
    FsInfo probe feeding DiskThresholdDecider)."""
    try:
        sv = os.statvfs(path)
        total = sv.f_blocks * sv.f_frsize
        free = sv.f_bavail * sv.f_frsize
        return 1.0 - (free / total) if total else 0.0
    except OSError:
        return 0.0
