"""Index lifecycle + per-index shard management on one node.

Reference parity targets: ``indices/IndicesService.java:176`` (create/
remove index services), ``index/IndexService.java`` (shard ownership),
``cluster/metadata/MetadataCreateIndexService.java`` (validation,
settings), ``action/bulk/TransportBulkAction.java:99`` (routing + per-shard
grouping). Single-node scope here; the distributed data plane in
``parallel/`` takes over shard placement across a device mesh.
"""

from __future__ import annotations

import os
import re
import shutil
import time
from typing import Any, Dict, List, Optional, Tuple

from ..common.errors import (ElasticsearchError, IllegalArgumentError,
                             IndexClosedError, IndexNotFoundError,
                             ResourceAlreadyExistsError)
from ..index.engine import Engine
from ..index.mapping import MapperService
from ..search.shard_search import ShardSearcher, ShardSearchResult
from ..utils.murmur3 import shard_for

_VALID_INDEX_RE = re.compile(r"^[^A-Z _\-+][^A-Z\\/*?\"<>| ,#]*$")


def validate_index_name(name: str) -> None:
    if not name or name in (".", ".."):
        raise IllegalArgumentError(f"invalid index name [{name}]")
    if name.startswith(("-", "_", "+")) or name != name.lower() or \
            any(c in name for c in '\\/*?"<>| ,#'):
        raise IllegalArgumentError(
            f"invalid index name [{name}], must be lowercase and may not "
            f"contain spaces or the characters \\/*?\"<>|,#")


class IndexService:
    """One index: settings, mapper, and its primary shards."""

    def __init__(self, name: str, path: str, settings: Optional[dict] = None,
                 mappings: Optional[dict] = None):
        self.name = name
        self.path = path
        settings = dict(settings or {})
        flat = _flatten_settings(settings)
        self.num_shards = int(flat.get("index.number_of_shards",
                                       flat.get("number_of_shards", 1)))
        self.num_replicas = int(flat.get("index.number_of_replicas",
                                         flat.get("number_of_replicas", 1)))
        if self.num_shards < 1 or self.num_shards > 1024:
            raise IllegalArgumentError(
                f"invalid number_of_shards [{self.num_shards}]")
        self.settings = flat
        self.creation_date = int(time.time() * 1000)
        self.uuid = f"{abs(hash((name, self.creation_date))):022x}"[:22]
        self.mapper = MapperService(mappings or {})
        self.shards: List[Engine] = []
        for i in range(self.num_shards):
            shard_path = os.path.join(path, str(i))
            os.makedirs(shard_path, exist_ok=True)
            self.shards.append(Engine(
                shard_path, self.mapper,
                translog_durability=flat.get("index.translog.durability",
                                             "request"),
                gc_deletes_seconds=_parse_time_seconds(
                    flat.get("index.gc_deletes", "60s"))))
        self.aliases: Dict[str, dict] = {}
        self.closed = False

    def _check_open(self) -> None:
        if self.closed:
            raise IndexClosedError(f"closed index [{self.name}]")

    # -- routing ------------------------------------------------------------

    def shard_id_for(self, doc_id: str, routing: Optional[str] = None) -> int:
        return shard_for(routing if routing is not None else doc_id,
                         self.num_shards)

    def shard_for_doc(self, doc_id: str, routing: Optional[str] = None) -> Engine:
        return self.shards[self.shard_id_for(doc_id, routing)]

    # -- document ops -------------------------------------------------------

    def index_doc(self, doc_id: str, source: dict, *,
                  routing: Optional[str] = None, op_type: str = "index",
                  if_seq_no=None, if_primary_term=None):
        self._check_open()
        return self.shard_for_doc(doc_id, routing).index(
            doc_id, source, routing=routing, op_type=op_type,
            if_seq_no=if_seq_no, if_primary_term=if_primary_term)

    def get_doc(self, doc_id: str, routing: Optional[str] = None):
        self._check_open()
        return self.shard_for_doc(doc_id, routing).get(doc_id)

    def delete_doc(self, doc_id: str, *, routing: Optional[str] = None,
                   if_seq_no=None, if_primary_term=None):
        self._check_open()
        return self.shard_for_doc(doc_id, routing).delete(
            doc_id, if_seq_no=if_seq_no, if_primary_term=if_primary_term)

    # -- search -------------------------------------------------------------

    def searcher(self) -> ShardSearcher:
        """Pooled searcher over every shard's searchable segments (used by
        single-shard paths and features that need one flat segment list,
        e.g. scroll snapshots). Term statistics are computed over the
        union — equivalent to the reference's DFS phase being always-on
        (``search/dfs/DfsPhase.java``)."""
        segments = []
        for shard in self.shards:
            segments.extend(shard.searchable_segments())
        return ShardSearcher(segments, self.mapper)

    def dist_searcher(self) -> "DistributedSearcher":
        """Scatter-gather searcher: one query phase per shard, one global
        reduce (``search/dist_query.py`` — the coordinating-node role)."""
        from ..search.dist_query import DistributedSearcher
        return DistributedSearcher(
            [shard.searchable_segments() for shard in self.shards],
            self.mapper)

    def search(self, body: Optional[dict] = None) -> ShardSearchResult:
        self._check_open()
        if self.num_shards > 1:
            return self.dist_searcher().search(body or {})
        return self.searcher().search(body or {})

    def count(self, body: Optional[dict] = None) -> int:
        self._check_open()
        if self.num_shards > 1:
            return self.dist_searcher().count(body or {})
        return self.searcher().count(body or {})

    # -- admin --------------------------------------------------------------

    def refresh(self) -> None:
        for s in self.shards:
            s.refresh()

    def flush(self) -> None:
        for s in self.shards:
            s.flush()

    def force_merge(self) -> None:
        for s in self.shards:
            s.force_merge()

    def put_mapping(self, mappings: dict) -> None:
        self.mapper.merge(mappings)

    def update_settings(self, settings: dict) -> None:
        flat = _flatten_settings(settings)
        static = {"index.number_of_shards", "number_of_shards"}
        for k in flat:
            if k in static:
                raise IllegalArgumentError(
                    f"final {self.name} setting [{k}], not updateable")
        self.settings.update(flat)
        if "index.number_of_replicas" in flat:
            self.num_replicas = int(flat["index.number_of_replicas"])

    def stats(self) -> dict:
        docs = sum(s.doc_count for s in self.shards)
        deleted = sum(s.deleted_count for s in self.shards)
        seg_count = sum(len(s.searchable_segments()) for s in self.shards)
        store = 0
        for s in self.shards:
            for root, _, files in os.walk(s.path):
                for f in files:
                    try:
                        store += os.path.getsize(os.path.join(root, f))
                    except OSError:
                        pass
        ops = {}
        for key in ("index_total", "delete_total", "refresh_total",
                    "flush_total", "merge_total", "get_total"):
            ops[key] = sum(s.stats.get(key, 0) for s in self.shards)
        tl_ops = sum(s.translog.total_operations() for s in self.shards)
        tl_size = sum(s.translog.size_in_bytes() for s in self.shards)
        return {"docs": {"count": docs, "deleted": deleted},
                "store": {"size_in_bytes": store},
                "translog": {"operations": tl_ops,
                             "size_in_bytes": tl_size,
                             "uncommitted_operations": tl_ops,
                             "uncommitted_size_in_bytes": tl_size,
                             "earliest_last_modified_age": 0},
                "segments": {"count": seg_count},
                "indexing": {"index_total": ops["index_total"],
                             "delete_total": ops["delete_total"]},
                "get": {"total": ops["get_total"]},
                "refresh": {"total": ops["refresh_total"]},
                "flush": {"total": ops["flush_total"]},
                "merges": {"total": ops["merge_total"]}}

    def close(self) -> None:
        for s in self.shards:
            s.close()


class IndicesService:
    """All indices on this node (reference: ``IndicesService.java:176``).
    Resolves index expressions (names, aliases, wildcards, _all)."""

    def __init__(self, data_path: str):
        self.data_path = data_path
        os.makedirs(data_path, exist_ok=True)
        self.indices: Dict[str, IndexService] = {}

    # -- lifecycle ----------------------------------------------------------

    def create_index(self, name: str, settings: Optional[dict] = None,
                     mappings: Optional[dict] = None,
                     aliases: Optional[dict] = None) -> IndexService:
        validate_index_name(name)
        if name in self.indices or name in self.all_aliases():
            raise ResourceAlreadyExistsError(f"index [{name}] already exists")
        svc = IndexService(name, os.path.join(self.data_path, name),
                           settings, mappings)
        for alias, spec in (aliases or {}).items():
            svc.aliases[alias] = spec or {}
        self.indices[name] = svc
        return svc

    def delete_index(self, expression: str) -> List[str]:
        names = self.resolve(expression, allow_aliases=False)
        for n in names:
            svc = self.indices.pop(n)
            svc.close()
            shutil.rmtree(svc.path, ignore_errors=True)
        return names

    def get(self, name: str) -> IndexService:
        svc = self.indices.get(name)
        if svc is None:
            resolved = self.resolve(name)
            if len(resolved) != 1:
                raise IllegalArgumentError(
                    f"alias [{name}] has more than one index associated")
            return self.indices[resolved[0]]
        return svc

    def exists(self, expression: str) -> bool:
        try:
            return bool(self.resolve(expression))
        except IndexNotFoundError:
            return False

    def all_aliases(self) -> Dict[str, List[str]]:
        out: Dict[str, List[str]] = {}
        for name, svc in self.indices.items():
            for a in svc.aliases:
                out.setdefault(a, []).append(name)
        return out

    def resolve(self, expression: Optional[str],
                allow_aliases: bool = True) -> List[str]:
        """Index expression → concrete index names (reference:
        ``IndexNameExpressionResolver``): comma lists, wildcards, _all,
        aliases."""
        if expression in (None, "", "_all", "*"):
            return sorted(self.indices)
        aliases = self.all_aliases() if allow_aliases else {}
        out: List[str] = []
        for part in str(expression).split(","):
            part = part.strip()
            if not part:
                continue
            if part in self.indices:
                out.append(part)
            elif part in aliases:
                out.extend(aliases[part])
            elif "*" in part or "?" in part:
                import fnmatch
                matched = [n for n in self.indices
                           if fnmatch.fnmatchcase(n, part)]
                if allow_aliases:
                    for a, names in aliases.items():
                        if fnmatch.fnmatchcase(a, part):
                            matched.extend(names)
                out.extend(sorted(set(matched)))
            else:
                raise IndexNotFoundError(f"no such index [{part}]")
        seen = set()
        uniq = []
        for n in out:
            if n not in seen:
                seen.add(n)
                uniq.append(n)
        return uniq

    def close(self) -> None:
        for svc in self.indices.values():
            svc.close()


def _flatten_settings(settings: dict, prefix: str = "") -> Dict[str, Any]:
    """{"index": {"number_of_shards": 2}} → {"index.number_of_shards": 2}."""
    out: Dict[str, Any] = {}
    for k, v in settings.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten_settings(v, key + "."))
        else:
            out[key] = v
    return out


def _parse_time_seconds(v) -> float:
    if isinstance(v, (int, float)):
        return float(v)
    s = str(v)
    m = re.fullmatch(r"(\d+(?:\.\d+)?)(ms|s|m|h|d)?", s)
    if not m:
        raise IllegalArgumentError(f"failed to parse time value [{v}]")
    mult = {"ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0,
            "d": 86400.0}.get(m.group(2) or "s", 1.0)
    return float(m.group(1)) * mult
