"""Index lifecycle + per-index shard management on one node.

Reference parity targets: ``indices/IndicesService.java:176`` (create/
remove index services), ``index/IndexService.java`` (shard ownership),
``cluster/metadata/MetadataCreateIndexService.java`` (validation,
settings), ``action/bulk/TransportBulkAction.java:99`` (routing + per-shard
grouping). Single-node scope here; the distributed data plane in
``parallel/`` takes over shard placement across a device mesh.
"""

from __future__ import annotations

import contextlib
import json
import os
import re
import shutil
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..common.errors import (ElasticsearchError, IllegalArgumentError,
                             IndexClosedError, IndexNotFoundError,
                             ResourceAlreadyExistsError)
from ..index.engine import Engine
from ..index.mapping import MapperService
from ..search.shard_search import ShardSearcher, ShardSearchResult
from ..utils.murmur3 import shard_for

_VALID_INDEX_RE = re.compile(r"^[^A-Z _\-+][^A-Z\\/*?\"<>| ,#]*$")

#: thread-local marker: the current thread is performing an internal
#: resize/recovery copy and may write through application write blocks
_INTERNAL_COPY = threading.local()


@contextlib.contextmanager
def internal_copy_writes():
    """Scope an internal (resize/recovery) copy on the current thread so
    ``IndexService._check_write_block`` lets its writes through."""
    prev = getattr(_INTERNAL_COPY, "active", False)
    _INTERNAL_COPY.active = True
    try:
        yield
    finally:
        _INTERNAL_COPY.active = prev


def validate_index_name(name: str) -> None:
    from ..common.errors import InvalidIndexNameError
    if not name or name in (".", ".."):
        raise InvalidIndexNameError(f"Invalid index name [{name}]")
    if name.startswith(("-", "_", "+")) or name != name.lower() or \
            any(c in name for c in '\\/*?"<>| ,#'):
        raise InvalidIndexNameError(
            f"Invalid index name [{name}], must be lowercase and may not "
            f"contain spaces or the characters \\/*?\"<>|,#")


class IndexService:
    """One index: settings, mapper, and its primary shards."""

    def __init__(self, name: str, path: str, settings: Optional[dict] = None,
                 mappings: Optional[dict] = None):
        self.name = name
        self.path = path
        settings = dict(settings or {})
        flat = _flatten_settings(settings)
        self.num_shards = int(flat.get("index.number_of_shards",
                                       flat.get("number_of_shards", 1)))
        self.num_replicas = int(flat.get("index.number_of_replicas",
                                         flat.get("number_of_replicas", 1)))
        if self.num_shards < 1 or self.num_shards > 1024:
            raise IllegalArgumentError(
                f"invalid number_of_shards [{self.num_shards}]")
        _reject_retired_settings(flat)
        # settings store under their canonical "index."-prefixed keys so
        # later lookups (preserve_existing, GET _settings) are uniform
        self.settings = {
            (k if k.startswith("index.") else f"index.{k}"): v
            for k, v in flat.items()}
        self.creation_date = int(time.time() * 1000)
        self.uuid = f"{abs(hash((name, self.creation_date))):022x}"[:22]
        self.mapper = MapperService(mappings or {})
        self.mapper.index_name = name       # hit rendering (_index)
        try:
            self.mapper.nested_limit = int(self.settings.get(
                "index.mapping.nested_objects.limit", 10000))
        except (TypeError, ValueError):
            pass
        # index sorting (reference: IndexSortConfig — segments hold docs
        # ordered by these fields; forbidden with nested docs)
        sort_fields = flat.get("index.sort.field")
        index_sort = None
        if sort_fields:
            if not isinstance(sort_fields, list):
                sort_fields = [sort_fields]
            sort_orders = flat.get("index.sort.order") or []
            if not isinstance(sort_orders, list):
                sort_orders = [sort_orders]
            index_sort = [
                (f, (sort_orders[i] if i < len(sort_orders) else "asc"))
                for i, f in enumerate(sort_fields)]
        self.shards: List[Engine] = []
        for i in range(self.num_shards):
            shard_path = os.path.join(path, str(i))
            os.makedirs(shard_path, exist_ok=True)
            self.shards.append(Engine(
                shard_path, self.mapper,
                translog_durability=flat.get("index.translog.durability",
                                             "request"),
                gc_deletes_seconds=_parse_time_seconds(
                    flat.get("index.gc_deletes", "60s")),
                index_sort=index_sort))
        self.aliases: Dict[str, dict] = {}
        self.closed = False
        # search-phase counters (+ per-group when a search carries a
        # ``stats`` group list; reference: SearchStats.groupStats)
        self.search_stats: Dict[str, object] = {
            "query_total": 0, "fetch_total": 0, "scroll_total": 0,
            "suggest_total": 0, "groups": {}}
        # shard request cache (reference: IndicesRequestCache.java):
        # size==0 results keyed on (segment signature, body); the
        # signature bakes in liveness so refresh/merge/delete invalidate
        from collections import OrderedDict
        self.request_cache: "OrderedDict" = OrderedDict()
        self.request_cache_stats = {"hit_count": 0, "miss_count": 0}
        # plane-served slice of the request cache (identical plane-eligible
        # bodies served before the micro-batcher) — counted separately so
        # the serving bench can attribute hits to this path. The counters
        # are telemetry-registry citizens: instance-owned Counter objects
        # (fresh per index — exact per-index counts) exposed through the
        # process registry via a weakref collector, like every other
        # node-scoped producer; :attr:`plane_cache_stats` is the
        # dict-shaped read view the stats/bench surfaces keep using.
        from ..common import telemetry as _tm
        self._plane_cache_counters = {"hit": _tm.Counter(),
                                      "miss": _tm.Counter()}
        _tm.DEFAULT.register_object_collector(
            f"plane_cache_requests_{self.uuid}", self,
            IndexService._plane_cache_requests_doc)
        # the plane path puts the concurrent serving hot path through this
        # cache: get's move_to_end racing put's eviction would KeyError
        self._cache_lock = threading.Lock()
        #: search/indexing slow-log ring (reference: SearchSlowLog.java /
        #: IndexingSlowLog.java write per-index log files; entries also
        #: persist to <index>/_index_*_slowlog.log)
        self.slow_log: List[dict] = []
        # serving planes for the tiered TPU kernel (search/plane_route.py);
        # lazily built per text field, invalidated by segment-list changes
        from ..search.plane_route import ServingPlaneCache
        self.plane_cache = ServingPlaneCache()
        # serving-plane refresh hook: every engine refresh/merge that
        # changed the searchable segment list reconciles the plane
        # generations immediately (delta pack / background repack start
        # on the indexing thread), instead of the first search paying a
        # signature miss
        for sh in self.shards:
            sh.refresh_listeners.append(self._on_shard_refresh)
        # cluster seam (node/cluster_rest.py): when set, per-shard doc ops
        # and whole-index search route through the cluster instead of the
        # local engines (which hold data only for locally-assigned shards).
        # None on the single-node path — zero behavior change.
        self.cluster_hooks = None

    def _on_shard_refresh(self) -> None:
        """Engine refresh listener → plane-generation reconcile. Text
        generations serve the POOLED list; kNN generations may be keyed
        per index shard (the distributed searcher probes one per shard),
        so every candidate view is offered and each generation
        reconciles against its best match."""
        try:
            shard_lists = [sh.searchable_segments() for sh in self.shards]
            segments = [seg for lst in shard_lists for seg in lst]
            knn_lists = list(shard_lists)
            if len(shard_lists) > 1:
                knn_lists.append(segments)     # pooled RRF probes
            self.plane_cache.notify_refresh(segments, self.mapper,
                                            knn_lists=knn_lists)
        except Exception:   # noqa: BLE001 — reconcile is best-effort;
            pass            # the query path re-reconciles on its own

    def _plane_cache_requests_doc(self) -> dict:
        return {"es_plane_cache_requests_total": {
            "type": "counter",
            "help": "plane-path request cache lookups by result",
            "samples": [({"index": self.name, "result": r}, c.value)
                        for r, c in self._plane_cache_counters.items()]}}

    @property
    def plane_cache_stats(self) -> Dict[str, int]:
        """Dict view over the plane-path cache counters (kept for the
        stats document / bench surfaces that predate the registry)."""
        return {"hit_count": int(self._plane_cache_counters["hit"].value),
                "miss_count": int(self._plane_cache_counters["miss"].value)}

    def record_search(self, groups: Optional[List[str]] = None) -> None:
        self.search_stats["query_total"] += 1
        self.search_stats["fetch_total"] += 1
        for g in groups or []:
            gs = self.search_stats["groups"].setdefault(
                str(g), {"query_total": 0, "fetch_total": 0})
            gs["query_total"] += 1
            gs["fetch_total"] += 1

    def _check_open(self) -> None:
        if self.closed:
            raise IndexClosedError(f"closed index [{self.name}]")

    def _check_write_block(self) -> None:
        """Write-level index blocks (reference: ``IndexMetadata``
        INDEX_WRITE_BLOCK / INDEX_READ_ONLY_BLOCK; set via the add-block
        API or ``index.blocks.*`` settings)."""
        from ..common.errors import ClusterBlockError
        if getattr(_INTERNAL_COPY, "active", False):
            # internal resize/recovery copy on THIS thread — the reference
            # moves segment files below the write API
            # (TransportResizeAction.java), so application write blocks
            # must not stop it; concurrent client writes on other threads
            # still hit the block
            return
        s = self.settings
        for key, desc in (("index.blocks.write", "index write (api)"),
                          ("index.blocks.read_only", "index read-only"),
                          ("index.blocks.read_only_allow_delete",
                           "index read-only / allow delete (api)")):
            if str(s.get(key, "")).lower() == "true":
                raise ClusterBlockError(
                    f"index [{self.name}] blocked by: [FORBIDDEN/8/"
                    f"{desc}];")

    # -- routing ------------------------------------------------------------

    def shard_id_for(self, doc_id: str, routing: Optional[str] = None) -> int:
        return shard_for(routing if routing is not None else doc_id,
                         self.num_shards)

    def shard_for_doc(self, doc_id: str, routing: Optional[str] = None) -> Engine:
        return self.shards[self.shard_id_for(doc_id, routing)]

    # -- document ops -------------------------------------------------------

    def index_doc(self, doc_id: str, source: dict, *,
                  routing: Optional[str] = None, op_type: str = "index",
                  if_seq_no=None, if_primary_term=None):
        self._check_open()
        self._check_write_block()
        t0 = time.perf_counter()
        try:
            return self._index_doc_inner(
                doc_id, source, routing=routing, op_type=op_type,
                if_seq_no=if_seq_no, if_primary_term=if_primary_term)
        finally:
            self._slowlog_record("index", time.perf_counter() - t0,
                                 f"[{doc_id}] " + str(source)[:500])

    def _index_doc_inner(self, doc_id, source, *, routing=None,
                         op_type="index", if_seq_no=None,
                         if_primary_term=None):
        if self.cluster_hooks is not None:
            w = self.cluster_hooks.writer(self.name, self.shard_id_for(
                doc_id, routing))
            if w is not None:
                return w.index(doc_id, source, routing=routing,
                               op_type=op_type, if_seq_no=if_seq_no,
                               if_primary_term=if_primary_term)
        return self.shard_for_doc(doc_id, routing).index(
            doc_id, source, routing=routing, op_type=op_type,
            if_seq_no=if_seq_no, if_primary_term=if_primary_term)

    def get_doc(self, doc_id: str, routing: Optional[str] = None):
        self._check_open()
        if self.cluster_hooks is not None:
            w = self.cluster_hooks.writer(self.name, self.shard_id_for(
                doc_id, routing), for_read=True)
            if w is not None:
                return w.get(doc_id)
        return self.shard_for_doc(doc_id, routing).get(doc_id)

    def delete_doc(self, doc_id: str, *, routing: Optional[str] = None,
                   if_seq_no=None, if_primary_term=None):
        self._check_open()
        self._check_write_block()
        if self.cluster_hooks is not None:
            w = self.cluster_hooks.writer(self.name, self.shard_id_for(
                doc_id, routing))
            if w is not None:
                return w.delete(doc_id, if_seq_no=if_seq_no,
                                if_primary_term=if_primary_term)
        return self.shard_for_doc(doc_id, routing).delete(
            doc_id, if_seq_no=if_seq_no, if_primary_term=if_primary_term)

    # -- search -------------------------------------------------------------

    def searcher(self) -> ShardSearcher:
        """Pooled searcher over every shard's searchable segments (used by
        single-shard paths and features that need one flat segment list,
        e.g. scroll snapshots). Term statistics are computed over the
        union — equivalent to the reference's DFS phase being always-on
        (``search/dfs/DfsPhase.java``)."""
        segments = []
        for shard in self.shards:
            segments.extend(shard.searchable_segments())
        sr = ShardSearcher(
            segments, self.mapper,
            plane_provider=lambda segs, field:
                self.plane_cache.plane_for(segs, self.mapper, field),
            knn_plane_provider=lambda segs, field:
                self.plane_cache.knn_plane_for(segs, self.mapper, field),
            fused_provider=lambda segs, tf, kf:
                self.plane_cache.fused_runner_for(segs, self.mapper,
                                                  tf, kf))
        mao = self.settings.get("index.highlight.max_analyzed_offset")
        if mao is not None:
            sr.max_analyzed_offset = int(mao)
        return sr

    def dist_searcher(self) -> "DistributedSearcher":
        """Scatter-gather searcher: one query phase per shard, one global
        reduce (``search/dist_query.py`` — the coordinating-node role)."""
        from ..search.dist_query import DistributedSearcher
        return DistributedSearcher(
            [shard.searchable_segments() for shard in self.shards],
            self.mapper,
            plane_provider=lambda segs, field:
                self.plane_cache.plane_for(segs, self.mapper, field),
            knn_plane_provider=lambda segs, field:
                self.plane_cache.knn_plane_for(segs, self.mapper, field),
            fused_provider=lambda segs, tf, kf:
                self.plane_cache.fused_runner_for(segs, self.mapper,
                                                  tf, kf))

    #: request-cache entry cap per index (reference sizes by bytes —
    #: indices.requests.cache.size 1%; entries are simpler and safe here)
    REQUEST_CACHE_MAX = 256

    def _request_cache_blob(self, body: dict,
                            explicit: Optional[bool]) -> Optional[str]:
        """The canonical body blob when this request is cacheable, else
        None (reference: ``IndicesRequestCache.java`` — size==0 requests
        by default, opt-in/out via ?request_cache, never
        non-deterministic bodies). No invalidation component here —
        callers add their own (segment signature locally, write
        generation on the cluster front)."""
        if explicit is False:
            return None
        if str(self.settings.get("index.requests.cache.enable", "true")
               ).lower() == "false":
            return None
        if int(body.get("size", 10)) != 0:
            # only size==0 shapes are safe to cache: the coordinator
            # mutates hit objects in place (sort-cursor lifting, boosts),
            # so a cached hit would be re-mutated on every cache hit —
            # the reference likewise only caches size==0 even under
            # ?request_cache=true
            return None
        try:
            blob = json.dumps(body, sort_keys=True)
        except (TypeError, ValueError):
            return None
        if "now" in blob or "random_score" in blob or \
                body.get("profile"):
            return None
        return blob

    def _request_cache_key(self, body: dict,
                           explicit: Optional[bool]) -> Optional[tuple]:
        """Local cache key: the segment-list+liveness signature IS the
        invalidation, like the reference cache's reader-key."""
        blob = self._request_cache_blob(body, explicit)
        if blob is None:
            return None
        sig = tuple((seg.seg_id, seg.n_docs, int(seg.live.sum()))
                    for sh in self.shards
                    for seg in sh.searchable_segments())
        return (sig, blob)

    def _plane_cache_key(self, body: dict,
                         explicit: Optional[bool]) -> Optional[tuple]:
        """Request-cache key for PLANE-ELIGIBLE bodies (size>0): a pure
        bag-of-terms query with no feature sections is a deterministic
        read of the segment state, so identical bodies can be served from
        the cache before they ever reach the micro-batcher. The usual
        size==0-only rule exists because the coordinator mutates hit
        objects in place (sort-cursor lifting, boosts) — the plane path
        instead caches a pristine copy and hands out per-hit copies
        (:func:`_copy_shard_result`), keeping cached hits immutable."""
        if explicit is False:
            return None
        if str(self.settings.get("index.requests.cache.enable", "true")
               ).lower() == "false":
            return None
        if not isinstance(body, dict) or not body.get("query"):
            return None
        # cursor/threshold kwargs keep per-request semantics out of the
        # cache (mirrors the plane route's own kwargs checks); scripted
        # fetch sections may be nondeterministic. No "now"-substring
        # guard like the size==0 cache: bag-of-terms queries cannot carry
        # date math, and a substring check would silently disable caching
        # for any body containing those letters ("snow", "know", ...).
        if body.get("search_after") is not None or \
                body.get("min_score") is not None or \
                body.get("script_fields") or body.get("runtime_mappings") \
                or body.get("profile"):
            # profiled bodies ride the plane but are never cached: a
            # cached profile would replay stale stage timings
            return None
        from ..search.plane_route import body_eligible, extract_bag_of_terms
        if not body_eligible(body):
            return None
        if extract_bag_of_terms(body["query"], self.mapper) is None:
            return None
        try:
            blob = json.dumps(body, sort_keys=True)
        except (TypeError, ValueError):
            return None
        sig = tuple((seg.seg_id, seg.n_docs, int(seg.live.sum()))
                    for sh in self.shards
                    for seg in sh.searchable_segments())
        return (sig, "plane", blob)

    def cache_get(self, key):
        with self._cache_lock:
            hit = self.request_cache.get(key)
            if hit is not None:
                self.request_cache.move_to_end(key)
                self.request_cache_stats["hit_count"] += 1
            return hit

    def cache_put(self, key, result) -> None:
        with self._cache_lock:
            self.request_cache_stats["miss_count"] += 1
            self.request_cache[key] = result
            while len(self.request_cache) > self.REQUEST_CACHE_MAX:
                self.request_cache.popitem(last=False)

    #: slow-log ring size per index (entries also append to the on-disk
    #: log file, the reference's actual surface)
    SLOWLOG_MAX = 512

    def _slowlog_threshold(self, kind: str, level: str) -> Optional[float]:
        """Threshold seconds for ``index.(search|indexing).slowlog.
        threshold...`` settings, None = disabled (reference:
        ``index/SearchSlowLog.java:43`` / ``IndexingSlowLog.java:46``)."""
        key = (f"index.search.slowlog.threshold.query.{level}"
               if kind == "query" else
               f"index.indexing.slowlog.threshold.index.{level}")
        raw = self.settings.get(key)
        if raw in (None, "", "-1", -1):
            return None
        try:
            return _parse_time_seconds(raw)
        except Exception:   # noqa: BLE001 — malformed threshold: off
            return None

    def _slowlog_record(self, kind: str, took_s: float,
                        detail: str, stages: Optional[dict] = None,
                        planner: Optional[dict] = None) -> None:
        worst = None
        for level in ("warn", "info", "debug", "trace"):
            thr = self._slowlog_threshold(kind, level)
            if thr is not None and took_s >= thr:
                worst = level
                break
        if worst is None:
            return
        entry = {"level": worst, "took_ms": round(took_s * 1e3, 3),
                 "index": self.name, "kind": kind, "source": detail,
                 "timestamp": time.time()}
        # request correlation (reference: SearchSlowLog stamps
        # X-Opaque-Id and the APM trace.id into every slow-log line)
        from ..common import tracing as _tracing
        tid = _tracing.current_trace_id()
        if tid:
            entry["trace.id"] = tid
        opaque = _tracing.current_opaque_id()
        if opaque:
            entry["x_opaque_id"] = opaque
        # the query shape id joins this line to /_insights/top_queries
        # and flight-recorder events without replaying the source
        from ..common import flightrec as _fr
        shape = _fr.current_shape()
        if shape:
            entry["shape"] = shape
        if stages:
            # plane-served queries: which pipeline stage ate the time
            # (queue wait / host prep / device dispatch / fetch)
            entry["serving_stages"] = {
                s: (round(ms, 3) if isinstance(ms, (int, float)) else ms)
                for s, ms in stages.items()}
        if planner:
            # one-dispatch planner context (PR 11's fused route): which
            # route served (fused vs fallback), the host-side lowering
            # cost, and the stages folded into the dispatch — a slow
            # fused query is bisectable from its slow-log line alone
            entry["planner"] = planner
        from .task_manager import current_resources
        res = current_resources()
        if res is not None:
            # the owning task's resource ledger AT THIS POINT: a slow
            # entry names what the request had already burned (CPU,
            # device-ms, docs scanned) when it crossed the threshold
            entry["task_resources"] = res.to_dict()
        self.slow_log.append(entry)
        del self.slow_log[: -self.SLOWLOG_MAX]
        try:
            import json as _json
            fname = ("_index_search_slowlog.log" if kind == "query"
                     else "_index_indexing_slowlog.log")
            with open(os.path.join(self.path, fname), "a") as f:
                f.write(_json.dumps(entry) + "\n")
        except OSError:
            pass

    def search(self, body: Optional[dict] = None,
               request_cache: Optional[bool] = None) -> ShardSearchResult:
        """One index's query execution. When a trace is active (REST
        requests), the whole shard-level phase records as a span under
        the coordinator's — the ``GET /_trace/{id}`` tree's shard tier."""
        from ..common import telemetry as _tm
        from ..common import tracing as _tracing
        from ..common import flightrec as _fr
        from ..search import query_insight as _qi
        from .task_manager import current_resources
        t0 = time.perf_counter()
        insights = _qi.insights_enabled()
        shape_token = None
        res = cpu0 = dev0 = bytes0 = None
        if insights:
            # bind the structural fingerprint up front; the shard layer
            # upgrades it in place to the plan-based id once the
            # planner lowers the body (flightrec.set_shape), so slow
            # log, ledger, dispatch records and this observation all
            # end on the same id
            if _fr.has_shape_holder():
                # the REST edge already bound a holder — upgrade it in
                # place so the whole request converges on one id
                _fr.set_shape(_qi.shape_of(body))
            else:
                shape_token = _fr.bind_shape(_qi.shape_of(body))
            cpu0 = time.thread_time()
            res = current_resources()
            if res is not None:
                dev0 = res.device_ms
                bytes0 = res.h2d_bytes + res.d2h_bytes
                # stamp the ledger NOW so a live _tasks?detailed poll
                # sees the shape while the task runs; the post-search
                # stamp below appends the plan-upgraded id if the
                # planner changed it mid-flight
                res.note_shape(_fr.current_shape())
        try:
            with _tracing.span(f"shards[{self.name}]",
                               attrs={"index": self.name,
                                      "shards": self.num_shards}):
                r = self._search_traced(body, request_cache)
                # SLO latency family: each sample may carry its trace
                # id as an OpenMetrics exemplar, so a p99 breach on the
                # scrape links straight to GET /_trace/{id} (O(1) on
                # this path)
                took_ms = (time.perf_counter() - t0) * 1e3
                _tm.DEFAULT.histogram(
                    "es_query_latency_ms", {"index": self.name},
                    help="per-index shard-phase query latency ms "
                         "(exemplars carry trace ids)").observe(
                    took_ms, exemplar=_tracing.current_trace_id())
                # the same sample feeds the SLO burn-rate engine (one
                # locked per-second bucket update — the watchdog
                # evaluates windows off this path)
                _fr.observe_query_latency(took_ms)
                if insights:
                    dev_ms = (res.device_ms - dev0) \
                        if res is not None else 0.0
                    xfer = (res.h2d_bytes + res.d2h_bytes - bytes0) \
                        if res is not None else 0.0
                    shape = _fr.current_shape()
                    if res is not None and shape:
                        res.note_shape(shape)
                    _qi.store_for(_fr.ambient_node()).observe(
                        shape, _tracing.current_opaque_id(),
                        latency_ms=took_ms,
                        cpu_ms=(time.thread_time() - cpu0) * 1e3,
                        device_ms=dev_ms, bytes_=xfer,
                        trace_id=_tracing.current_trace_id(),
                        sample_body=body)
                return r
        finally:
            if shape_token is not None:
                _fr.reset_shape(shape_token)

    def _search_traced(self, body: Optional[dict],
                       request_cache: Optional[bool]) -> ShardSearchResult:
        self._check_open()
        t0 = time.perf_counter()
        if self.cluster_hooks is not None:
            r = self.cluster_hooks.search(self.name, body or {},
                                          request_cache=request_cache)
            if r is not None:
                self._slowlog_record("query", time.perf_counter() - t0,
                                     str(body or {})[:1000],
                                     stages=getattr(r, "serving_stages",
                                                    None),
                                     planner=getattr(r, "planner", None))
                return r
        key = self._request_cache_key(body or {}, request_cache)
        plane_key = None
        if key is not None:
            hit = self.cache_get(key)
            if hit is not None:
                return hit
        else:
            # plane-served path: identical plane-eligible bodies hit the
            # shard request cache BEFORE the micro-batcher (cached hits
            # stay pristine — copies in, copies out)
            plane_key = self._plane_cache_key(body or {}, request_cache)
            if plane_key is not None:
                hit = self.cache_get(plane_key)
                if hit is not None:
                    self._plane_cache_counters["hit"].inc()
                    return _copy_shard_result(hit)
        if self.num_shards > 1:
            r = self.dist_searcher().search(body or {})
        else:
            r = self.searcher().search(body or {})
        if key is not None:
            self.cache_put(key, r)
        elif plane_key is not None:
            self._plane_cache_counters["miss"].inc()
            self.cache_put(plane_key, _copy_shard_result(r))
        self._slowlog_record("query", time.perf_counter() - t0,
                             str(body or {})[:1000],
                             stages=getattr(r, "serving_stages", None),
                             planner=getattr(r, "planner", None))
        return r

    def count(self, body: Optional[dict] = None) -> int:
        self._check_open()
        if self.cluster_hooks is not None:
            c = self.cluster_hooks.count(self.name, body or {})
            if c is not None:
                return c
        if self.num_shards > 1:
            return self.dist_searcher().count(body or {})
        return self.searcher().count(body or {})

    # -- admin --------------------------------------------------------------

    def refresh(self) -> None:
        if self.cluster_hooks is not None and \
                self.cluster_hooks.refresh(self.name):
            return
        for s in self.shards:
            s.refresh()

    def refresh_shard(self, doc_id: str,
                      routing: Optional[str] = None) -> None:
        """Refresh only the shard owning ``doc_id`` — the scope of a doc
        op's ``?refresh=true`` (reference: ``TransportShardBulkAction``
        refreshes the affected shard, never the whole index; other
        shards' pending NRT deletes must stay invisible)."""
        sid = self.shard_id_for(doc_id, routing)
        if self.cluster_hooks is not None and \
                self.cluster_hooks.refresh(self.name, shard=sid):
            return
        self.shards[sid].refresh()

    def flush(self) -> None:
        for s in self.shards:
            s.flush()

    def force_merge(self) -> None:
        for s in self.shards:
            s.force_merge()

    def put_mapping(self, mappings: dict) -> None:
        self.mapper.merge(mappings)

    def update_settings(self, settings: dict) -> None:
        flat = {(k if k.startswith("index.") else f"index.{k}"): v
                for k, v in _flatten_settings(settings).items()}
        _reject_retired_settings(flat)
        for k in flat:
            if k == "index.number_of_shards":
                raise IllegalArgumentError(
                    f"final {self.name} setting [{k}], not updateable")
        self.settings.update(flat)
        if "index.number_of_replicas" in flat:
            self.num_replicas = int(flat["index.number_of_replicas"])
        if "index.mapping.nested_objects.limit" in flat:
            try:
                self.mapper.nested_limit = int(
                    flat["index.mapping.nested_objects.limit"])
            except (TypeError, ValueError):
                pass

    def field_bytes(self):
        """(fielddata_bytes_by_field, completion_bytes_by_field) — host
        array footprints of each field's loaded columns, the analog of
        Lucene fielddata / completion FST memory accounting."""
        from ..index.mapping import CompletionFieldType
        completion_fields = {n for n, ft in self.mapper._fields.items()
                             if isinstance(ft, CompletionFieldType)}
        loaded = self.mapper.fielddata_loaded
        fd: Dict[str, int] = {}
        comp: Dict[str, int] = {}
        for s in self.shards:
            for seg in s.searchable_segments():
                for fname, f in seg.text_fields.items():
                    if fname not in loaded:
                        continue          # fielddata loads lazily
                    fd[fname] = fd.get(fname, 0) + int(
                        f.docs_host.nbytes + f.tf_host.nbytes +
                        f.pos_flat.nbytes + f.doc_len_host.nbytes)
                for fname, f in seg.keyword_fields.items():
                    n = int(f.docs_host.nbytes + f.dv_ords_host.nbytes +
                            f.dv_docs_host.nbytes +
                            sum(len(t) for t in f.ord_terms))
                    if fname in completion_fields:
                        comp[fname] = comp.get(fname, 0) + n
                    elif fname in loaded:
                        fd[fname] = fd.get(fname, 0) + n
                for fname, f in seg.numeric_fields.items():
                    if fname not in loaded:
                        continue
                    fd[fname] = fd.get(fname, 0) + int(
                        f.vals_host.nbytes + f.docs_host.nbytes)
        return fd, comp

    def plane_serving_stats(self) -> dict:
        """Micro-batcher serving stats aggregated over this index's
        serving generations (lexical + kNN), plus the plane-path cache
        counters and the generation-maintenance rollup (rebuilds by mode,
        delta-served queries) — the ``plane_serving`` nodes-stats
        section."""
        from ..search.microbatch import empty_serving_stats
        out = empty_serving_stats()
        # locked generation snapshot: iterating the registry dicts raw
        # races the background repack thread's atomic swap — a scrape
        # mid-swap would die with "dictionary changed size during
        # iteration" (ESTP-R01, found by the first full race scan)
        # topology keys describe the shared serving mesh, not per-batcher
        # work — max-merge them; everything else is additive
        _topo = ("max_batch", "mesh_shard_devices", "mesh_replica_devices")
        for b in self.plane_cache.serving_batchers():
            doc = b.stats_doc()
            for k, v in doc.items():
                out[k] = max(out[k], v) if k in _topo else out[k] + v
        out["cache_hit_count"] = self.plane_cache_stats["hit_count"]
        out["cache_miss_count"] = self.plane_cache_stats["miss_count"]
        try:
            rb = self.plane_cache.rebuild_stats()
        except Exception:   # noqa: BLE001 — stats must never fail a node
            rb = {}
        out["rebuilds_sync"] = rb.get("sync", 0)
        out["rebuilds_background"] = rb.get("background", 0)
        out["delta_served_queries"] = rb.get("delta_serves", 0)
        return out

    def stats(self, with_field_bytes: bool = True) -> dict:
        """``with_field_bytes=False`` skips the per-field column-footprint
        walk (O(vocabulary)) for callers that only need counts (cat,
        rollover conditions)."""
        docs = sum(s.doc_count for s in self.shards)
        deleted = sum(s.deleted_count for s in self.shards)
        seg_count = sum(len(s.searchable_segments()) for s in self.shards)
        store = 0
        for s in self.shards:
            for root, _, files in os.walk(s.path):
                for f in files:
                    try:
                        store += os.path.getsize(os.path.join(root, f))
                    except OSError:
                        pass
        ops = {}
        for key in ("index_total", "delete_total", "refresh_total",
                    "flush_total", "merge_total", "get_total"):
            ops[key] = sum(s.stats.get(key, 0) for s in self.shards)
        tl_ops = sum(s.translog.total_operations() for s in self.shards)
        tl_size = sum(s.translog.size_in_bytes() for s in self.shards)
        fd, comp = self.field_bytes() if with_field_bytes else ({}, {})
        ss = self.search_stats
        out = empty_index_stats()
        # request_cache_stats already count the plane-path entries (they
        # share cache_get/cache_put); plane_serving breaks them out
        out["request_cache"].update(self.request_cache_stats)
        out["plane_serving"].update(self.plane_serving_stats())
        out["docs"].update(count=docs, deleted=deleted)
        out["store"].update(size_in_bytes=store,
                            total_data_set_size_in_bytes=store)
        out["translog"].update(operations=tl_ops, size_in_bytes=tl_size,
                               uncommitted_operations=tl_ops,
                               uncommitted_size_in_bytes=tl_size)
        out["segments"].update(count=seg_count,
                               memory_in_bytes=sum(fd.values()))
        out["indexing"].update(index_total=ops["index_total"],
                               delete_total=ops["delete_total"])
        out["get"].update(total=ops["get_total"])
        out["search"].update(query_total=ss["query_total"],
                             fetch_total=ss["fetch_total"],
                             scroll_total=ss["scroll_total"],
                             suggest_total=ss["suggest_total"])
        out["refresh"].update(total=ops["refresh_total"],
                              external_total=ops["refresh_total"])
        out["flush"].update(total=ops["flush_total"])
        out["merges"].update(total=ops["merge_total"])
        out["fielddata"].update(memory_size_in_bytes=sum(fd.values()))
        out["completion"].update(size_in_bytes=sum(comp.values()))
        return out

    def shard_stats(self, node_id: str = "node") -> Dict[str, list]:
        """level=shards payload: shard number → list of copies."""
        out: Dict[str, list] = {}
        for i, s in enumerate(self.shards):
            segs = s.searchable_segments()
            commit_id = f"{abs(hash(tuple(sorted(g.seg_id for g in segs)))):016x}"
            out[str(i)] = [{
                "routing": {"state": "STARTED", "primary": True,
                            "node": node_id, "relocating_node": None},
                "docs": {"count": s.doc_count, "deleted": s.deleted_count},
                "store": {"size_in_bytes": 0},
                "commit": {"id": commit_id,
                           "generation": s.stats.get("flush_total", 0) + 1,
                           "user_data": {}, "num_docs": s.doc_count},
                "seq_no": {"max_seq_no": s.tracker.max_seq_no,
                           "local_checkpoint": s.tracker.checkpoint,
                           "global_checkpoint": s.tracker.checkpoint},
                "shard_path": {"data_path": s.path,
                               "is_custom_data_path": False},
            }]
        return out

    def close(self) -> None:
        for s in self.shards:
            s.close()
        # release the serving planes' breaker reservations (their dense
        # tiers die with the index)
        try:
            self.plane_cache.release()
        except Exception:   # noqa: BLE001 — close must not throw
            pass


class IndicesService:
    """All indices on this node (reference: ``IndicesService.java:176``).
    Resolves index expressions (names, aliases, wildcards, _all)."""

    def __init__(self, data_path: str):
        self.data_path = data_path
        os.makedirs(data_path, exist_ok=True)
        self.indices: Dict[str, IndexService] = {}
        #: data-stream seam: name -> backing index list (or None) —
        #: set by the owning RestAPI's DataStreamService so stream names
        #: resolve like aliases over their generations
        self.data_streams_provider = None

    # -- lifecycle ----------------------------------------------------------

    def create_index(self, name: str, settings: Optional[dict] = None,
                     mappings: Optional[dict] = None,
                     aliases: Optional[dict] = None) -> IndexService:
        validate_index_name(name)
        if name in self.indices or name in self.all_aliases():
            raise ResourceAlreadyExistsError(f"index [{name}] already exists")
        svc = IndexService(name, os.path.join(self.data_path, name),
                           settings, mappings)
        for alias, spec in (aliases or {}).items():
            svc.aliases[alias] = spec or {}
        self.indices[name] = svc
        return svc

    def delete_index(self, expression: str) -> List[str]:
        names = self.resolve(expression, allow_aliases=False)
        mounted = getattr(self, "_mounted_snapshots", None)
        for n in names:
            svc = self.indices.pop(n)
            svc.close()
            shutil.rmtree(svc.path, ignore_errors=True)
            if mounted is not None:
                # searchable-snapshot bookkeeping follows the index out
                # on EVERY deletion path (REST, ILM, resize cleanup)
                mounted.pop(n, None)
        return names

    def get(self, name: str) -> IndexService:
        svc = self.indices.get(name)
        if svc is None:
            resolved = self.resolve(name)
            if len(resolved) != 1:
                raise IllegalArgumentError(
                    f"alias [{name}] has more than one index associated")
            return self.indices[resolved[0]]
        return svc

    def exists(self, expression: str) -> bool:
        try:
            return bool(self.resolve(expression))
        except IndexNotFoundError:
            return False

    def all_aliases(self) -> Dict[str, List[str]]:
        out: Dict[str, List[str]] = {}
        for name, svc in self.indices.items():
            for a in svc.aliases:
                out.setdefault(a, []).append(name)
        return out

    def resolve(self, expression: Optional[str],
                allow_aliases: bool = True) -> List[str]:
        """Index expression → concrete index names (reference:
        ``IndexNameExpressionResolver``): comma lists, wildcards, _all,
        aliases."""
        if expression in (None, "", "_all", "*"):
            return sorted(self.indices)
        aliases = self.all_aliases() if allow_aliases else {}
        out: List[str] = []
        for part in str(expression).split(","):
            part = part.strip()
            if not part:
                continue
            if part in self.indices:
                out.append(part)
            elif part in aliases:
                out.extend(aliases[part])
            elif self.data_streams_provider is not None and \
                    self.data_streams_provider(part) is not None:
                out.extend(self.data_streams_provider(part))
            elif "*" in part or "?" in part:
                import fnmatch
                matched = [n for n in self.indices
                           if fnmatch.fnmatchcase(n, part)]
                if allow_aliases:
                    for a, names in aliases.items():
                        if fnmatch.fnmatchcase(a, part):
                            matched.extend(names)
                out.extend(sorted(set(matched)))
            else:
                raise IndexNotFoundError(part)
        seen = set()
        uniq = []
        for n in out:
            if n not in seen:
                seen.add(n)
                uniq.append(n)
        return uniq

    def close(self) -> None:
        for svc in self.indices.values():
            svc.close()


def _copy_shard_result(r: ShardSearchResult) -> ShardSearchResult:
    """Defensive copy for plane-path cache entries: the coordinator
    mutates hit objects in place (score boosts, sort-cursor lifting), so
    both the stored entry and every served hit get fresh ShardHit shells
    (sources/highlights are shared read-only payloads)."""
    import copy
    hits = []
    for h in r.hits:
        h2 = copy.copy(h)
        if h2.sort_values is not None:
            h2.sort_values = list(h2.sort_values)
        if h2.fields is not None:
            h2.fields = dict(h2.fields)
        hits.append(h2)
    r2 = copy.copy(r)
    r2.hits = hits
    return r2


def _flatten_settings(settings: dict, prefix: str = "") -> Dict[str, Any]:
    """{"index": {"number_of_shards": 2}} → {"index.number_of_shards": 2}."""
    out: Dict[str, Any] = {}
    for k, v in settings.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten_settings(v, key + "."))
        else:
            out[key] = v
    return out


def _parse_time_seconds(v) -> float:
    if isinstance(v, (int, float)):
        return float(v)
    s = str(v)
    m = re.fullmatch(r"(\d+(?:\.\d+)?)(ms|s|m|h|d)?", s)
    if not m:
        raise IllegalArgumentError(f"failed to parse time value [{v}]")
    mult = {"ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0,
            "d": 86400.0}.get(m.group(2) or "s", 1.0)
    return float(m.group(1)) * mult


#: settings removed in 8.0 — using them is an error, not a no-op
#: (reference: IndexSettings deprecation/removal of translog retention)
_RETIRED_SETTING_PREFIXES = ("index.translog.retention.",
                             "translog.retention.")


def _reject_retired_settings(flat: Dict[str, Any]) -> None:
    for k in flat:
        if any(k.startswith(p) for p in _RETIRED_SETTING_PREFIXES):
            raise IllegalArgumentError(
                f"unknown setting [{k}] please check that any required "
                f"plugins are installed, or check the breaking changes "
                f"documentation for removed settings")


def empty_index_stats() -> Dict[str, Any]:
    """Zero-valued index stats tree — the full section/field shape of the
    reference's CommonStats serialization; IndexService.stats() fills in
    the live numbers and nodes-level rollups start from this so every
    section exists even with zero indices."""
    from ..search.microbatch import \
        empty_serving_stats as _empty_serving_stats
    zero_cache = {"memory_size_in_bytes": 0, "evictions": 0,
                  "hit_count": 0, "miss_count": 0}
    return {
        "docs": {"count": 0, "deleted": 0},
        "store": {"size_in_bytes": 0, "total_data_set_size_in_bytes": 0,
                  "reserved_in_bytes": 0},
        "indexing": {"index_total": 0, "index_time_in_millis": 0,
                     "index_current": 0, "index_failed": 0,
                     "delete_total": 0, "delete_time_in_millis": 0,
                     "delete_current": 0, "noop_update_total": 0,
                     "is_throttled": False, "throttle_time_in_millis": 0},
        "get": {"total": 0, "time_in_millis": 0, "exists_total": 0,
                "exists_time_in_millis": 0, "missing_total": 0,
                "missing_time_in_millis": 0, "current": 0},
        "search": {"open_contexts": 0, "query_total": 0,
                   "query_time_in_millis": 0, "query_current": 0,
                   "fetch_total": 0, "fetch_time_in_millis": 0,
                   "fetch_current": 0, "scroll_total": 0,
                   "scroll_time_in_millis": 0, "scroll_current": 0,
                   "suggest_total": 0, "suggest_time_in_millis": 0,
                   "suggest_current": 0},
        "merges": {"current": 0, "current_docs": 0,
                   "current_size_in_bytes": 0, "total": 0,
                   "total_time_in_millis": 0, "total_docs": 0,
                   "total_size_in_bytes": 0},
        "refresh": {"total": 0, "total_time_in_millis": 0,
                    "external_total": 0,
                    "external_total_time_in_millis": 0, "listeners": 0},
        "flush": {"total": 0, "periodic": 0, "total_time_in_millis": 0},
        "warmer": {"current": 0, "total": 0, "total_time_in_millis": 0},
        "query_cache": dict(zero_cache, total_count=0, cache_size=0,
                            cache_count=0),
        "fielddata": {"memory_size_in_bytes": 0, "evictions": 0},
        "completion": {"size_in_bytes": 0},
        "segments": {"count": 0, "memory_in_bytes": 0,
                     "terms_memory_in_bytes": 0,
                     "stored_fields_memory_in_bytes": 0,
                     "doc_values_memory_in_bytes": 0,
                     "index_writer_memory_in_bytes": 0,
                     "version_map_memory_in_bytes": 0,
                     "fixed_bit_set_memory_in_bytes": 0,
                     "max_unsafe_auto_id_timestamp": -1, "file_sizes": {}},
        "translog": {"operations": 0, "size_in_bytes": 0,
                     "uncommitted_operations": 0,
                     "uncommitted_size_in_bytes": 0,
                     "earliest_last_modified_age": 0},
        "request_cache": dict(zero_cache),
        # serving-pipeline observability (search/microbatch.py): per-stage
        # time totals + dispatch/coalescing counters + plane-path cache +
        # generation maintenance (rebuild storms must be visible)
        "plane_serving": dict(_empty_serving_stats(),
                              cache_hit_count=0, cache_miss_count=0,
                              rebuilds_sync=0, rebuilds_background=0,
                              delta_served_queries=0),
        "recovery": {"current_as_source": 0, "current_as_target": 0,
                     "throttle_time_in_millis": 0},
        "bulk": {"total_operations": 0, "total_time_in_millis": 0,
                 "total_size_in_bytes": 0, "avg_time_in_millis": 0,
                 "avg_size_in_bytes": 0},
    }
