"""estpu-sql: interactive SQL shell against a running node.

Reference: the x-pack SQL CLI (``x-pack/plugin/sql/sql-cli``) — reads
statements, POSTs to ``/_sql?format=txt``, prints the table.

    python -m elasticsearch_tpu.cli.sql --server 127.0.0.1:9200
    echo "SELECT * FROM idx" | python -m elasticsearch_tpu.cli.sql
"""
from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="estpu-sql")
    ap.add_argument("--server", default="127.0.0.1:9200")
    ap.add_argument("-e", "--execute", default=None,
                    help="run one statement and exit")
    args = ap.parse_args(argv)
    from ..client.transport import ClientTransport, TransportError
    t = ClientTransport([args.server])

    def run(stmt: str) -> int:
        stmt = stmt.strip().rstrip(";")
        if not stmt:
            return 0
        try:
            _st, out = t.perform_request(
                "POST", "/_sql", {"format": "txt"}, {"query": stmt})
            print(out, end="" if str(out).endswith("\n") else "\n")
            return 0
        except TransportError as e:
            info = e.info
            reason = info
            if isinstance(info, dict):
                reason = (info.get("error") or {}).get("reason", info)
            print(f"ERROR: {reason}", file=sys.stderr)
            return 1

    if args.execute is not None:
        return run(args.execute)
    if not sys.stdin.isatty():
        rc = 0
        for line in sys.stdin:
            rc |= run(line)
        return rc
    print(f"estpu-sql connected to {args.server} "
          f"(terminate statements with Enter; Ctrl-D to exit)")
    while True:
        try:
            line = input("sql> ")
        except (EOFError, KeyboardInterrupt):
            print()
            return 0
        run(line)


if __name__ == "__main__":
    raise SystemExit(main())
