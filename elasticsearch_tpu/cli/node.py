"""estpu-node: launch a single node serving HTTP.

Reference: the ``elasticsearch`` launcher scripts
(``distribution/tools/launchers/``) + ``bootstrap/Elasticsearch.java:75``
reduced to the single-process case: build the node stack, bind the HTTP
port, serve until SIGINT. Cluster formation (multi-node) is configured
through ``--seed`` peers, in which case the full coordination stack runs.

    python -m elasticsearch_tpu.cli.node --port 9200 --data ./data
    python -m elasticsearch_tpu.cli.node --name n1 --transport-port 9300 \\
        --seed n1=127.0.0.1:9300 --seed n2=127.0.0.1:9301
"""
from __future__ import annotations

import argparse
import asyncio
import os
import signal

# Opt-in runtime lockdep witness (ES_TPU_LOCKDEP=1): install BEFORE the
# node stack imports create their module/instance locks, so a live node
# serves with observed lock-order checking and exports the es_lockdep_*
# evidence families (see STATIC_ANALYSIS.md). Inert otherwise.
from ..common import lockdep as _lockdep

_lockdep.install()


def _wrap_handler(handle, owner=None):
    """Adapt a REST ``handle`` to the HttpServer's 4-tuple form: collect
    the echoed response headers (Trace-Id, X-Opaque-Id) per request.
    ``owner`` keeps the ``__self__`` link HttpServer.start uses to
    advertise the real bound address (http_publish_address)."""
    def handler(method, path, query, body, headers=None):
        rh = {}
        status, ct, out = handle(method, path, query, body,
                                 headers=headers, resp_headers=rh)
        return status, ct, out, rh
    if owner is not None:
        handler.__self__ = owner
    return handler


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="estpu-node")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=9200)
    ap.add_argument("--data", default="./data")
    ap.add_argument("--name", default="estpu-node-0")
    ap.add_argument("--cluster-name", default="es-tpu")
    ap.add_argument("--transport-port", type=int, default=None,
                    help="enable the cluster transport on this port")
    ap.add_argument("--seed", action="append", default=[],
                    metavar="NAME=HOST:PORT",
                    help="cluster peer (repeatable; includes self)")
    ap.add_argument("--jax-platform", default=None,
                    help="force the jax backend (tpu/cpu); default: "
                         "ambient")
    args = ap.parse_args(argv)
    if args.jax_platform:
        import jax
        jax.config.update("jax_platforms", args.jax_platform)
    os.makedirs(args.data, exist_ok=True)

    if args.transport_port is not None and args.seed:
        peers = {}
        for s in args.seed:
            name, _, addr = s.partition("=")
            host, _, port = addr.partition(":")
            peers[name] = (host, int(port))
        from ..node.cluster_node import ClusterNode
        node = ClusterNode(args.name, args.host, args.transport_port,
                           peers, args.data)
        handler = _wrap_handler(node.rest.handle)
        print(f"[{args.name}] cluster node up: transport "
              f"{args.host}:{args.transport_port}, peers "
              f"{sorted(peers)}")
    else:
        from ..node.indices_service import IndicesService
        from ..rest.api import RestAPI
        api = RestAPI(IndicesService(args.data),
                      cluster_name=args.cluster_name,
                      node_name=args.name)
        handler = _wrap_handler(api.handle, owner=api)
        node = None

    from ..rest.http_server import HttpServer

    async def serve():
        srv = HttpServer(handler, host=args.host, port=args.port,
                         pass_headers=True)
        await srv.start()
        print(f"[{args.name}] HTTP listening on "
              f"http://{args.host}:{args.port}")
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, stop.set)
            except NotImplementedError:   # pragma: no cover (windows)
                pass
        await stop.wait()
        await srv.stop()

    try:
        asyncio.run(serve())
    finally:
        if node is not None:
            node.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
