"""CLI tools (L15): keystore management, node launcher, SQL shell.

Reference: ``distribution/tools/{keystore-cli,launchers}`` and the
x-pack SQL CLI. Run as modules:

    python -m elasticsearch_tpu.cli.keystore  <create|list|add|remove>
    python -m elasticsearch_tpu.cli.node      [--port 9200] [--data DIR]
    python -m elasticsearch_tpu.cli.sql       [--server host:port]
"""
