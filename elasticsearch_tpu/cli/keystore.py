"""estpu-keystore: manage the secure-settings keystore.

Reference: ``distribution/tools/keystore-cli/`` (CreateKeyStoreCommand,
AddStringKeyStoreCommand, ListKeyStoreCommand, RemoveSettingKeyStore
Command, ChangeKeyStorePasswordCommand).

    python -m elasticsearch_tpu.cli.keystore create [--path FILE]
    python -m elasticsearch_tpu.cli.keystore list
    python -m elasticsearch_tpu.cli.keystore add <setting> [--stdin]
    python -m elasticsearch_tpu.cli.keystore remove <setting>
    python -m elasticsearch_tpu.cli.keystore passwd
"""
from __future__ import annotations

import argparse
import getpass
import os
import sys

from ..common.keystore import Keystore, KeystoreError


def _default_path() -> str:
    return os.environ.get("ESTPU_KEYSTORE",
                          os.path.join(os.getcwd(), Keystore.FILENAME))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="estpu-keystore")
    ap.add_argument("--path", default=None,
                    help="keystore file (default: $ESTPU_KEYSTORE or "
                         "./estpu.keystore)")
    ap.add_argument("--password", default=None,
                    help="keystore password (prompted when protected)")
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("create")
    sub.add_parser("list")
    p_add = sub.add_parser("add")
    p_add.add_argument("setting")
    p_add.add_argument("--stdin", action="store_true",
                       help="read the value from stdin")
    p_rm = sub.add_parser("remove")
    p_rm.add_argument("setting")
    sub.add_parser("passwd")
    args = ap.parse_args(argv)
    path = args.path or _default_path()

    def load() -> Keystore:
        pw = args.password if args.password is not None else ""
        try:
            return Keystore.load(path, pw)
        except KeystoreError:
            if args.password is None and sys.stdin.isatty():
                pw = getpass.getpass("Keystore password: ")
                return Keystore.load(path, pw)
            raise

    try:
        if args.cmd == "create":
            if os.path.exists(path):
                print(f"keystore already exists at [{path}]",
                      file=sys.stderr)
                return 1
            Keystore(path, args.password or "").save()
            print(f"Created keystore [{path}]")
            return 0
        if not os.path.exists(path):
            print(f"ERROR: keystore not found at [{path}]; run 'create'",
                  file=sys.stderr)
            return 1
        ks = load()
        if args.cmd == "list":
            for k in ks.list_keys():
                print(k)
        elif args.cmd == "add":
            if args.stdin or not sys.stdin.isatty():
                value = sys.stdin.readline().rstrip("\n")
            else:
                value = getpass.getpass(
                    f"Enter value for {args.setting}: ")
            ks.set(args.setting, value)
            ks.save()
        elif args.cmd == "remove":
            ks.remove(args.setting)
            ks.save()
        elif args.cmd == "passwd":
            new = args.password
            if new is None:
                new = getpass.getpass("New password: ")
            ks.password = new
            ks.save()
            print("Password updated")
        return 0
    except KeystoreError as e:
        print(f"ERROR: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    raise SystemExit(main())
