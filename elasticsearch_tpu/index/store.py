"""Binary columnar segment store + columnar merge.

Replaces the round-1 gzip-JSON-of-sources format (which re-analyzed every
document through the mapper on restart and merge — O(corpus) re-analysis)
with persisted *index structures*:

- ``seg_<id>.npz``          — all postings/doc-values/vector arrays plus the
  packed source bytes and per-doc metadata, written once, immutable.
- ``seg_<id>.live.npy``     — the liveness bitmap alone, rewritten when
  deletes dirty an already-persisted segment (Lucene's ``.liv`` files next
  to immutable segment files — reference: ``index/store/Store.java:130``,
  ``SoftDeletesDirectoryReaderWrapper``).

Merge is a vectorized columnar concatenation (union vocab → stable sort of
posting runs by union term id → run-gather of positions); no document is
re-tokenized. Reference behavior: Lucene segment merging driven by
``EsTieredMergePolicy.java:35``.

String dictionaries are packed as (uint8 concat, int64 offsets) pairs so the
whole segment round-trips through ``np.savez``/``np.load`` without pickle.
Sources decode lazily (:class:`PackedSources`) so restart cost is zip-read,
not JSON-parse.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .segment import (KeywordFieldData, NumericFieldData, Segment,
                      TextFieldData, VectorFieldData)

FORMAT_VERSION = 2


# ---------------------------------------------------------------------------
# packed string lists
# ---------------------------------------------------------------------------


def pack_strs(strs: Sequence[str]) -> Tuple[np.ndarray, np.ndarray]:
    """list[str] → (uint8 data, int64 offsets[len+1])."""
    encoded = [s.encode("utf-8") for s in strs]
    offsets = np.zeros(len(encoded) + 1, np.int64)
    if encoded:
        np.cumsum([len(b) for b in encoded], out=offsets[1:])
    data = np.frombuffer(b"".join(encoded), dtype=np.uint8).copy() \
        if encoded else np.empty(0, np.uint8)
    return data, offsets


def unpack_strs(data: np.ndarray, offsets: np.ndarray) -> List[str]:
    buf = data.tobytes()
    return [buf[offsets[i]: offsets[i + 1]].decode("utf-8")
            for i in range(len(offsets) - 1)]


class PackedSources:
    """Lazily-decoded packed ``_source`` column: JSON bytes + offsets.

    Quacks like the ``List[Optional[dict]]`` the rest of the engine indexes
    into, but restart pays zero JSON parsing until a doc is actually
    fetched."""

    __slots__ = ("data", "offsets")

    def __init__(self, data: np.ndarray, offsets: np.ndarray):
        self.data = data
        self.offsets = offsets

    @classmethod
    def from_list(cls, sources: Sequence[Optional[dict]]) -> "PackedSources":
        data, offsets = pack_strs(
            [json.dumps(s, separators=(",", ":")) if s is not None
             else "null" for s in sources])
        return cls(data, offsets)

    def __len__(self) -> int:
        return len(self.offsets) - 1

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        raw = self.data[self.offsets[i]: self.offsets[i + 1]].tobytes()
        return json.loads(raw) if raw != b"null" else None

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def gather(self, keep: np.ndarray) -> "PackedSources":
        """Select rows by boolean mask — byte-level, no decode."""
        idx = np.nonzero(keep)[0]
        lengths = (self.offsets[1:] - self.offsets[:-1])[idx]
        data = _gather_runs(self.data, self.offsets[:-1][idx], lengths)
        offsets = np.zeros(idx.size + 1, np.int64)
        np.cumsum(lengths, out=offsets[1:])
        return PackedSources(data, offsets)


def _as_packed_sources(sources) -> PackedSources:
    if isinstance(sources, PackedSources):
        return sources
    return PackedSources.from_list(sources)


# ---------------------------------------------------------------------------
# vectorized run gather
# ---------------------------------------------------------------------------


def _gather_runs(flat: np.ndarray, starts: np.ndarray,
                 lengths: np.ndarray) -> np.ndarray:
    """Concatenate ``flat[starts[i] : starts[i]+lengths[i]]`` for all i,
    fully vectorized (the repeat-arange trick)."""
    lengths = np.asarray(lengths, np.int64)
    total = int(lengths.sum())
    if total == 0:
        return np.empty(0, flat.dtype)
    out_starts = np.zeros(lengths.shape[0], np.int64)
    np.cumsum(lengths[:-1], out=out_starts[1:])
    idx = np.repeat(np.asarray(starts, np.int64) - out_starts, lengths) \
        + np.arange(total, dtype=np.int64)
    return flat[idx]


# ---------------------------------------------------------------------------
# save / load
# ---------------------------------------------------------------------------


def _seg_npz_name(seg_id: str) -> str:
    return f"seg_{seg_id}.npz"


def _seg_live_name(seg_id: str) -> str:
    return f"seg_{seg_id}.live.npy"


def save_segment(seg: Segment, store_dir: str, versions: Sequence[int],
                 routing: Sequence[Optional[str]]) -> str:
    """Persist one immutable segment; returns the npz file name. The
    liveness bitmap goes to its own file via :func:`save_liveness` so later
    deletes never rewrite this file."""
    arrays: Dict[str, np.ndarray] = {}
    manifest: dict = {"format": FORMAT_VERSION, "seg_id": seg.seg_id,
                      "n_docs": seg.n_docs,
                      "text_fields": [], "keyword_fields": [],
                      "numeric_fields": [], "vector_fields": []}

    uid_data, uid_off = pack_strs(seg.doc_uids)
    arrays["uids_data"], arrays["uids_off"] = uid_data, uid_off
    src = _as_packed_sources(seg.sources)
    arrays["src_data"], arrays["src_off"] = src.data, src.offsets
    arrays["seq_nos"] = np.asarray(seg.seq_nos, np.int64)
    arrays["versions"] = np.asarray(list(versions), np.int64)
    arrays["routing_isnull"] = np.asarray(
        [r is None for r in routing], bool)
    r_data, r_off = pack_strs([r or "" for r in routing])
    arrays["routing_data"], arrays["routing_off"] = r_data, r_off

    for i, (name, f) in enumerate(sorted(seg.text_fields.items())):
        manifest["text_fields"].append(
            {"name": name, "sum_dl": f.sum_dl,
             "field_doc_count": f.field_doc_count})
        terms = sorted(f.term_ids, key=f.term_ids.get)
        td, to = pack_strs(terms)
        p = f"t{i}_"
        arrays[p + "terms_data"], arrays[p + "terms_off"] = td, to
        arrays[p + "df"] = f.df
        arrays[p + "offsets"] = f.offsets
        arrays[p + "docs"] = f.docs_host
        arrays[p + "tf"] = f.tf_host
        arrays[p + "doc_len"] = f.doc_len_host
        arrays[p + "ttf"] = f.total_term_freq
        arrays[p + "pos_off"] = f.pos_offsets
        arrays[p + "pos_flat"] = f.pos_flat

    for i, (name, f) in enumerate(sorted(seg.keyword_fields.items())):
        manifest["keyword_fields"].append({"name": name})
        td, to = pack_strs(f.ord_terms)
        p = f"k{i}_"
        arrays[p + "terms_data"], arrays[p + "terms_off"] = td, to
        arrays[p + "df"] = f.df
        arrays[p + "offsets"] = f.offsets
        arrays[p + "docs"] = f.docs_host
        arrays[p + "dv_ords"] = f.dv_ords_host
        arrays[p + "dv_docs"] = f.dv_docs_host

    for i, (name, f) in enumerate(sorted(seg.numeric_fields.items())):
        manifest["numeric_fields"].append({"name": name, "base": f.base})
        p = f"n{i}_"
        arrays[p + "vals"] = f.vals_host
        arrays[p + "docs"] = f.docs_host

    for i, (name, f) in enumerate(sorted(seg.vector_fields.items())):
        manifest["vector_fields"].append({"name": name})
        p = f"v{i}_"
        arrays[p + "mat"] = f.matrix_host
        arrays[p + "exists"] = f.exists

    i64 = getattr(seg, "int64_fields", {}) or {}
    if i64:
        # exact ns doc values (date_nanos) — absent key reads as {}
        manifest["int64_fields"] = sorted(i64)
        for i, name in enumerate(sorted(i64)):
            docs, vals = i64[name]
            arrays[f"i{i}_docs"] = docs
            arrays[f"i{i}_vals"] = vals

    if seg.nested_paths:
        manifest["nested_paths"] = sorted(seg.nested_paths)
        arrays["parent_of"] = seg.parent_of
        for i, path in enumerate(sorted(seg.nested_paths)):
            arrays[f"np{i}_mask"] = seg.nested_paths[path]

    arrays["manifest"] = np.frombuffer(
        json.dumps(manifest).encode("utf-8"), dtype=np.uint8).copy()

    fname = _seg_npz_name(seg.seg_id)
    tmp = os.path.join(store_dir, fname + ".tmp")
    with open(tmp, "wb") as fh:
        np.savez(fh, **arrays)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, os.path.join(store_dir, fname))
    save_liveness(seg, store_dir)
    return fname


def save_liveness(seg: Segment, store_dir: str) -> None:
    """Rewrite only the liveness bitmap (deletes don't touch segment data)."""
    tmp = os.path.join(store_dir, _seg_live_name(seg.seg_id) + ".tmp")
    with open(tmp, "wb") as fh:
        np.save(fh, seg.live)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, os.path.join(store_dir, _seg_live_name(seg.seg_id)))


def load_segment(store_dir: str, fname: str):
    """Load one persisted segment without touching the mapper.

    Returns ``(segment, versions int64[N], routing list[Optional[str]])``.
    """
    with np.load(os.path.join(store_dir, fname)) as z:
        arrays = {k: z[k] for k in z.files}
    manifest = json.loads(arrays["manifest"].tobytes().decode("utf-8"))

    doc_uids = unpack_strs(arrays["uids_data"], arrays["uids_off"])
    sources = PackedSources(arrays["src_data"], arrays["src_off"])
    seq_nos = arrays["seq_nos"]
    versions = arrays["versions"]
    isnull = arrays["routing_isnull"]
    r_strs = unpack_strs(arrays["routing_data"], arrays["routing_off"])
    routing = [None if isnull[i] else r_strs[i] for i in range(len(r_strs))]

    text_fields: Dict[str, TextFieldData] = {}
    for i, m in enumerate(manifest["text_fields"]):
        p = f"t{i}_"
        terms = unpack_strs(arrays[p + "terms_data"], arrays[p + "terms_off"])
        text_fields[m["name"]] = TextFieldData(
            term_ids={t: j for j, t in enumerate(terms)},
            df=arrays[p + "df"], offsets=arrays[p + "offsets"],
            docs_host=arrays[p + "docs"], tf_host=arrays[p + "tf"],
            doc_len_host=arrays[p + "doc_len"], sum_dl=m["sum_dl"],
            field_doc_count=m["field_doc_count"],
            total_term_freq=arrays[p + "ttf"],
            pos_offsets=arrays[p + "pos_off"],
            pos_flat=arrays[p + "pos_flat"])

    keyword_fields: Dict[str, KeywordFieldData] = {}
    for i, m in enumerate(manifest["keyword_fields"]):
        p = f"k{i}_"
        terms = unpack_strs(arrays[p + "terms_data"], arrays[p + "terms_off"])
        keyword_fields[m["name"]] = KeywordFieldData(
            ord_terms=terms, term_ords={t: j for j, t in enumerate(terms)},
            df=arrays[p + "df"], offsets=arrays[p + "offsets"],
            docs_host=arrays[p + "docs"],
            dv_ords_host=arrays[p + "dv_ords"],
            dv_docs_host=arrays[p + "dv_docs"])

    numeric_fields: Dict[str, NumericFieldData] = {}
    for i, m in enumerate(manifest["numeric_fields"]):
        p = f"n{i}_"
        numeric_fields[m["name"]] = NumericFieldData(
            base=m["base"], vals_host=arrays[p + "vals"],
            docs_host=arrays[p + "docs"])

    vector_fields: Dict[str, VectorFieldData] = {}
    for i, m in enumerate(manifest["vector_fields"]):
        p = f"v{i}_"
        vector_fields[m["name"]] = VectorFieldData(
            matrix_host=arrays[p + "mat"], exists=arrays[p + "exists"])

    parent_of = arrays.get("parent_of")
    nested_paths = None
    if "nested_paths" in manifest:
        nested_paths = {path: arrays[f"np{i}_mask"]
                        for i, path in enumerate(manifest["nested_paths"])}

    seg = Segment(manifest["seg_id"], manifest["n_docs"], doc_uids, sources,
                  seq_nos, text_fields, keyword_fields, numeric_fields,
                  vector_fields, parent_of=parent_of,
                  nested_paths=nested_paths)
    seg.int64_fields = {
        name: (arrays[f"i{i}_docs"], arrays[f"i{i}_vals"])
        for i, name in enumerate(manifest.get("int64_fields", []))}
    apply_liveness_sidecar(seg, store_dir)
    return seg, versions, routing


def apply_liveness_sidecar(seg: Segment, store_dir: str) -> None:
    """Overlay the ``.live.npy`` sidecar (if present) onto a freshly loaded
    segment — deletes after the segment file was written live only here."""
    live_path = os.path.join(store_dir, _seg_live_name(seg.seg_id))
    if os.path.exists(live_path):
        live = np.load(live_path)
        if live.shape[0] == seg.n_docs:
            seg.live = live.astype(bool)
            seg._live_dev = None


def segment_file_names(seg_id: str) -> List[str]:
    return [_seg_npz_name(seg_id), _seg_live_name(seg_id)]


# ---------------------------------------------------------------------------
# columnar merge
# ---------------------------------------------------------------------------


def merge_segments(seg_id: str,
                   segments: List[Segment]) -> Optional[Segment]:
    """Merge live docs of ``segments`` into one new segment **columnar-ly**:
    no re-tokenization, no mapper. Returns None when nothing is live.
    (Routing stays in the engine's version map — the source of truth at
    persist time.)"""
    lives = [s.live.copy() for s in segments]
    n_live = [int(m.sum()) for m in lives]
    n_new = sum(n_live)
    if n_new == 0:
        return None
    # new doc id for each old local doc (valid where live)
    remaps: List[np.ndarray] = []
    base = 0
    for s, m in zip(segments, lives):
        r = np.cumsum(m, dtype=np.int64) - 1 + base
        remaps.append(r.astype(np.int32))
        base += int(m.sum())

    doc_uids: List[str] = []
    for s, m in zip(segments, lives):
        idx = np.nonzero(m)[0]
        doc_uids.extend(s.doc_uids[i] for i in idx)
    seq_nos = np.concatenate(
        [np.asarray(s.seq_nos)[m] for s, m in zip(segments, lives)]) \
        if segments else np.empty(0, np.int64)
    sources = _concat_sources(segments, lives)

    text_fields = _merge_text(segments, lives, remaps, n_new)
    keyword_fields = _merge_keyword(segments, lives, remaps)
    numeric_fields = _merge_numeric(segments, lives, remaps)
    vector_fields = _merge_vector(segments, lives, remaps, n_new)

    # block-join arrays: remap child→parent pointers and per-path marks
    # (delete cascade guarantees a live child's parent is live too)
    parent_of = None
    nested_paths: Dict[str, np.ndarray] = {}
    if any(s.nested_paths for s in segments):
        parent_of = np.concatenate(
            [r[s.parent_of[m]] for s, m, r in zip(segments, lives, remaps)]
        ).astype(np.int32) if n_new else np.empty(0, np.int32)
        all_paths = sorted({p for s in segments for p in s.nested_paths})
        for path in all_paths:
            nested_paths[path] = np.concatenate([
                (s.nested_paths[path][m] if path in s.nested_paths
                 else np.zeros(int(m.sum()), bool))
                for s, m in zip(segments, lives)])

    merged = Segment(seg_id, n_new, doc_uids, sources,
                     seq_nos.astype(np.int64), text_fields, keyword_fields,
                     numeric_fields, vector_fields,
                     parent_of=parent_of, nested_paths=nested_paths or None)
    i64_names = sorted({n for s in segments
                        for n in getattr(s, "int64_fields", {}) or {}})
    if i64_names:
        out64: Dict[str, tuple] = {}
        for name in i64_names:
            docs_parts, vals_parts = [], []
            for s, m, r in zip(segments, lives, remaps):
                pair = (getattr(s, "int64_fields", {}) or {}).get(name)
                if pair is None:
                    continue
                docs, vals = pair
                keep = m[docs]
                docs_parts.append(r[docs[keep]])
                vals_parts.append(vals[keep])
            out64[name] = (
                np.concatenate(docs_parts).astype(np.int32)
                if docs_parts else np.empty(0, np.int32),
                np.concatenate(vals_parts).astype(np.int64)
                if vals_parts else np.empty(0, np.int64))
        merged.int64_fields = out64
    return merged


def _concat_sources(segments, lives):
    packed = [_as_packed_sources(s.sources).gather(m)
              for s, m in zip(segments, lives)]
    data = np.concatenate([p.data for p in packed]) if packed \
        else np.empty(0, np.uint8)
    sizes = [p.offsets[1:] - p.offsets[:-1] for p in packed]
    lengths = np.concatenate(sizes) if sizes else np.empty(0, np.int64)
    offsets = np.zeros(lengths.size + 1, np.int64)
    np.cumsum(lengths, out=offsets[1:])
    return PackedSources(data, offsets)


def _union_vocab(term_lists: List[List[str]]):
    union = sorted(set().union(*map(set, term_lists))) if term_lists else []
    index = {t: i for i, t in enumerate(union)}
    maps = [np.asarray([index[t] for t in terms], np.int64)
            if terms else np.empty(0, np.int64) for terms in term_lists]
    return union, maps


def _merge_text(segments, lives, remaps, n_new) -> Dict[str, TextFieldData]:
    names = sorted({n for s in segments for n in s.text_fields})
    out: Dict[str, TextFieldData] = {}
    for name in names:
        parts = []          # (utid, docs, tf, pos_lengths, pos_starts, flat)
        doc_len_new = np.zeros(n_new, np.float32)
        term_lists = []
        active = []
        for s, m, r in zip(segments, lives, remaps):
            f = s.text_fields.get(name)
            if f is None:
                continue
            active.append((f, m, r))
            term_lists.append(sorted(f.term_ids, key=f.term_ids.get))
        union_terms, term_maps = _union_vocab(term_lists)
        for (f, m, r), tmap in zip(active, term_maps):
            df_pre = (f.offsets[1:] - f.offsets[:-1]).astype(np.int64)
            pair_term = np.repeat(np.arange(df_pre.size), df_pre)
            keep = m[f.docs_host]
            docs_k = r[f.docs_host[keep]]
            tf_k = f.tf_host[keep]
            utid_k = tmap[pair_term[keep]]
            pos_lengths = (f.pos_offsets[1:] - f.pos_offsets[:-1])[keep]
            pos_starts = f.pos_offsets[:-1][keep]
            flat_k = _gather_runs(f.pos_flat, pos_starts, pos_lengths)
            parts.append((utid_k, docs_k, tf_k, pos_lengths, flat_k))
            live_idx = np.nonzero(m)[0]
            doc_len_new[r[live_idx]] = f.doc_len_host[live_idx]
        if not parts:
            continue
        utid = np.concatenate([p[0] for p in parts])
        docs = np.concatenate([p[1] for p in parts])
        tf = np.concatenate([p[2] for p in parts])
        pos_lengths = np.concatenate([p[3] for p in parts])
        pos_flat = np.concatenate([p[4] for p in parts])
        pair_starts = np.zeros(pos_lengths.size, np.int64)
        np.cumsum(pos_lengths[:-1], out=pair_starts[1:])

        order = np.argsort(utid, kind="stable")
        utid_o = utid[order]
        docs_o = docs[order].astype(np.int32)
        tf_o = tf[order].astype(np.float32)
        lengths_o = pos_lengths[order]
        pos_flat_o = _gather_runs(pos_flat, pair_starts[order], lengths_o)
        pos_off_o = np.zeros(lengths_o.size + 1, np.int64)
        np.cumsum(lengths_o, out=pos_off_o[1:])

        v_u = len(union_terms)
        df_new = np.bincount(utid_o, minlength=v_u).astype(np.int32)
        ttf_new = np.bincount(utid_o, weights=tf_o,
                              minlength=v_u).astype(np.int64)
        keep_terms = df_new > 0
        terms_c = [t for t, k in zip(union_terms, keep_terms) if k]
        df_c = df_new[keep_terms]
        ttf_c = ttf_new[keep_terms]
        offsets_c = np.zeros(df_c.size + 1, np.int64)
        np.cumsum(df_c, out=offsets_c[1:])
        out[name] = TextFieldData(
            term_ids={t: j for j, t in enumerate(terms_c)},
            df=df_c, offsets=offsets_c, docs_host=docs_o, tf_host=tf_o,
            doc_len_host=doc_len_new, sum_dl=float(doc_len_new.sum()),
            field_doc_count=int((doc_len_new > 0).sum()),
            total_term_freq=ttf_c, pos_offsets=pos_off_o,
            pos_flat=pos_flat_o)
    return out


def _merge_keyword(segments, lives, remaps) -> Dict[str, KeywordFieldData]:
    names = sorted({n for s in segments for n in s.keyword_fields})
    out: Dict[str, KeywordFieldData] = {}
    for name in names:
        active = []
        term_lists = []
        for s, m, r in zip(segments, lives, remaps):
            f = s.keyword_fields.get(name)
            if f is None:
                continue
            active.append((f, m, r))
            term_lists.append(f.ord_terms)
        union_terms_all, term_maps = _union_vocab(term_lists)
        p_utid, p_docs, dv_ords_parts, dv_docs_parts = [], [], [], []
        for (f, m, r), tmap in zip(active, term_maps):
            df_pre = (f.offsets[1:] - f.offsets[:-1]).astype(np.int64)
            pair_term = np.repeat(np.arange(df_pre.size), df_pre)
            keep = m[f.docs_host]
            p_docs.append(r[f.docs_host[keep]])
            p_utid.append(tmap[pair_term[keep]])
            dv_keep = m[f.dv_docs_host]
            dv_docs_parts.append(r[f.dv_docs_host[dv_keep]])
            dv_ords_parts.append(tmap[f.dv_ords_host[dv_keep]])
        if not active:
            continue
        utid = np.concatenate(p_utid) if p_utid else np.empty(0, np.int64)
        docs = np.concatenate(p_docs) if p_docs else np.empty(0, np.int64)
        order = np.argsort(utid, kind="stable")
        utid_o = utid[order]
        docs_o = docs[order].astype(np.int32)
        v_u = len(union_terms_all)
        df_new = np.bincount(utid_o, minlength=v_u).astype(np.int32)
        keep_terms = df_new > 0
        comp = np.cumsum(keep_terms, dtype=np.int64) - 1
        terms_c = [t for t, k in zip(union_terms_all, keep_terms) if k]
        df_c = df_new[keep_terms]
        offsets_c = np.zeros(df_c.size + 1, np.int64)
        np.cumsum(df_c, out=offsets_c[1:])
        dv_docs = np.concatenate(dv_docs_parts).astype(np.int32) \
            if dv_docs_parts else np.empty(0, np.int32)
        dv_ords_u = np.concatenate(dv_ords_parts) if dv_ords_parts \
            else np.empty(0, np.int64)
        dv_ords = comp[dv_ords_u].astype(np.int32) if dv_ords_u.size \
            else np.empty(0, np.int32)
        out[name] = KeywordFieldData(
            ord_terms=terms_c,
            term_ords={t: j for j, t in enumerate(terms_c)},
            df=df_c, offsets=offsets_c, docs_host=docs_o,
            dv_ords_host=dv_ords, dv_docs_host=dv_docs)
    return out


def _merge_numeric(segments, lives, remaps) -> Dict[str, NumericFieldData]:
    names = sorted({n for s in segments for n in s.numeric_fields})
    out: Dict[str, NumericFieldData] = {}
    for name in names:
        docs_parts, vals_parts = [], []
        for s, m, r in zip(segments, lives, remaps):
            f = s.numeric_fields.get(name)
            if f is None:
                continue
            keep = m[f.docs_host]
            docs_parts.append(r[f.docs_host[keep]])
            vals_parts.append(f.vals_host[keep])
        if not docs_parts:
            continue
        docs = np.concatenate(docs_parts).astype(np.int32)
        vals = np.concatenate(vals_parts)
        base = float(vals.min()) if vals.size else 0.0
        out[name] = NumericFieldData(base=base, vals_host=vals,
                                     docs_host=docs)
    return out


def _merge_vector(segments, lives, remaps, n_new) -> Dict[str,
                                                          VectorFieldData]:
    names = sorted({n for s in segments for n in s.vector_fields})
    out: Dict[str, VectorFieldData] = {}
    for name in names:
        dim = 0
        for s in segments:
            f = s.vector_fields.get(name)
            if f is not None and f.matrix_host.size:
                dim = f.matrix_host.shape[1]
                break
        mat = np.zeros((n_new, dim), np.float32)
        exists = np.zeros(n_new, bool)
        for s, m, r in zip(segments, lives, remaps):
            f = s.vector_fields.get(name)
            if f is None:
                continue
            live_idx = np.nonzero(m)[0]
            mat[r[live_idx]] = f.matrix_host[live_idx]
            exists[r[live_idx]] = f.exists[live_idx]
        out[name] = VectorFieldData(matrix_host=mat, exists=exists)
    return out
