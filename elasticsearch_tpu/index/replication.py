"""Shard replication: primary fan-out, global checkpoints, peer recovery.

Re-design of the reference's replication write path and recovery stack:

- ``action/support/replication/TransportReplicationAction.java:94`` /
  ``ReplicationOperation.java:57,181`` — the primary executes an op,
  assigns its seq-no, then fans it out to every in-sync copy and only
  acks once the group has it; a failed copy is demoted out of the in-sync
  set rather than blocking the write.
- ``index/seqno/ReplicationTracker.java`` — primary-side checkpoint
  bookkeeping (already implemented in ``seqno.py``; this module is its
  first production consumer).
- ``indices/recovery/RecoverySourceHandler.java:149`` — peer recovery:
  ops-based replay from the primary's translog when history retention
  covers the copy's checkpoint (phase2 :667), file-based store copy +
  replay otherwise (phase1 :463).
- Primary-term fencing (``IndexShard.java`` operation primary terms): a
  replica rejects ops from a deposed primary's term, so a network-zombie
  old primary cannot diverge a copy after promotion.

Replica copies are reached through a :class:`ReplicaChannel` so the same
group logic runs over direct in-process calls (here, and in the
deterministic sim) or a node-to-node transport (the multi-node path).
"""

from __future__ import annotations

import os
import shutil
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from ..common.errors import ElasticsearchError, IllegalArgumentError
from .engine import DeleteResult, Engine, IndexResult
from .seqno import ReplicationTracker, UNASSIGNED_SEQ_NO
from .translog import OP_DELETE, OP_INDEX, OP_NOOP, TranslogOp


class ReplicaFencedError(ElasticsearchError):
    status = 409
    error_type = "illegal_index_shard_state_exception"


class ReplicaShard:
    """One replica copy: an engine plus the fencing/checkpoint surface the
    primary talks to. In-process stand-in for the replica-side transport
    handlers (``TransportReplicationAction.ReplicaOperationTransportHandler``)."""

    def __init__(self, allocation_id: str, engine: Engine):
        self.allocation_id = allocation_id
        self.engine = engine
        self.known_global_checkpoint = UNASSIGNED_SEQ_NO

    def _fence(self, primary_term: int) -> None:
        # the engine's primary term is the single fencing authority — a
        # promotion bumps it there, immediately fencing the old primary
        if primary_term < self.engine.primary_term:
            raise ReplicaFencedError(
                f"operation primary term [{primary_term}] is too old "
                f"(current [{self.engine.primary_term}])")
        if primary_term > self.engine.primary_term:
            self.engine.primary_term = primary_term

    def apply_index(self, primary_term: int, seq_no: int, version: int,
                    doc_id: str, source: dict,
                    routing: Optional[str], global_checkpoint: int) -> int:
        self._fence(primary_term)
        self.engine.index(doc_id, source, routing=routing, seq_no=seq_no,
                          version=version)
        self._update_gcp(global_checkpoint)
        return self.engine.tracker.checkpoint

    def apply_delete(self, primary_term: int, seq_no: int, version: int,
                     doc_id: str, global_checkpoint: int) -> int:
        self._fence(primary_term)
        self.engine.delete(doc_id, seq_no=seq_no, version=version)
        self._update_gcp(global_checkpoint)
        return self.engine.tracker.checkpoint

    def apply_translog_op(self, primary_term: int, op: TranslogOp) -> int:
        self._fence(primary_term)
        if op.op_type == OP_INDEX:
            self.engine.index(op.doc_id, op.source, routing=op.routing,
                              seq_no=op.seq_no, version=op.version)
        elif op.op_type == OP_DELETE:
            self.engine.delete(op.doc_id, seq_no=op.seq_no,
                               version=op.version)
        else:
            self.engine.noop(op.seq_no, op.reason or "recovery")
        return self.engine.tracker.checkpoint

    def _update_gcp(self, global_checkpoint: int) -> None:
        # replicas learn the global checkpoint piggybacked on writes
        # (ReplicationTracker.updateGlobalCheckpointOnReplica); it is the
        # copy's safe resume point when it later peer-recovers or promotes
        self.known_global_checkpoint = max(
            self.known_global_checkpoint, global_checkpoint)

    @property
    def local_checkpoint(self) -> int:
        return self.engine.tracker.checkpoint


class ReplicaChannel:
    """Transport seam: the in-process default calls the replica directly;
    the multi-node build substitutes an RPC-backed channel with identical
    semantics (exceptions propagate as failures)."""

    def __init__(self, replica: ReplicaShard):
        self.replica = replica

    def index(self, *a, **kw) -> int:
        return self.replica.apply_index(*a, **kw)

    def delete(self, *a, **kw) -> int:
        return self.replica.apply_delete(*a, **kw)

    def translog_op(self, *a, **kw) -> int:
        return self.replica.apply_translog_op(*a, **kw)

    def sync_gcp(self, global_checkpoint: int) -> None:
        self.replica._update_gcp(global_checkpoint)

    @property
    def allocation_id(self) -> str:
        return self.replica.allocation_id


@dataclass
class ReplicationResponse:
    result: object                       # IndexResult | DeleteResult
    total: int
    successful: int
    failed: List[str]


class PrimaryShardGroup:
    """The primary's replication group: local engine + replica channels +
    the (previously dead, now load-bearing) ReplicationTracker."""

    def __init__(self, allocation_id: str, engine: Engine,
                 on_replica_failure: Optional[Callable[[str, Exception],
                                                       None]] = None):
        self.allocation_id = allocation_id
        self.engine = engine
        self.tracker = ReplicationTracker(allocation_id, engine.tracker)
        self.tracker.activate_primary_mode(engine.tracker.checkpoint)
        self.replicas: Dict[str, ReplicaChannel] = {}
        self.on_replica_failure = on_replica_failure
        # retention leases actually pin translog history: flushes on this
        # engine will not trim ops at/above the lease floor
        engine.history_retention_provider = self.tracker.min_retained_seq_no
        #: set when a replica on a newer primary term fences us — this
        #: group must stop acking writes (it has been deposed)
        self.deposed = False

    # -- write path ----------------------------------------------------------

    def index(self, doc_id: str, source: dict, *,
              routing: Optional[str] = None,
              if_seq_no: Optional[int] = None,
              if_primary_term: Optional[int] = None,
              op_type: str = "index") -> ReplicationResponse:
        r: IndexResult = self.engine.index(
            doc_id, source, routing=routing, if_seq_no=if_seq_no,
            if_primary_term=if_primary_term, op_type=op_type)
        return self._replicate(
            r, lambda ch: ch.index(
                self.engine.primary_term, r.seq_no, r.version, doc_id,
                source, routing, self.tracker.global_checkpoint))

    def delete(self, doc_id: str, *,
               if_seq_no: Optional[int] = None,
               if_primary_term: Optional[int] = None) -> ReplicationResponse:
        r: DeleteResult = self.engine.delete(
            doc_id, if_seq_no=if_seq_no, if_primary_term=if_primary_term)
        return self._replicate(
            r, lambda ch: ch.delete(
                self.engine.primary_term, r.seq_no, r.version, doc_id,
                self.tracker.global_checkpoint))

    def _replicate(self, result,
                   send: Callable[[ReplicaChannel], int]
                   ) -> ReplicationResponse:
        if self.deposed:
            raise ReplicaFencedError(
                "shard group was deposed by a newer primary term")
        failed: List[str] = []
        for aid, ch in list(self.replicas.items()):
            try:
                replica_ckpt = send(ch)
                self.tracker.update_local_checkpoint(aid, replica_ckpt)
            except ReplicaFencedError:
                # a copy on a NEWER primary term rejected us: WE are the
                # deposed primary. Fail the operation (never ack) and stop
                # accepting writes — the reference fails the primary shard
                # on this (ReplicationOperation's primary-term check), it
                # does not demote the promoted copy.
                self.deposed = True
                raise
            except Exception as e:   # noqa: BLE001 — a copy failed, not us
                failed.append(aid)
                self._fail_replica(aid, e)
        self.tracker.update_local_checkpoint(
            self.allocation_id, self.engine.tracker.checkpoint)
        return ReplicationResponse(
            result=result, total=1 + len(self.replicas) + len(failed),
            successful=1 + len(self.replicas), failed=failed)

    def _fail_replica(self, allocation_id: str, error: Exception) -> None:
        """Demote a failed copy (ReplicationOperation.java:181 →
        shard-failed to the master; here: drop from the group)."""
        self.replicas.pop(allocation_id, None)
        self.tracker.remove_allocation(allocation_id)
        if self.on_replica_failure:
            self.on_replica_failure(allocation_id, error)

    # -- group management / recovery ----------------------------------------

    def add_replica(self, replica: ReplicaShard) -> None:
        """Peer-recover a new/stale copy into the in-sync set
        (RecoverySourceHandler.recoverToTarget :149)."""
        aid = replica.allocation_id
        self.tracker.init_tracking(aid)
        lease_floor = replica.local_checkpoint + 1
        self.tracker.add_lease(f"peer_recovery/{aid}", max(lease_floor, 0),
                               source="peer recovery")
        channel = ReplicaChannel(replica)

        ops = self.engine.translog.read_ops(
            from_seq_no=replica.local_checkpoint + 1)
        covered = self._history_covers(replica.local_checkpoint + 1, ops)
        if not covered:
            # phase1: file-based — ship the primary's store wholesale,
            # then replay what the new commit point doesn't contain.
            # Re-opens the engine IN PLACE: the caller's ReplicaShard
            # stays the live object (it may later be promoted)
            self._file_based_restart(replica)
            ops = self.engine.translog.read_ops(
                from_seq_no=replica.local_checkpoint + 1)

        # phase2: ops-based replay from the translog
        for op in ops:
            channel.translog_op(self.engine.primary_term, op)

        # the copy is caught up to everything the primary had when we
        # snapshotted; ops indexed meanwhile arrive via the live fan-out
        # (which starts now) — matching the reference's "finalize" step
        self.replicas[aid] = channel
        self.tracker.mark_in_sync(aid, replica.local_checkpoint)
        self.tracker.remove_lease(f"peer_recovery/{aid}")

    def _history_covers(self, from_seq_no: int,
                        ops: List[TranslogOp]) -> bool:
        """True if retained translog history contains every op in
        [from_seq_no, max_seq_no] (no gaps below what we must replay)."""
        need_from = from_seq_no
        have = {op.seq_no for op in ops}
        for s in range(need_from, self.engine.tracker.max_seq_no + 1):
            if s not in have:
                return False
        return True

    def _file_based_restart(self, replica: ReplicaShard) -> None:
        """Replace the replica's store with a copy of the primary's and
        re-open its engine in place (phase1 file sync)."""
        self.engine.flush()
        replica_path = replica.engine.path
        mapper = replica.engine.mapper
        replica.engine.close()
        store_src = self.engine.store_dir
        store_dst = os.path.join(replica_path, "store")
        translog_dst = os.path.join(replica_path, "translog")
        shutil.rmtree(store_dst, ignore_errors=True)
        shutil.rmtree(translog_dst, ignore_errors=True)
        shutil.copytree(store_src, store_dst)
        replica.engine = Engine(replica_path, mapper,
                                primary_term=self.engine.primary_term)

    # -- checkpoints ---------------------------------------------------------

    @property
    def global_checkpoint(self) -> int:
        return self.tracker.global_checkpoint

    def sync_global_checkpoint(self) -> None:
        """Background GCP sync (the reference's
        ``GlobalCheckpointSyncAction``) — piggybacking covers the common
        case; this pushes after quiet periods. Goes through the channel
        seam so an RPC-backed channel works identically."""
        for aid, ch in list(self.replicas.items()):
            try:
                ch.sync_gcp(self.tracker.global_checkpoint)
            except Exception as e:   # noqa: BLE001
                self._fail_replica(aid, e)


def promote_to_primary(replica: ReplicaShard,
                       new_primary_term: int) -> PrimaryShardGroup:
    """Replica → primary promotion (the reference's
    ``IndexShard.updateShardState`` on a promotion cluster-state delta):
    bump the primary term, fill checkpoint gaps with no-ops so the local
    checkpoint catches up to max_seq_no, and activate primary mode."""
    engine = replica.engine
    if new_primary_term <= engine.primary_term:
        raise IllegalArgumentError(
            f"promotion term [{new_primary_term}] must exceed "
            f"[{engine.primary_term}]")
    engine.primary_term = new_primary_term
    # fill gaps: ops the old primary acked to us may skip seq-nos it
    # assigned to writes that never reached this copy
    # (IndexShard.fillSeqNoGaps)
    for s in range(engine.tracker.checkpoint + 1,
                   engine.tracker.max_seq_no + 1):
        engine.noop(s, reason="primary promotion gap fill")
    return PrimaryShardGroup(replica.allocation_id, engine)
