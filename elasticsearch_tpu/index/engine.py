"""InternalEngine: versioned CAS writes, NRT refresh, flush/commit, recovery.

Re-design of the reference engine
(``index/engine/Engine.java:106``, ``InternalEngine.java:123``): wraps the
in-memory indexing buffer (``SegmentBuilder``) + immutable device segments
in place of Lucene's ``IndexWriter``, with:

- a ``LiveVersionMap`` equivalent for versioned compare-and-swap indexing
  (internal versioning, ``if_seq_no``/``if_primary_term`` CAS, version
  conflicts — reference: ``LiveVersionMap.java`` + ``VersionConflictEngine-
  Exception``),
- sequence-number assignment through ``LocalCheckpointTracker``,
- a fsynced translog for durability and restart replay (``translog.py``),
- NRT refresh: freezing the buffer into a device segment makes it visible to
  searches (reference: dual ``ReaderManager`` refresh),
- flush/commit: segment *documents* persist to the store directory (gzip
  JSON; postings are rebuilt device-side on load — the device arrays are
  derived state), then the translog is rolled and trimmed,
- delete tombstones kept in the version map for out-of-order replica ops,
- a tiered-ish merge policy collapsing small/tombstone-heavy segments
  (reference: ``EsTieredMergePolicy.java:35``).
"""

from __future__ import annotations

import gzip
import json
import os
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..common.errors import DocumentMissingError, VersionConflictError
from .mapping import MapperService
from .segment import Segment, SegmentBuilder
from .seqno import LocalCheckpointTracker, NO_OPS_PERFORMED
from .store import (apply_liveness_sidecar, load_segment, merge_segments,
                    save_liveness, save_segment, segment_file_names)
from .translog import (OP_DELETE, OP_INDEX, OP_NOOP, Translog, TranslogOp)


@dataclass(slots=True)
class VersionValue:
    version: int
    seq_no: int
    primary_term: int
    deleted: bool = False
    # location of the live copy: ("buffer", local_id) or ("segment", seg_pos,
    # local_doc); None for tombstones
    location: Optional[Tuple] = None
    source: Optional[dict] = None  # retained for realtime GET from buffer
    routing: Optional[str] = None
    ts: float = 0.0  # tombstone creation time, for gc_deletes pruning


@dataclass
class IndexResult:
    seq_no: int
    version: int
    created: bool
    doc_id: str


@dataclass
class DeleteResult:
    seq_no: int
    version: int
    found: bool
    doc_id: str


@dataclass
class GetResult:
    found: bool
    doc_id: str
    source: Optional[dict] = None
    version: Optional[int] = None
    seq_no: Optional[int] = None
    routing: Optional[str] = None


class Engine:
    """One shard's storage engine."""

    def __init__(self, path: str, mapper: MapperService,
                 primary_term: int = 1,
                 translog_durability: str = Translog.DURABILITY_REQUEST,
                 max_segments: int = 12,
                 gc_deletes_seconds: float = 60.0,
                 index_sort: Optional[List[Tuple[str, str]]] = None):
        self.path = path
        self.mapper = mapper
        #: [(field, "asc"|"desc")] — segments hold docs in this order
        #: (reference: IndexSortConfig); applied at refresh/merge via a
        #: sorted rebuild
        self.index_sort = index_sort
        self.primary_term = primary_term
        self.max_segments = max_segments
        # tombstone retention window (reference: `index.gc_deletes`)
        self.gc_deletes_seconds = gc_deletes_seconds
        self.store_dir = os.path.join(path, "store")
        os.makedirs(self.store_dir, exist_ok=True)

        self.segments: List[Segment] = []
        self._persisted_segments: Dict[str, str] = {}  # seg_id -> file name
        self._dirty_segments: set = set()  # persisted segs with changed liveness
        #: segment-located deletes awaiting the next refresh — NRT delete
        #: isolation: a delete is realtime-GET-visible immediately (version
        #: map tombstone) but search-visible only after refresh, like the
        #: reference's reader-reopen semantics (InternalEngine.delete +
        #: ReaderManager swap)
        self._pending_seg_deletes: List[Tuple[object, int]] = []
        self._next_seg_no = 0
        self.version_map: Dict[str, VersionValue] = {}
        self.tracker = LocalCheckpointTracker()
        self._buffer: SegmentBuilder = None  # type: ignore
        #: callables invoked after every refresh/merge that changed the
        #: searchable segment list (reference: ``ReferenceManager.
        #: RefreshListener``). The serving layer uses this to reconcile
        #: its plane generations — delta packs and background repacks
        #: start at refresh time instead of on the first search to
        #: notice a signature miss. Listeners must not throw.
        self.refresh_listeners: List = []
        self.stats = {"index_total": 0, "delete_total": 0, "refresh_total": 0,
                      "flush_total": 0, "merge_total": 0, "get_total": 0}
        #: optional () -> int returning the lowest seq-no that must stay in
        #: translog history (set by the replication layer's lease tracker)
        self.history_retention_provider = None

        self._recover_from_store()
        # allocate the buffer only after recovery has claimed the persisted
        # segment ids, so a fresh buffer can never collide with (and shadow)
        # a recovered segment in the commit point
        self._new_buffer()
        self.translog = Translog(os.path.join(path, "translog"),
                                 durability=translog_durability)
        self._replay_translog()

    # ------------------------------------------------------------------
    # buffer management
    # ------------------------------------------------------------------

    def _new_buffer(self) -> None:
        self._buffer = SegmentBuilder(f"_{self._next_seg_no}")
        self._next_seg_no += 1

    # ------------------------------------------------------------------
    # recovery
    # ------------------------------------------------------------------

    def _commit_point_path(self) -> str:
        return os.path.join(self.store_dir, "commit_point.json")

    def _recover_from_store(self) -> None:
        """Rebuild committed segments from persisted sources (postings are
        derived state, reconstructed by re-parsing through the mapper)."""
        try:
            with open(self._commit_point_path()) as f:
                commit = json.load(f)
        except FileNotFoundError:
            self._committed_seq_no = NO_OPS_PERFORMED
            return
        mapping = commit.get("mapping")
        if mapping:
            self.mapper.merge(mapping)
        # fast-forward to the committed checkpoint up front so the per-doc
        # seq-no accounting below is vectorized (only seq-nos ABOVE the
        # checkpoint need individual marking — persisted ops at or below it
        # are contiguous by definition of the safe commit)
        committed_ckpt = commit.get("local_checkpoint", NO_OPS_PERFORMED)
        self.tracker.fast_forward(committed_ckpt)
        for seg_file in commit["segments"]:
            if seg_file.endswith(".npz"):
                # binary columnar format: postings/doc-values load directly,
                # no re-analysis through the mapper (store.py)
                seg, versions, routing = load_segment(self.store_dir,
                                                      seg_file)
                primary_term = commit.get("primary_term", 1)
            else:
                seg, versions, routing, primary_term = \
                    self._load_legacy_segment(seg_file, commit)
            self.segments.append(seg)
            self._persisted_segments[seg.seg_id] = seg_file
            seg_no = int(seg.seg_id.lstrip("_")) if \
                seg.seg_id.lstrip("_").isdigit() else 0
            self._next_seg_no = max(self._next_seg_no, seg_no + 1)
            seq_nos = np.asarray(seg.seq_nos)
            versions_l = np.asarray(versions).tolist()
            seq_nos_l = seq_nos.tolist()
            live_l = seg.live.tolist()
            vm = self.version_map
            for local, uid in enumerate(seg.doc_uids):
                if live_l[local]:
                    vm[uid] = VersionValue(
                        version=versions_l[local],
                        seq_no=seq_nos_l[local],
                        primary_term=primary_term,
                        location=("segment", seg, local),
                        routing=routing[local])
            if seq_nos.size:
                self.tracker.advance_max_seq_no(int(seq_nos.max()))
                for s in seq_nos[seq_nos > committed_ckpt].tolist():
                    self.tracker.mark_processed(s)
        for uid, ts in commit.get("tombstones", {}).items():
            cur = self.version_map.get(uid)
            if cur is None or cur.seq_no < ts["seq_no"]:
                self.version_map[uid] = VersionValue(
                    version=ts["version"], seq_no=ts["seq_no"],
                    primary_term=ts.get("primary_term", 1), deleted=True,
                    ts=ts.get("ts", 0.0))
        self._committed_seq_no = committed_ckpt

    def _load_legacy_segment(self, seg_file: str, commit: dict):
        """Round-1 gzip-JSON segments (sources only): rebuild through the
        mapper. Kept for forward-compat of old stores; new flushes always
        write the binary format."""
        with gzip.open(os.path.join(self.store_dir, seg_file), "rt") as f:
            data = json.load(f)
        builder = SegmentBuilder(data["seg_id"])
        for uid, source, seq_no, live, routing in zip(
                data["doc_uids"], data["sources"], data["seq_nos"],
                data["live"], data["routing"]):
            parsed = self.mapper.parse_document(uid, source, routing)
            local = builder.add(parsed, seq_no)
            if not live:
                builder.deleted.add(local)
        seg = builder.build()
        # deletes flushed after the legacy file was written live only in the
        # .live.npy sidecar — without this overlay they'd resurrect here
        apply_liveness_sidecar(seg, self.store_dir)
        return (seg, data["versions"], data["routing"],
                data.get("primary_term", 1))

    def _replay_translog(self) -> None:
        """Replay ops above the commit point (reference:
        ``InternalEngine.recoverFromTranslog``)."""
        replayed = 0
        for op in self.translog.read_ops(
                from_seq_no=self._committed_seq_no + 1):
            if op.op_type == OP_INDEX:
                self._apply_index(op.doc_id, op.source, op.seq_no,
                                  op.primary_term, op.version, op.routing,
                                  add_to_translog=False)
            elif op.op_type == OP_DELETE:
                self._apply_delete(op.doc_id, op.seq_no, op.primary_term,
                                   op.version, add_to_translog=False)
            self.tracker.advance_max_seq_no(op.seq_no)
            self.tracker.mark_processed(op.seq_no)
            replayed += 1
        if replayed:
            self.refresh()

    # ------------------------------------------------------------------
    # version resolution
    # ------------------------------------------------------------------

    def _resolve_version(self, doc_id: str, if_seq_no: Optional[int],
                         if_primary_term: Optional[int]) -> VersionValue:
        current = self.version_map.get(doc_id)
        if if_seq_no is not None or if_primary_term is not None:
            cur_seq = current.seq_no if current and not current.deleted else -1
            cur_term = current.primary_term if current and not current.deleted else 0
            if cur_seq != if_seq_no or cur_term != if_primary_term:
                raise VersionConflictError(
                    f"[{doc_id}]: version conflict, required seqNo "
                    f"[{if_seq_no}], primary term [{if_primary_term}]. "
                    f"current document has seqNo [{cur_seq}] and primary "
                    f"term [{cur_term}]")
        return current

    def _remove_existing(self, current: Optional[VersionValue]) -> None:
        """Mark the previous live copy of a doc as deleted."""
        if current is None or current.deleted or current.location is None:
            return
        kind = current.location[0]
        if kind == "buffer":
            parent = current.location[1]
            self._buffer.deleted.add(parent)
            # nested children die with their buffered parent
            for c, p in self._buffer.parent_of.items():
                if p == parent:
                    self._buffer.deleted.add(c)
        else:
            _, seg, local = current.location
            # NRT isolation: queue for the next refresh instead of marking
            # the shared liveness bitmap now — the open "reader" (current
            # segment views) must not see the delete until refresh
            self._pending_seg_deletes.append((seg, local))

    # ------------------------------------------------------------------
    # index / delete / get
    # ------------------------------------------------------------------

    def index(self, doc_id: str, source: dict, *,
              routing: Optional[str] = None,
              seq_no: Optional[int] = None,
              version: Optional[int] = None,
              if_seq_no: Optional[int] = None,
              if_primary_term: Optional[int] = None,
              op_type: str = "index") -> IndexResult:
        """Index one document. ``seq_no`` is None on the primary (assigned
        here) and pre-assigned on replicas (reference:
        ``IndexShard.applyIndexOperationOnPrimary/OnReplica``
        ``index/shard/IndexShard.java:797,806``)."""
        current = self._resolve_version(doc_id, if_seq_no, if_primary_term)
        if op_type == "create" and current is not None and not current.deleted:
            raise VersionConflictError(
                f"[{doc_id}]: version conflict, document already exists "
                f"(current version [{current.version}])")
        is_replica = seq_no is not None
        if is_replica and current is not None and current.seq_no >= seq_no:
            # out-of-order replica op; already superseded — record a no-op so
            # the seq-no still reaches the checkpoint and ops-based recovery
            # (reference: InternalEngine.noOp / Translog.NoOp)
            self._note_superseded_op(seq_no, doc_id)
            return IndexResult(seq_no=seq_no, version=current.version,
                               created=False, doc_id=doc_id)
        if seq_no is None:
            seq_no = self.tracker.generate_seq_no()
        else:
            self.tracker.advance_max_seq_no(seq_no)
        if version is None:
            version = 1 if current is None or current.deleted \
                else current.version + 1
        created = current is None or current.deleted
        self._apply_index(doc_id, source, seq_no, self.primary_term, version,
                          routing, add_to_translog=True)
        self.tracker.mark_processed(seq_no)
        self.stats["index_total"] += 1
        return IndexResult(seq_no=seq_no, version=version, created=created,
                           doc_id=doc_id)

    def _prune_tombstones(self) -> int:
        """Drop tombstones past the gc_deletes window whose seq-no is fully
        accounted in the local checkpoint — beyond the window, a stale
        replica op for them can no longer be told apart anyway (reference
        semantics: `index.gc_deletes` + LiveVersionMap tombstone pruning)."""
        cutoff = time.time() - self.gc_deletes_seconds
        ckpt = self.tracker.checkpoint
        dead = [uid for uid, vv in self.version_map.items()
                if vv.deleted and vv.seq_no <= ckpt and vv.ts <= cutoff]
        for uid in dead:
            del self.version_map[uid]
        return len(dead)

    def _note_superseded_op(self, seq_no: int, doc_id: str) -> None:
        """An out-of-order replica op was skipped: the seq-no must still be
        accounted (checkpoint advance) and durably represented (translog
        no-op) or the local checkpoint would stall below it forever."""
        self.tracker.advance_max_seq_no(seq_no)
        self.translog.add(TranslogOp(OP_NOOP, seq_no, self.primary_term,
                                     doc_id=doc_id,
                                     reason="superseded by newer op"))
        self.tracker.mark_processed(seq_no)

    def _apply_index(self, doc_id, source, seq_no, primary_term, version,
                     routing, add_to_translog: bool) -> None:
        current = self.version_map.get(doc_id)
        self._remove_existing(current)
        parsed = self.mapper.parse_document(doc_id, source, routing)
        local = self._buffer.add(parsed, seq_no)
        self.version_map[doc_id] = VersionValue(
            version=version, seq_no=seq_no, primary_term=primary_term,
            location=("buffer", local), source=source, routing=routing)
        if add_to_translog:
            self.translog.add(TranslogOp(OP_INDEX, seq_no, primary_term,
                                         doc_id=doc_id, source=source,
                                         routing=routing, version=version))

    def delete(self, doc_id: str, *, seq_no: Optional[int] = None,
               version: Optional[int] = None,
               if_seq_no: Optional[int] = None,
               if_primary_term: Optional[int] = None) -> DeleteResult:
        current = self._resolve_version(doc_id, if_seq_no, if_primary_term)
        found = current is not None and not current.deleted
        is_replica = seq_no is not None
        if is_replica and current is not None and current.seq_no >= seq_no:
            self._note_superseded_op(seq_no, doc_id)
            return DeleteResult(seq_no=seq_no, version=current.version,
                                found=False, doc_id=doc_id)
        if seq_no is None:
            seq_no = self.tracker.generate_seq_no()
        else:
            self.tracker.advance_max_seq_no(seq_no)
        if version is None:
            version = (current.version + 1) if current else 1
        self._apply_delete(doc_id, seq_no, self.primary_term, version,
                           add_to_translog=True)
        self.tracker.mark_processed(seq_no)
        self.stats["delete_total"] += 1
        return DeleteResult(seq_no=seq_no, version=version, found=found,
                            doc_id=doc_id)

    def _apply_delete(self, doc_id, seq_no, primary_term, version,
                      add_to_translog: bool) -> None:
        current = self.version_map.get(doc_id)
        self._remove_existing(current)
        # tombstone retained for out-of-order replica ops
        self.version_map[doc_id] = VersionValue(
            version=version, seq_no=seq_no, primary_term=primary_term,
            deleted=True, ts=time.time())
        if add_to_translog:
            self.translog.add(TranslogOp(OP_DELETE, seq_no, primary_term,
                                         doc_id=doc_id, version=version))

    def noop(self, seq_no: int, reason: str = "") -> None:
        self.tracker.advance_max_seq_no(seq_no)
        self.translog.add(TranslogOp(OP_NOOP, seq_no, self.primary_term,
                                     reason=reason))
        self.tracker.mark_processed(seq_no)

    def get(self, doc_id: str, realtime: bool = True) -> GetResult:
        """Realtime GET (reference: ``index/get/ShardGetService.java:70`` —
        served from the version map / translog without refresh)."""
        self.stats["get_total"] += 1
        current = self.version_map.get(doc_id)
        if current is None or current.deleted:
            return GetResult(found=False, doc_id=doc_id)
        if current.source is not None:
            return GetResult(found=True, doc_id=doc_id, source=current.source,
                             version=current.version, seq_no=current.seq_no,
                             routing=current.routing)
        if current.location and current.location[0] == "segment":
            _, seg, local = current.location
            return GetResult(found=True, doc_id=doc_id,
                             source=seg.sources[local],
                             version=current.version, seq_no=current.seq_no,
                             routing=current.routing)
        return GetResult(found=False, doc_id=doc_id)

    # ------------------------------------------------------------------
    # refresh / flush / merge
    # ------------------------------------------------------------------

    def _apply_pending_deletes(self) -> bool:
        """Publish queued segment-level deletes to the liveness bitmaps —
        the refresh-time half of NRT delete isolation."""
        if not self._pending_seg_deletes:
            return False
        pending, self._pending_seg_deletes = self._pending_seg_deletes, []
        for seg, local in pending:
            seg.delete_doc(local)
            # an already-persisted segment's liveness bitmap changed: it
            # must be re-persisted at the next flush or the delete is lost
            # on restart (the persisted file still says live=True)
            if seg.seg_id in self._persisted_segments:
                self._dirty_segments.add(seg.seg_id)
        return True

    def _sorted_rebuild(self, seg: Segment) -> Segment:
        """Reorder a fully-live segment by ``index_sort`` (re-parse of the
        stored sources — index sorting is opt-in and write-time-paid, like
        the reference's sorted flush; nested docs forbid index sorting in
        the reference, so segments with nested paths pass through)."""
        if not self.index_sort or seg.n_docs <= 1 or seg.nested_paths:
            return seg
        n = seg.n_docs
        cols = []
        for field, order in self.index_sort:
            nf = seg.numeric_fields.get(field)
            col = np.full(n, np.inf)
            if nf is not None:
                # first value per doc (pairs sorted by doc)
                docs = np.asarray(nf.docs_host)
                vals = np.asarray(nf.vals_host, np.float64)
                first = np.full(n, np.inf)
                # reversed assignment keeps the FIRST pair per doc
                first[docs[::-1]] = vals[::-1]
                col = first
            if str(order) == "desc":
                col = np.where(np.isinf(col), col, -col)
            cols.append(col)
        # np.lexsort sorts by the LAST key first: insertion-order tiebreak
        # least significant, index_sort[0] most significant (last)
        order_idx = np.lexsort([np.arange(n)] + cols[::-1])
        builder = SegmentBuilder(seg.seg_id)
        for local in order_idx:
            local = int(local)
            if not seg.live[local]:
                continue                    # dead rows drop, like a merge
            uid = seg.doc_uids[local]
            vv = self.version_map.get(uid)
            parsed = self.mapper.parse_document(
                uid, seg.sources[local],
                vv.routing if vv is not None else None)
            builder.add(parsed, int(seg.seq_nos[local]))
        # both callers repoint the version map themselves (refresh by the
        # builder's buffer locals, merge by enumerating the result)
        return builder.build()

    def _notify_refresh_listeners(self) -> None:
        for fn in list(self.refresh_listeners):
            try:
                fn()
            except Exception:   # noqa: BLE001 — a broken listener must
                pass            # never fail the refresh itself

    def refresh(self) -> bool:
        """Freeze the buffer into a searchable device segment (NRT refresh;
        reference: ``InternalEngine.refresh`` dual ReaderManager swap)."""
        applied_deletes = self._apply_pending_deletes()
        if len(self._buffer) == 0:
            if applied_deletes:
                self.stats["refresh_total"] += 1
                self.maybe_merge()
                self._notify_refresh_listeners()
            return applied_deletes
        builder = self._buffer
        self._new_buffer()
        seg = builder.build()
        seg = self._sorted_rebuild(seg)
        self.segments.append(seg)
        # repoint version map entries from buffer to the new segment (by
        # the BUILDER's local ids — index sorting may have permuted the
        # segment's doc order)
        for old_local, uid in enumerate(builder.doc_uids):
            vv = self.version_map.get(uid)
            if vv and vv.location == ("buffer", old_local):
                new_local = seg.find_doc(uid)
                if new_local is None:       # deleted while buffered
                    continue
                vv.location = ("segment", seg, new_local)
                vv.source = None  # now served from segment store
        self.stats["refresh_total"] += 1
        self.maybe_merge()
        self._notify_refresh_listeners()
        return True

    def flush(self) -> None:
        """Commit: refresh, persist unpersisted segments, write commit point,
        roll + trim the translog (reference: ``InternalEngine.flush`` —
        Lucene commit + translog trim)."""
        self.refresh()
        for seg in self.segments:
            if seg.seg_id not in self._persisted_segments:
                self._persist_segment(seg)
            elif seg.seg_id in self._dirty_segments:
                # only the liveness bitmap changed: rewrite the sidecar
                # .live.npy, never the immutable segment data
                save_liveness(seg, self.store_dir)
        self._dirty_segments.clear()
        self._prune_tombstones()
        commit = {
            "segments": [self._persisted_segments[s.seg_id]
                         for s in self.segments],
            "max_seq_no": self.tracker.max_seq_no,
            "local_checkpoint": self.tracker.checkpoint,
            "primary_term": self.primary_term,
            "mapping": self.mapper.mapping_dict(),
            "timestamp": time.time(),
            # delete tombstones must survive restarts or a redelivered stale
            # replica op could resurrect a deleted doc (reference: Lucene
            # soft-delete tombstone docs kept by SoftDeletesPolicy)
            "tombstones": {
                uid: {"seq_no": vv.seq_no, "primary_term": vv.primary_term,
                      "version": vv.version, "ts": vv.ts}
                for uid, vv in self.version_map.items() if vv.deleted},
        }
        tmp = self._commit_point_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(commit, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._commit_point_path())
        self._committed_seq_no = self.tracker.checkpoint
        committed = self.tracker.checkpoint
        if self.history_retention_provider is not None:
            # retention leases (ReplicationTracker.min_retained_seq_no) pin
            # translog history for recovering copies: never trim at/above
            # the lease floor, even though the ops are committed
            committed = min(committed,
                            self.history_retention_provider() - 1)
        self.translog.mark_committed(committed)
        self.translog.rollover()
        self.translog.trim_unneeded_generations()
        # drop orphaned segment files from before merges (the .live.npy
        # sidecar of every referenced segment must survive too)
        referenced = set(commit["segments"]) | {"commit_point.json"}
        for s in self.segments:
            referenced.update(segment_file_names(s.seg_id))
        for fname in os.listdir(self.store_dir):
            if fname.startswith("seg_") and fname not in referenced:
                try:
                    os.remove(os.path.join(self.store_dir, fname))
                except OSError:
                    pass
        self.stats["flush_total"] += 1

    def _persist_segment(self, seg: Segment) -> None:
        versions = []
        for local, uid in enumerate(seg.doc_uids):
            vv = self.version_map.get(uid)
            if vv and vv.location and vv.location[0] == "segment" and \
                    vv.location[2] == local and vv.location[1] is seg:
                versions.append(vv.version)
            else:
                versions.append(1)
        routing = [self.version_map[u].routing
                   if u in self.version_map else None
                   for u in seg.doc_uids]
        # save_segment fsyncs data before the commit point references it: a
        # crash after the commit-point fsync must never find a truncated
        # segment with its ops already trimmed from the translog
        fname = save_segment(seg, self.store_dir, versions, routing)
        self._persisted_segments[seg.seg_id] = fname

    def maybe_merge(self) -> bool:
        """Tiered-ish merge: collapse the smallest segments when the segment
        count exceeds the budget, and prune tombstone-heavy segments
        (reference: ``EsTieredMergePolicy.java:35``). Merging re-parses live
        sources into a fresh segment; device postings are rebuilt."""
        self._apply_pending_deletes()       # merges rewrite liveness
        candidates = [s for s in self.segments
                      if s.n_docs and s.live_count < s.n_docs // 2]
        if len(self.segments) > self.max_segments:
            by_size = sorted(self.segments, key=lambda s: s.live_count)
            candidates = list({id(s): s for s in
                               (candidates + by_size[: len(self.segments)
                                                     - self.max_segments + 1])
                               }.values())
        if len(candidates) < 2 and not any(
                s.live_count < s.n_docs // 2 for s in candidates):
            return False
        return self._merge(candidates)

    def force_merge(self) -> bool:
        """Merge everything into one segment (``_forcemerge`` API)."""
        # a merge rewrites liveness into the new segment: publish queued
        # NRT deletes first or they'd dangle on dropped segment objects
        self._apply_pending_deletes()
        live_segments = [s for s in self.segments if s.n_docs > 0]
        if len(live_segments) <= 1 and all(
                s.live_count == s.n_docs for s in live_segments):
            return False
        merged = self._merge(list(self.segments))
        if merged:
            # the segment list was restructured below any refresh: the
            # serving layer must see it (its base planes decode hits
            # against segments that no longer exist)
            self._notify_refresh_listeners()
        return merged

    def _merge(self, to_merge: List[Segment]) -> bool:
        """Columnar merge (``store.merge_segments``): postings and doc
        values concatenate vectorized under a union vocab — documents are
        NOT re-analyzed through the mapper."""
        if not to_merge:
            return False
        merged_ids = {id(s) for s in to_merge}
        ordered = [s for s in self.segments if id(s) in merged_ids]
        new_seg = merge_segments(f"_{self._next_seg_no}", ordered)
        self._next_seg_no += 1
        rest = [s for s in self.segments if id(s) not in merged_ids]
        if new_seg is not None:
            new_seg = self._sorted_rebuild(new_seg)
            rest.append(new_seg)
            for new_local, uid in enumerate(new_seg.doc_uids):
                vv = self.version_map.get(uid)
                if vv and not vv.deleted:
                    vv.location = ("segment", new_seg, new_local)
        self.segments = rest
        for seg in to_merge:
            self._persisted_segments.pop(seg.seg_id, None)
        self.stats["merge_total"] += 1
        return True

    # ------------------------------------------------------------------
    # searchers / stats
    # ------------------------------------------------------------------

    def searchable_segments(self) -> List[Segment]:
        return list(self.segments)

    @property
    def doc_count(self) -> int:
        # queued NRT deletes are already logically dead (their version-map
        # entry is a tombstone or points at a newer copy): subtract them so
        # an updated-but-unrefreshed doc never counts twice
        pending = sum(1 for seg, local in self._pending_seg_deletes
                      if seg.live[local] and
                      (len(seg.nested_paths) == 0 or
                       seg.parent_mask[local]))
        return sum(s.live_parent_count for s in self.segments) + \
            sum(1 for i in range(self._buffer.n_docs)
                if i not in self._buffer.deleted
                and i not in self._buffer.parent_of) - pending

    @property
    def deleted_count(self) -> int:
        return sum(s.n_docs - s.live_count for s in self.segments) + \
            sum(1 for seg, local in self._pending_seg_deletes
                if seg.live[local])

    def close(self) -> None:
        self.translog.close()
