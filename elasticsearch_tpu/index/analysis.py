"""Text analysis: char filters → tokenizer → token filters → token stream.

Re-design of the reference analysis registry
(``server/.../index/analysis/AnalysisRegistry.java:57`` and the analyzer
implementations in ``modules/analysis-common/``). Analysis runs on the host at
index/query time; its output feeds the device-side postings builder
(`elasticsearch_tpu.index.segment`). Tokens carry positions (phrase queries)
and character offsets (highlighting), like Lucene token attributes.

Built-in analyzers (named like the reference's): ``standard``, ``simple``,
``whitespace``, ``keyword``, ``stop``, ``english``. Custom analyzers can be
declared per index via ``settings.analysis`` with the same JSON shape the
reference accepts.
"""

from __future__ import annotations

import re
import unicodedata
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, List, Optional, Sequence

from ..common.errors import IllegalArgumentError


@dataclass(slots=True)
class Token:
    """A single analyzed token (term text, position, char offsets)."""

    term: str
    position: int
    start_offset: int
    end_offset: int


# ---------------------------------------------------------------------------
# Tokenizers
# ---------------------------------------------------------------------------

# Unicode word tokenizer: runs of letters/digits (plus combining marks within).
# Approximates UAX#29 word segmentation used by Lucene's StandardTokenizer.
_WORD_RE = re.compile(r"[\w]+", re.UNICODE)
_LETTER_RE = re.compile(r"[^\W\d_]+", re.UNICODE)
_WHITESPACE_RE = re.compile(r"\S+")


def _regex_tokenize(text: str, pattern: re.Pattern) -> List[Token]:
    tokens = []
    for pos, m in enumerate(pattern.finditer(text)):
        tokens.append(Token(m.group(), pos, m.start(), m.end()))
    return tokens


def standard_tokenizer(text: str) -> List[Token]:
    return _regex_tokenize(text, _WORD_RE)


def letter_tokenizer(text: str) -> List[Token]:
    return _regex_tokenize(text, _LETTER_RE)


def whitespace_tokenizer(text: str) -> List[Token]:
    return _regex_tokenize(text, _WHITESPACE_RE)


def keyword_tokenizer(text: str) -> List[Token]:
    return [Token(text, 0, 0, len(text))] if text else []


def ngram_tokenizer(min_gram: int = 1, max_gram: int = 2):
    def tokenize(text: str) -> List[Token]:
        tokens = []
        pos = 0
        for start in range(len(text)):
            for n in range(min_gram, max_gram + 1):
                if start + n > len(text):
                    break
                tokens.append(Token(text[start:start + n], pos, start, start + n))
                pos += 1
        return tokens
    return tokenize


def edge_ngram_tokenizer(min_gram: int = 1, max_gram: int = 2):
    def tokenize(text: str) -> List[Token]:
        return [Token(text[:n], 0, 0, n)
                for n in range(min_gram, min(max_gram, len(text)) + 1)]
    return tokenize


TOKENIZERS: Dict[str, Callable[[str], List[Token]]] = {
    "standard": standard_tokenizer,
    "letter": letter_tokenizer,
    "whitespace": whitespace_tokenizer,
    "keyword": keyword_tokenizer,
}


# ---------------------------------------------------------------------------
# Token filters
# ---------------------------------------------------------------------------

ENGLISH_STOP_WORDS = frozenset(
    "a an and are as at be but by for if in into is it no not of on or such "
    "that the their then there these they this to was will with".split())


def lowercase_filter(tokens: List[Token]) -> List[Token]:
    for t in tokens:
        t.term = t.term.lower()
    return tokens


def asciifolding_filter(tokens: List[Token]) -> List[Token]:
    for t in tokens:
        t.term = "".join(c for c in unicodedata.normalize("NFKD", t.term)
                         if not unicodedata.combining(c))
    return tokens


def make_stop_filter(stopwords: Iterable[str] = ENGLISH_STOP_WORDS):
    stopset = frozenset(stopwords)

    def stop_filter(tokens: List[Token]) -> List[Token]:
        # Positions are preserved across removed stopwords (position gaps),
        # matching Lucene's StopFilter position-increment behaviour.
        return [t for t in tokens if t.term not in stopset]

    return stop_filter


def make_length_filter(min_len: int = 0, max_len: int = 2 ** 31 - 1):
    def length_filter(tokens):
        return [t for t in tokens if min_len <= len(t.term) <= max_len]
    return length_filter


def unique_filter(tokens: List[Token]) -> List[Token]:
    seen = set()
    out = []
    for t in tokens:
        if t.term not in seen:
            seen.add(t.term)
            out.append(t)
    return out


def _porter_stem(word: str) -> str:
    """Porter stemming algorithm (Porter 1980), english analyzer's stemmer.

    Self-contained implementation of the classic algorithm; behaviourally
    equivalent to Lucene's PorterStemFilter for ASCII words.
    """
    if len(word) <= 2:
        return word

    vowels = "aeiou"

    def is_cons(w, i):
        c = w[i]
        if c in vowels:
            return False
        if c == "y":
            return i == 0 or not is_cons(w, i - 1)
        return True

    def measure(w):
        # number of VC sequences
        m = 0
        prev_vowel = False
        for i in range(len(w)):
            cons = is_cons(w, i)
            if prev_vowel and cons:
                m += 1
            prev_vowel = not cons
        return m

    def has_vowel(w):
        return any(not is_cons(w, i) for i in range(len(w)))

    def ends_double_cons(w):
        return len(w) >= 2 and w[-1] == w[-2] and is_cons(w, len(w) - 1)

    def cvc(w):
        if len(w) < 3:
            return False
        if not (is_cons(w, len(w) - 3) and not is_cons(w, len(w) - 2)
                and is_cons(w, len(w) - 1)):
            return False
        return w[-1] not in "wxy"

    w = word

    # Step 1a
    if w.endswith("sses"):
        w = w[:-2]
    elif w.endswith("ies"):
        w = w[:-2]
    elif w.endswith("ss"):
        pass
    elif w.endswith("s"):
        w = w[:-1]

    # Step 1b
    flag = False
    if w.endswith("eed"):
        if measure(w[:-3]) > 0:
            w = w[:-1]
    elif w.endswith("ed") and has_vowel(w[:-2]):
        w = w[:-2]
        flag = True
    elif w.endswith("ing") and has_vowel(w[:-3]):
        w = w[:-3]
        flag = True
    if flag:
        if w.endswith(("at", "bl", "iz")):
            w += "e"
        elif ends_double_cons(w) and not w.endswith(("l", "s", "z")):
            w = w[:-1]
        elif measure(w) == 1 and cvc(w):
            w += "e"

    # Step 1c
    if w.endswith("y") and has_vowel(w[:-1]):
        w = w[:-1] + "i"

    # Step 2
    step2 = [("ational", "ate"), ("tional", "tion"), ("enci", "ence"),
             ("anci", "ance"), ("izer", "ize"), ("abli", "able"),
             ("alli", "al"), ("entli", "ent"), ("eli", "e"), ("ousli", "ous"),
             ("ization", "ize"), ("ation", "ate"), ("ator", "ate"),
             ("alism", "al"), ("iveness", "ive"), ("fulness", "ful"),
             ("ousness", "ous"), ("aliti", "al"), ("iviti", "ive"),
             ("biliti", "ble")]
    for suf, rep in step2:
        if w.endswith(suf):
            stem = w[: -len(suf)]
            if measure(stem) > 0:
                w = stem + rep
            break

    # Step 3
    step3 = [("icate", "ic"), ("ative", ""), ("alize", "al"), ("iciti", "ic"),
             ("ical", "ic"), ("ful", ""), ("ness", "")]
    for suf, rep in step3:
        if w.endswith(suf):
            stem = w[: -len(suf)]
            if measure(stem) > 0:
                w = stem + rep
            break

    # Step 4
    step4 = ["al", "ance", "ence", "er", "ic", "able", "ible", "ant", "ement",
             "ment", "ent", "ou", "ism", "ate", "iti", "ous", "ive", "ize"]
    for suf in sorted(step4, key=len, reverse=True):
        if w.endswith(suf):
            stem = w[: -len(suf)]
            if measure(stem) > 1:
                w = stem
            break
    else:
        if w.endswith("ion") and len(w) > 3 and w[-4] in "st" and measure(w[:-3]) > 1:
            w = w[:-3]

    # Step 5a
    if w.endswith("e"):
        stem = w[:-1]
        m = measure(stem)
        if m > 1 or (m == 1 and not cvc(stem)):
            w = stem
    # Step 5b
    if measure(w) > 1 and ends_double_cons(w) and w.endswith("l"):
        w = w[:-1]

    return w


def porter_stem_filter(tokens: List[Token]) -> List[Token]:
    for t in tokens:
        t.term = _porter_stem(t.term)
    return tokens


TOKEN_FILTERS: Dict[str, Callable[[List[Token]], List[Token]]] = {
    "lowercase": lowercase_filter,
    "asciifolding": asciifolding_filter,
    "stop": make_stop_filter(),
    "porter_stem": porter_stem_filter,
    "stemmer": porter_stem_filter,
    "unique": unique_filter,
}


# ---------------------------------------------------------------------------
# Char filters
# ---------------------------------------------------------------------------

_HTML_RE = re.compile(r"<[^>]*>")


def html_strip_char_filter(text: str) -> str:
    return _HTML_RE.sub(" ", text)


CHAR_FILTERS: Dict[str, Callable[[str], str]] = {
    "html_strip": html_strip_char_filter,
}


# ---------------------------------------------------------------------------
# Analyzer
# ---------------------------------------------------------------------------


class Analyzer:
    def __init__(self, name: str,
                 tokenizer: Callable[[str], List[Token]],
                 token_filters: Sequence[Callable[[List[Token]], List[Token]]] = (),
                 char_filters: Sequence[Callable[[str], str]] = ()):
        self.name = name
        self.tokenizer = tokenizer
        self.token_filters = list(token_filters)
        self.char_filters = list(char_filters)

    #: set on analyzers whose (tokenizer, first filter) pair is exactly
    #: (standard word segmentation, lowercase) — eligible for the native
    #: ASCII fast path, which fuses both steps in C++
    _native_fast = False

    def analyze(self, text: str) -> List[Token]:
        for cf in self.char_filters:
            text = cf(text)
        if self._native_fast:
            fast = _native_tokenize(text)
            if fast is not None:
                tokens = [Token(term, pos, s, e)
                          for pos, (term, s, e) in enumerate(fast)]
                for tf in self.token_filters[1:]:   # lowercase fused in
                    tokens = tf(tokens)
                return tokens
        tokens = self.tokenizer(text)
        for tf in self.token_filters:
            tokens = tf(tokens)
        return tokens

    def terms(self, text: str) -> List[str]:
        return [t.term for t in self.analyze(text)]


def _native_tokenize(text: str):
    """ASCII fast path via the C++ library; None → use the Python path."""
    try:
        from ..native import tokenize_ascii
    except Exception:   # noqa: BLE001 — no native package
        return None
    return tokenize_ascii(text)


def _mark_native(an: Analyzer) -> Analyzer:
    if an.tokenizer is standard_tokenizer and an.token_filters and \
            an.token_filters[0] is lowercase_filter:
        an._native_fast = True
    return an


BUILTIN_ANALYZERS: Dict[str, Analyzer] = {
    "standard": _mark_native(
        Analyzer("standard", standard_tokenizer, [lowercase_filter])),
    "simple": Analyzer("simple", letter_tokenizer, [lowercase_filter]),
    "whitespace": Analyzer("whitespace", whitespace_tokenizer),
    "keyword": Analyzer("keyword", keyword_tokenizer),
    "stop": Analyzer("stop", letter_tokenizer,
                     [lowercase_filter, make_stop_filter()]),
    "english": _mark_native(
        Analyzer("english", standard_tokenizer,
                 [lowercase_filter, make_stop_filter(),
                  porter_stem_filter])),
}


class AnalysisRegistry:
    """Per-index analyzer registry built from index settings
    (reference: ``index/analysis/AnalysisRegistry.java:57``).

    Accepts the reference's settings JSON shape::

        "analysis": {
          "char_filter":  {"my_cf": {"type": "html_strip"}},
          "filter":     {"my_stop": {"type": "stop", "stopwords": [...]}},
          "tokenizer":  {"my_ng": {"type": "ngram", "min_gram": 2, ...}},
          "analyzer":   {"my_an": {"type": "custom", "tokenizer": "standard",
                                   "filter": ["lowercase", "my_stop"]}}
        }
    """

    def __init__(self, analysis_config: Optional[dict] = None):
        self._analyzers: Dict[str, Analyzer] = dict(BUILTIN_ANALYZERS)
        config = analysis_config or {}

        custom_char_filters = dict(CHAR_FILTERS)
        for name, spec in (config.get("char_filter") or {}).items():
            custom_char_filters[name] = self._build_char_filter(name, spec)

        custom_tokenizers = dict(TOKENIZERS)
        for name, spec in (config.get("tokenizer") or {}).items():
            custom_tokenizers[name] = self._build_tokenizer(name, spec)

        custom_filters = dict(TOKEN_FILTERS)
        for name, spec in (config.get("filter") or {}).items():
            custom_filters[name] = self._build_token_filter(name, spec)

        for name, spec in (config.get("analyzer") or {}).items():
            atype = spec.get("type", "custom")
            if atype != "custom" and atype in BUILTIN_ANALYZERS:
                self._analyzers[name] = BUILTIN_ANALYZERS[atype]
                continue
            tok_name = spec.get("tokenizer", "standard")
            if tok_name not in custom_tokenizers:
                raise IllegalArgumentError(
                    f"failed to find tokenizer [{tok_name}] for analyzer [{name}]")
            filters = []
            for fname in spec.get("filter", []):
                if fname not in custom_filters:
                    raise IllegalArgumentError(
                        f"failed to find filter [{fname}] for analyzer [{name}]")
                filters.append(custom_filters[fname])
            char_filters = []
            for cfname in spec.get("char_filter", []):
                if cfname not in custom_char_filters:
                    raise IllegalArgumentError(
                        f"failed to find char_filter [{cfname}] for analyzer [{name}]")
                char_filters.append(custom_char_filters[cfname])
            self._analyzers[name] = _mark_native(
                Analyzer(name, custom_tokenizers[tok_name],
                         filters, char_filters))

    @staticmethod
    def _build_tokenizer(name: str, spec: dict):
        ttype = spec.get("type", name)
        if ttype == "ngram":
            return ngram_tokenizer(int(spec.get("min_gram", 1)),
                                   int(spec.get("max_gram", 2)))
        if ttype == "edge_ngram":
            return edge_ngram_tokenizer(int(spec.get("min_gram", 1)),
                                        int(spec.get("max_gram", 2)))
        if ttype == "pattern":
            return lambda text, _p=re.compile(spec.get("pattern", r"\W+")): [
                Token(part, i, 0, 0)
                for i, part in enumerate(p for p in _p.split(text) if p)]
        if ttype in TOKENIZERS:
            return TOKENIZERS[ttype]
        raise IllegalArgumentError(f"unknown tokenizer type [{ttype}] for [{name}]")

    @staticmethod
    def _build_token_filter(name: str, spec: dict):
        ftype = spec.get("type", name)
        if ftype == "stop":
            stopwords = spec.get("stopwords", ENGLISH_STOP_WORDS)
            if stopwords == "_english_":
                stopwords = ENGLISH_STOP_WORDS
            return make_stop_filter(stopwords)
        if ftype == "length":
            return make_length_filter(int(spec.get("min", 0)),
                                      int(spec.get("max", 2 ** 31 - 1)))
        if ftype in ("stemmer", "porter_stem"):
            return porter_stem_filter
        if ftype == "synonym":
            mapping: Dict[str, List[str]] = {}
            for rule in spec.get("synonyms", []):
                if "=>" in rule:
                    lhs, rhs = rule.split("=>")
                    targets = [s.strip() for s in rhs.split(",")]
                    for src in lhs.split(","):
                        mapping[src.strip()] = targets
                else:
                    group = [s.strip() for s in rule.split(",")]
                    for src in group:
                        mapping[src] = group

            def synonym_filter(tokens: List[Token]) -> List[Token]:
                out = []
                for t in tokens:
                    if t.term in mapping:
                        for syn in mapping[t.term]:
                            out.append(Token(syn, t.position, t.start_offset,
                                             t.end_offset))
                    else:
                        out.append(t)
                return out

            return synonym_filter
        if ftype in TOKEN_FILTERS:
            return TOKEN_FILTERS[ftype]
        raise IllegalArgumentError(f"unknown filter type [{ftype}] for [{name}]")

    @staticmethod
    def _build_char_filter(name: str, spec: dict):
        cftype = spec.get("type", name)
        if cftype == "html_strip":
            return html_strip_char_filter
        if cftype == "mapping":
            pairs = []
            for rule in spec.get("mappings", []):
                src, _, dst = rule.partition("=>")
                pairs.append((src.strip(), dst.strip()))

            def mapping_filter(text: str) -> str:
                for src, dst in pairs:
                    text = text.replace(src, dst)
                return text

            return mapping_filter
        if cftype == "pattern_replace":
            pat = re.compile(spec.get("pattern", ""))
            repl = spec.get("replacement", "")
            return lambda text: pat.sub(repl, text)
        raise IllegalArgumentError(f"unknown char_filter type [{cftype}] for [{name}]")

    def get(self, name: str) -> Analyzer:
        a = self._analyzers.get(name)
        if a is None and name == "default":
            # "analyzer": "default" aliases the index default analyzer
            # (settings `index.analysis.analyzer.default`), falling back
            # to standard (reference: AnalysisRegistry.getAnalyzer)
            a = self._analyzers.get("standard")
        if a is None:
            raise IllegalArgumentError(f"failed to find analyzer [{name}]")
        return a

    def has(self, name: str) -> bool:
        return name in self._analyzers
