"""Mappings: field types, document parsing, dynamic mapping.

Re-design of the reference mapper layer (``server/.../index/mapper/``:
``MapperService.java``, ``DocumentParser.java:52``, ``FieldMapper.java``,
``MappedFieldType.java``). A mapping is a tree of typed fields; parsing a JSON
document produces a ``ParsedDocument`` whose per-field values feed the
TPU-friendly columnar/postings builders in ``segment.py``:

- ``text``      → analyzed terms with positions     (postings → BM25 kernel)
- ``keyword``   → exact terms + ordinal doc values  (terms agg / sort)
- numerics/date/boolean → float64 doc values        (range masks / aggs / sort)
- ``dense_vector`` → fixed-dim float32 rows         (einsum kNN)

Dynamic mapping infers types from JSON values like the reference
(``DynamicFieldsBuilder``): string → text + ``.keyword`` subfield, int → long,
float → double ("float" JSON numbers map to double), bool → boolean.
"""

from __future__ import annotations

import datetime as _dt
import numbers
import re
from dataclasses import dataclass, field as dc_field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..common.errors import IllegalArgumentError, MapperParsingError
from .analysis import AnalysisRegistry, Analyzer, Token


# ---------------------------------------------------------------------------
# Field types
# ---------------------------------------------------------------------------

NUMERIC_TYPES = {"long", "integer", "short", "byte", "double", "float",
                 "half_float", "unsigned_long"}

_INT_BOUNDS = {
    "byte": (-(1 << 7), (1 << 7) - 1),
    "short": (-(1 << 15), (1 << 15) - 1),
    "integer": (-(1 << 31), (1 << 31) - 1),
    "long": (-(1 << 63), (1 << 63) - 1),
    "unsigned_long": (0, (1 << 64) - 1),
}


class MappedFieldType:
    """Base resolved field type (reference: ``MappedFieldType.java``)."""

    type_name = "object"
    has_doc_values = False
    is_searchable = True

    def __init__(self, name: str, params: Optional[dict] = None):
        self.name = name
        self.params = params or {}

    def to_mapping(self) -> dict:
        out = {"type": self.type_name}
        out.update({k: v for k, v in self.params.items() if v is not None})
        return out

    # Parse one JSON leaf value into its indexable form; may raise.
    def parse_value(self, value: Any) -> Any:
        return value


class TextFieldType(MappedFieldType):
    type_name = "text"

    def __init__(self, name: str, analyzer: Analyzer,
                 search_analyzer: Optional[Analyzer] = None,
                 params: Optional[dict] = None):
        super().__init__(name, params)
        self.analyzer = analyzer
        self.search_analyzer = search_analyzer or analyzer

    def parse_value(self, value):
        return str(value)


class KeywordFieldType(MappedFieldType):
    type_name = "keyword"
    has_doc_values = True

    def __init__(self, name: str, ignore_above: int = 2 ** 31 - 1,
                 normalize_lowercase: bool = False, params: Optional[dict] = None):
        super().__init__(name, params)
        self.ignore_above = ignore_above
        self.normalize_lowercase = normalize_lowercase

    def parse_value(self, value):
        if isinstance(value, bool):
            value = "true" if value else "false"
        s = str(value)
        if len(s) > self.ignore_above:
            return None
        return s.lower() if self.normalize_lowercase else s


class ConstantKeywordFieldType(KeywordFieldType):
    """A single value shared by every document of the index (reference:
    ``x-pack/plugin/mapper-constant-keyword/.../ConstantKeywordFieldMapper
    .java``). The value pins on the mapping or on the first document that
    supplies one; later documents must agree. Each document indexes the
    constant term (including documents that omit the field — stamped in
    ``parse_document``) so term/terms/exists/aggs ride the normal keyword
    column."""

    type_name = "constant_keyword"

    def __init__(self, name: str, params: Optional[dict] = None):
        super().__init__(name, 2 ** 31 - 1, False, params)
        self.value: Optional[str] = (None if params is None
                                     else params.get("value"))

    def parse_value(self, value):
        # query-side parsing must NOT pin: only documents set the value
        # (ConstantKeywordFieldMapper pins on parse of an indexed doc)
        return super().parse_value(value)

    def index_value(self, value):
        s = super().parse_value(value)
        if self.value is None:
            self.value = s
            self.params["value"] = s      # round-trips in the mapping
            self._pinned_dirty = True     # owning mapper re-renders
        elif s != self.value:
            raise MapperParsingError(
                f"[constant_keyword] field [{self.name}] only accepts "
                f"values that are equal to the value defined in the "
                f"mappings [{self.value}], but got [{s}]")
        return self.value


class WildcardFieldType(KeywordFieldType):
    """Wildcard-optimized keyword (reference: ``x-pack/plugin/wildcard/``
    — n-gram-accelerated there; here wildcard/regexp queries scan the
    keyword ordinal table directly, which the TPU build's term
    dictionaries keep host-side anyway, so no acceleration structure is
    needed for correctness)."""

    type_name = "wildcard"

    def __init__(self, name: str, params: Optional[dict] = None):
        super().__init__(name, int((params or {}).get(
            "ignore_above", 2 ** 31 - 1)), False, params)


_VERSION_RX = re.compile(r"^(\d+)\.(\d+)\.(\d+)(?:[-+].*)?$")


class VersionFieldType(KeywordFieldType):
    """Semver-ordered keyword (reference: ``x-pack/plugin/mapper-version/
    .../VersionStringFieldMapper.java`` encodes versions into
    order-preserving sortable bytes). Here each value indexes its keyword
    term plus a numeric order key into the paired numeric column — the
    same dual-column trick the ip type uses — so sorting is semver-
    correct while term queries and aggs stay string-shaped. Non-semver
    strings carry no order key and sort as missing (documented
    approximation of the reference's 'sorts after valid versions')."""

    type_name = "version"

    def __init__(self, name: str, params: Optional[dict] = None):
        super().__init__(name, 2 ** 31 - 1, False, params)

    #: parts cap: each of major/minor/patch packs into a 100k radix
    _RADIX = 100_000

    def sort_key(self, s: str) -> Optional[float]:
        m = _VERSION_RX.match(s)
        if m is None:
            return None
        major, minor, patch = (min(int(g), self._RADIX - 1)
                               for g in m.groups())
        pre = 0 if "-" in s else 1        # prereleases order before GA
        return float(((major * self._RADIX + minor) * self._RADIX
                      + patch) * 2 + pre)


class FlattenedFieldType(KeywordFieldType):
    """Whole-object field (reference: ``x-pack/plugin/mapper-flattened/
    .../FlattenedFieldMapper.java``): one mapped field indexes every leaf
    of a JSON object. The root field column carries every leaf value (a
    query on ``field`` matches any leaf); each dotted key path gets its
    own keyword column (``field.key``), resolved to a synthetic keyword
    type by ``MapperService.field_type`` without appearing in the
    mapping. Subclassing the keyword type lets every keyword-capable
    query/agg work on the root column unchanged (the reference's root
    type is likewise a keyword-family type)."""

    type_name = "flattened"

    def __init__(self, name: str, params: Optional[dict] = None):
        super().__init__(name, 2 ** 31 - 1, False, params)
        self.depth_limit = int((self.params or {}).get("depth_limit", 20))

    def leaves(self, value: Any):
        """Yield (dotted_path, leaf_string) pairs; '' path for the root."""
        out: List[Tuple[str, str]] = []

        def walk(prefix: str, v: Any, depth: int) -> None:
            if depth > self.depth_limit:
                raise MapperParsingError(
                    f"The provided [flattened] field [{self.name}] "
                    f"exceeds the maximum depth limit of "
                    f"[{self.depth_limit}].")
            if isinstance(v, dict):
                for k, sub in v.items():
                    walk(f"{prefix}.{k}" if prefix else str(k), sub,
                         depth + 1)
            elif isinstance(v, list):
                for sub in v:
                    walk(prefix, sub, depth)
            elif v is not None:
                if isinstance(v, bool):
                    s = "true" if v else "false"
                else:
                    s = str(v)
                out.append((prefix, s))

        walk("", value, 0)
        return out


class NumberFieldType(MappedFieldType):
    has_doc_values = True

    def __init__(self, name: str, number_type: str, params: Optional[dict] = None):
        super().__init__(name, params)
        if number_type not in NUMERIC_TYPES:
            raise IllegalArgumentError(f"unknown numeric type [{number_type}]")
        self.type_name = number_type

    def parse_value(self, value):
        if isinstance(value, bool):
            raise MapperParsingError(
                f"failed to parse field [{self.name}] of type [{self.type_name}]: "
                f"boolean value")
        try:
            if self.type_name in _INT_BOUNDS:
                if isinstance(value, int):
                    v = value
                else:
                    try:
                        v = int(value)  # exact for integer strings (no f64 loss)
                    except ValueError:
                        v = int(float(value))
                lo, hi = _INT_BOUNDS[self.type_name]
                if not (lo <= v <= hi):
                    raise MapperParsingError(
                        f"value [{value}] out of range for type [{self.type_name}]")
                return float(v)
            return float(value)
        except (TypeError, ValueError) as e:
            raise MapperParsingError(
                f"failed to parse field [{self.name}] of type "
                f"[{self.type_name}]: [{value}]") from e


_EPOCH = _dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)

_DATE_YMD_RE = re.compile(r"^\d{4}-\d{2}-\d{2}$")


#: month-abbreviation tables for locale-dependent java patterns (MMM);
#: keys are the first three letters, lowercased, dots stripped
_MONTHS_BY_LOCALE = {
    "en": {"jan": 1, "feb": 2, "mar": 3, "apr": 4, "may": 5, "jun": 6,
           "jul": 7, "aug": 8, "sep": 9, "oct": 10, "nov": 11, "dec": 12},
    "de": {"jan": 1, "feb": 2, "mär": 3, "apr": 4, "mai": 5, "jun": 6,
           "jul": 7, "aug": 8, "sep": 9, "okt": 10, "nov": 11, "dez": 12},
}


def _parse_java_pattern(s: str, pattern: str, locale: str) -> Optional[float]:
    """Parse against ONE java date pattern ("E, d MMM yyyy HH:mm:ss Z")
    with locale-dependent month names (reference: DateFormatters with a
    Locale). Returns epoch ms or None when the text doesn't fit."""
    ns = _parse_java_pattern_ns(s, pattern, locale)
    return None if ns is None else ns / 1e6


def _parse_java_pattern_ns(s: str, pattern: str,
                           locale: str) -> Optional[int]:
    """Same as :func:`_parse_java_pattern` at exact NANOS resolution
    (sub-second digits beyond 3 survive — date_nanos formats)."""
    months = _MONTHS_BY_LOCALE.get(
        (locale or "en").split("-")[0].split("_")[0],
        _MONTHS_BY_LOCALE["en"])
    groups = []         # extractor names, one per capture group

    def _tok(m):
        run = m.group(0)
        c = run[0]
        if c == "E":
            return r"[^\W\d]+\.?"
        if c == "y":
            groups.append("y" if len(run) >= 4 else "yy")
            return r"(\d{4})" if len(run) >= 4 else r"(\d{2})"
        if run == "MMM" or run == "MMMM":
            groups.append("Mname")
            return r"([^\W\d]+\.?)"
        if c == "M":
            groups.append("M")
            return r"(\d{2})" if len(run) == 2 else r"(\d{1,2})"
        if c == "d":
            groups.append("d")
            return r"(\d{2})" if len(run) == 2 else r"(\d{1,2})"
        if c in "Hh":
            groups.append("H")
            return r"(\d{2})" if len(run) == 2 else r"(\d{1,2})"
        if c == "m":
            groups.append("mi")
            return r"(\d{2})"
        if c == "s":
            groups.append("se")
            return r"(\d{2})"
        if c == "S":
            groups.append("S")
            return r"(\d{1,%d})" % len(run)
        if c == "Z" or c == "X":
            groups.append("tz")
            return r"([+-]\d{2}:?\d{2}|Z)"
        return re.escape(run)

    pat = re.sub(r"([a-zA-Z])\1*|[^a-zA-Z]+",
                 lambda m: _tok(m) if m.group(0)[0].isalpha()
                 else re.escape(m.group(0)), pattern)
    m = re.fullmatch(pat, s.strip())
    if m is None:
        return None
    vals = {"y": 1970, "M": 1, "d": 1, "H": 0, "mi": 0, "se": 0,
            "S_ns": 0, "tz_s": 0}
    for name, g in zip(groups, m.groups()):
        if name == "Mname":
            key = g.rstrip(".").lower()[:3]
            mo = months.get(key) or _MONTHS_BY_LOCALE["en"].get(key)
            if mo is None:
                return None
            vals["M"] = mo
        elif name == "tz":
            if g != "Z":
                sign = 1 if g[0] == "+" else -1
                digits = g[1:].replace(":", "")
                vals["tz_s"] = sign * (int(digits[:2]) * 3600 +
                                       int(digits[2:4]) * 60)
        elif name == "S":
            vals["S_ns"] = int(g.ljust(9, "0")[:9])
        elif name == "yy":
            # java reduced year: two digits pivot on 2000 (00-99 →
            # 2000-2099, DateTimeFormatterBuilder.appendValueReduced)
            vals["y"] = 2000 + int(g)
        else:
            vals[name] = int(g)
    try:
        d = _dt.datetime(vals["y"], vals["M"], vals["d"], vals["H"],
                         vals["mi"], vals["se"],
                         tzinfo=_dt.timezone.utc)
    except ValueError:
        return None
    delta = d - _EPOCH
    return ((delta.days * 86400 + delta.seconds - vals["tz_s"]) * 10 ** 9
            + vals["S_ns"])


_ISO_NS_RE = re.compile(
    r"(\d{4})-(\d{2})-(\d{2})[T ](\d{2}):(\d{2}):(\d{2})"
    r"(?:\.(\d{1,9}))?(Z|[+-]\d{2}:?\d{2})?")


def parse_date_nanos(value: Any, fmt: str, locale: str = "en") -> int:
    """Exact epoch-NANOS parse for date_nanos fields. float64 millis tops
    out around 200ns granularity at 2018-era epochs, so ns-resolution
    values must never round-trip through the float path (reference:
    ``DateFieldMapper.Resolution.NANOSECONDS``)."""
    if isinstance(value, numbers.Number) and not isinstance(value, bool):
        if "epoch_second" in fmt and "epoch_millis" not in fmt:
            return int(value) * 10 ** 9
        return int(value) * 10 ** 6
    s = str(value).strip()
    m = _ISO_NS_RE.fullmatch(s)
    if m:
        y, mo, d, H, Mi, S, frac, tz = m.groups()
        base = _dt.datetime(int(y), int(mo), int(d), int(H), int(Mi),
                            int(S), tzinfo=_dt.timezone.utc)
        delta = base - _EPOCH
        ns = (delta.days * 86400 + delta.seconds) * 10 ** 9
        ns += int((frac or "").ljust(9, "0") or 0)
        if tz and tz != "Z":
            sign = 1 if tz[0] == "+" else -1
            digits = tz[1:].replace(":", "")
            ns -= sign * (int(digits[:2]) * 3600 +
                          int(digits[2:4] or 0) * 60) * 10 ** 9
        return ns
    if re.fullmatch(r"-?\d+", s):
        if "epoch_second" in fmt and "epoch_millis" not in fmt:
            return int(s) * 10 ** 9
        return int(s) * 10 ** 6
    for alt in fmt.split("||"):
        if alt in ("strict_date_optional_time", "epoch_millis",
                   "epoch_second"):
            continue
        ns = _parse_java_pattern_ns(s, alt, locale)
        if ns is not None:
            return ns
    # date-math and anything else: ms-resolution fallback
    return int(round(parse_date_millis(s, fmt, locale=locale) * 1e6))


def parse_date_millis(value: Any, fmt: str = "strict_date_optional_time||epoch_millis",
                      round_up: bool = False,
                      date_math: bool = True,
                      locale: str = "en") -> float:
    """Parse a date into epoch milliseconds (UTC). Supports the reference's
    default ``strict_date_optional_time||epoch_millis`` plus
    ``epoch_second``. ``round_up`` resolves /unit date-math rounding to
    the END of the unit (gt/lte range-bound semantics)."""
    if isinstance(value, bool):
        raise MapperParsingError(f"failed to parse date [{value}]")
    if isinstance(value, numbers.Number):
        if "epoch_second" in fmt and "epoch_millis" not in fmt:
            return float(value) * 1000.0
        return float(value)
    s = str(value).strip()
    if "||" in s or s.startswith("now"):
        if not date_math:
            # date math is a QUERY-side construct; document values must
            # be concrete (nondeterministic now() would poison reindex)
            raise MapperParsingError(f"failed to parse date field [{s}]")
        return _parse_date_math(s, fmt, round_up)
    if re.fullmatch(r"-?\d+", s):
        if "epoch_second" in fmt and "epoch_millis" not in fmt:
            return float(s) * 1000.0
        if len(s) == 4 and "strict_date_optional_time" in fmt and \
                1000 <= int(s) <= 9999:
            # strict_date_optional_time accepts a bare year and comes
            # before epoch_millis in the default format list
            d = _dt.datetime(int(s), 1, 1, tzinfo=_dt.timezone.utc)
            return (d - _EPOCH).total_seconds() * 1000.0
        return float(s)
    try:
        if _DATE_YMD_RE.match(s):
            d = _dt.datetime.strptime(s, "%Y-%m-%d").replace(tzinfo=_dt.timezone.utc)
        else:
            d = _dt.datetime.fromisoformat(s)
            if d.tzinfo is None:
                d = d.replace(tzinfo=_dt.timezone.utc)
        return (d - _EPOCH).total_seconds() * 1000.0
    except ValueError as e:
        # custom java patterns (letter runs + literals), locale-aware
        for alt in fmt.split("||"):
            if alt in ("strict_date_optional_time", "epoch_millis",
                       "epoch_second"):
                continue
            ms = _parse_java_pattern(s, alt, locale)
            if ms is not None:
                return ms
        raise MapperParsingError(f"failed to parse date field [{value}]") from e


_DM_OP_RE = re.compile(r"([+\-]\d+[yMwdhHms])|(/[yMwdhHms])")


def _add_months(base: "_dt.datetime", n: int) -> "_dt.datetime":
    """Calendar month addition with day-of-month clamping (the
    reference's DateMathParser clamps to the target month's last day)."""
    import calendar
    total = base.year * 12 + (base.month - 1) + n
    year, month = total // 12, total % 12 + 1
    day = min(base.day, calendar.monthrange(year, month)[1])
    return base.replace(year=year, month=month, day=day)


def _parse_date_math(s: str, fmt: str, round_up: bool = False) -> float:
    """Date-math expressions: ``<base>||<ops>`` or ``now<ops>`` where ops
    are ±N<unit> adjustments and /<unit> floor rounding
    (``common/time/DateMathParser`` semantics)."""
    if s.startswith("now"):
        base = _dt.datetime.now(_dt.timezone.utc)
        ops = s[3:]
    else:
        base_s, _, ops = s.partition("||")
        ms = parse_date_millis(base_s, fmt)
        base = _EPOCH + _dt.timedelta(milliseconds=ms)
    pos = 0
    for m in _DM_OP_RE.finditer(ops):
        if m.start() != pos:
            raise MapperParsingError(
                f"failed to parse date field [{s}]")
        pos = m.end()
        tok = m.group(0)
        if tok.startswith("/"):
            u = tok[1]
            if u == "y":
                base = base.replace(month=1, day=1, hour=0, minute=0,
                                    second=0, microsecond=0)
            elif u == "M":
                base = base.replace(day=1, hour=0, minute=0, second=0,
                                    microsecond=0)
            elif u == "w":
                base = (base - _dt.timedelta(days=base.weekday())).replace(
                    hour=0, minute=0, second=0, microsecond=0)
            elif u == "d":
                base = base.replace(hour=0, minute=0, second=0,
                                    microsecond=0)
            elif u in ("h", "H"):
                base = base.replace(minute=0, second=0, microsecond=0)
            elif u == "m":
                base = base.replace(second=0, microsecond=0)
            elif u == "s":
                base = base.replace(microsecond=0)
            if round_up:
                # RoundUp semantics apply AT the rounding step, so later
                # ± offsets compose on top of the end-of-unit instant
                if u == "y":
                    base = base.replace(year=base.year + 1)
                elif u == "M":
                    base = _add_months(base, 1)
                else:
                    base = base + {"w": _dt.timedelta(weeks=1),
                                   "d": _dt.timedelta(days=1),
                                   "h": _dt.timedelta(hours=1),
                                   "H": _dt.timedelta(hours=1),
                                   "m": _dt.timedelta(minutes=1),
                                   "s": _dt.timedelta(seconds=1)}[u]
                base = base - _dt.timedelta(milliseconds=1)
        else:
            n = int(tok[:-1])
            u = tok[-1]
            if u == "y":
                base = _add_months(base, 12 * n)
            elif u == "M":
                base = _add_months(base, n)
            else:
                delta = {"w": _dt.timedelta(weeks=n),
                         "d": _dt.timedelta(days=n),
                         "h": _dt.timedelta(hours=n),
                         "H": _dt.timedelta(hours=n),
                         "m": _dt.timedelta(minutes=n),
                         "s": _dt.timedelta(seconds=n)}[u]
                base = base + delta
    if pos != len(ops):
        raise MapperParsingError(f"failed to parse date field [{s}]")
    return (base - _EPOCH).total_seconds() * 1000.0


def _looks_date(s: str) -> bool:
    if not (_DATE_YMD_RE.match(s) or
            re.match(r"^\d{4}-\d{2}-\d{2}[T ]\d{2}:", s)):
        return False
    try:
        parse_date_millis(s)            # detection VALIDATES by parsing
        return True
    except MapperParsingError:
        return False


def _looks_iso_datetime(s: str) -> bool:
    if not re.match(r"^\d{4}-\d{2}-\d{2}[T ]\d{2}:", s):
        return False
    try:
        parse_date_millis(s)
        return True
    except MapperParsingError:
        return False


def format_date_millis(millis: float) -> str:
    d = _EPOCH + _dt.timedelta(milliseconds=millis)
    return d.strftime("%Y-%m-%dT%H:%M:%S.") + f"{d.microsecond // 1000:03d}Z"


class DateFieldType(MappedFieldType):
    type_name = "date"
    has_doc_values = True

    def __init__(self, name: str, date_format: str = "strict_date_optional_time||epoch_millis",
                 params: Optional[dict] = None, nanos: bool = False):
        super().__init__(name, params)
        self.format = date_format
        self.locale = (params or {}).get("locale") or "en"
        self.nanos = nanos          # date_nanos resolution (sort values
                                    # serialize as epoch nanos)
        if nanos:
            # instance override: rendered mappings must say date_nanos or
            # a replicated put_mapping round-trip silently demotes the
            # field to ms resolution (cluster tier replays the RENDERED
            # mapping on every node)
            self.type_name = "date_nanos"

    #: max epoch-millis storable in a signed-64 nanosecond long
    NANOS_MAX_MS = (1 << 63) / 1e6

    def parse_value(self, value):
        ms = parse_date_millis(value, self.format, date_math=False,
                               locale=self.locale)
        if self.nanos:
            if ms < 0:
                e = MapperParsingError(
                    f"failed to parse field [{self.name}] of type "
                    f"[date_nanos]")
                e.caused_by = {
                    "type": "illegal_argument_exception",
                    "reason": f"date[{value}] is before the epoch in 1970 "
                              f"and cannot be stored in nanosecond "
                              f"resolution"}
                raise e
            if ms > self.NANOS_MAX_MS:
                e = MapperParsingError(
                    f"failed to parse field [{self.name}] of type "
                    f"[date_nanos]")
                e.caused_by = {
                    "type": "illegal_argument_exception",
                    "reason": f"date[{value}] is after 2262-04-11T23:47:"
                              f"16.854775807 and cannot be stored in "
                              f"nanosecond resolution"}
                raise e
        return ms


class TokenCountFieldType(MappedFieldType):
    """token_count (reference: TokenCountFieldMapper): stores the analyzed
    token count of its input as an integer doc value."""

    type_name = "token_count"
    has_doc_values = True

    def __init__(self, name: str, analyzer: Analyzer,
                 params: Optional[dict] = None):
        super().__init__(name, params)
        self.analyzer = analyzer
        self.doc_values = (params or {}).get("doc_values", True)

    def parse_value(self, value):
        return float(len(self.analyzer.terms(str(value))))


class BooleanFieldType(MappedFieldType):
    type_name = "boolean"
    has_doc_values = True

    def parse_value(self, value):
        if isinstance(value, bool):
            return 1.0 if value else 0.0
        if value in ("true", "True"):
            return 1.0
        if value in ("false", "False", ""):
            return 0.0
        raise MapperParsingError(f"failed to parse boolean [{value}]")


class DenseVectorFieldType(MappedFieldType):
    """Reference: ``x-pack/plugin/vectors/.../DenseVectorFieldMapper.java:43``.
    Brute-force scored via a single einsum + top_k on TPU."""

    type_name = "dense_vector"
    has_doc_values = True

    def __init__(self, name: str, dims: int, similarity: str = "cosine",
                 params: Optional[dict] = None):
        super().__init__(name, params)
        self.dims = int(dims)
        self.similarity = similarity

    def parse_value(self, value):
        arr = np.asarray(value, dtype=np.float32)
        if arr.shape != (self.dims,):
            raise MapperParsingError(
                f"the [dims] of field [{self.name}] is [{self.dims}] but found "
                f"vector of dims [{arr.shape}]")
        return arr


_GEOHASH_B32 = "0123456789bcdefghjkmnpqrstuvwxyz"
_GEOHASH_ORD = {c: i for i, c in enumerate(_GEOHASH_B32)}


def geohash_decode(h: str):
    """Geohash → (lat, lon) cell center (``Geohash.java`` semantics)."""
    lat_lo, lat_hi, lon_lo, lon_hi = -90.0, 90.0, -180.0, 180.0
    even = True
    for c in h:
        bits = _GEOHASH_ORD[c]
        for shift in range(4, -1, -1):
            bit = (bits >> shift) & 1
            if even:
                mid = (lon_lo + lon_hi) / 2
                lon_lo, lon_hi = (mid, lon_hi) if bit else (lon_lo, mid)
            else:
                mid = (lat_lo + lat_hi) / 2
                lat_lo, lat_hi = (mid, lat_hi) if bit else (lat_lo, mid)
            even = not even
    return ((lat_lo + lat_hi) / 2, (lon_lo + lon_hi) / 2)


class GeoPointFieldType(MappedFieldType):
    type_name = "geo_point"
    has_doc_values = True

    def parse_value(self, value):
        # Accept {"lat":..,"lon":..}, [lon, lat], "lat,lon", and geohash.
        try:
            if isinstance(value, dict):
                if "geohash" in value:
                    lat, lon = geohash_decode(str(value["geohash"]))
                else:
                    lat, lon = float(value["lat"]), float(value["lon"])
            elif isinstance(value, (list, tuple)):
                lon, lat = float(value[0]), float(value[1])
            elif isinstance(value, str):
                if "," in value:
                    parts = value.split(",")
                    lat, lon = float(parts[0]), float(parts[1])
                elif all(c in _GEOHASH_ORD for c in value) and value:
                    lat, lon = geohash_decode(value)
                else:
                    raise MapperParsingError(
                        f"failed to parse geo_point [{value}]")
            else:
                raise MapperParsingError(
                    f"failed to parse geo_point [{value}]")
        except (ValueError, TypeError, KeyError, IndexError):
            raise MapperParsingError(f"failed to parse geo_point [{value}]")
        if not (-90 <= lat <= 90) or not (-180 <= lon <= 180):
            raise MapperParsingError(f"geo_point out of bounds [{value}]")
        return (lat, lon)


class RankFeatureFieldType(MappedFieldType):
    """Single positive feature value for ``rank_feature`` queries
    (reference: ``mapper-extras/.../RankFeatureFieldMapper.java``).
    Stored as an ordinary numeric doc-values column — the rank_feature
    query reads it straight off the device-resident column instead of
    the reference's frequency-encoded term."""

    type_name = "rank_feature"
    has_doc_values = True

    def __init__(self, name, params=None,
                 positive_score_impact: bool = True):
        super().__init__(name, params)
        self.positive_score_impact = positive_score_impact

    def parse_value(self, value):
        try:
            v = float(value)
        except (TypeError, ValueError):
            raise MapperParsingError(
                f"failed to parse field [{self.name}] of type "
                f"[rank_feature]")
        if v <= 0:
            raise MapperParsingError(
                f"[rank_feature] fields must have a positive value, "
                f"got [{v}] for field [{self.name}]")
        return v


class RankFeaturesFieldType(MappedFieldType):
    """Sparse feature map {name: positive value}
    (``RankFeaturesFieldMapper.java``); each feature lands in its own
    ``field.feature`` numeric column."""

    type_name = "rank_features"
    has_doc_values = True

    def __init__(self, name, params=None,
                 positive_score_impact: bool = True):
        super().__init__(name, params)
        self.positive_score_impact = positive_score_impact

    def parse_value(self, value):
        if not isinstance(value, dict):
            raise MapperParsingError(
                f"[rank_features] fields must be json objects, "
                f"expected a START_OBJECT for field [{self.name}]")
        out = {}
        for feat, v in value.items():
            try:
                fv = float(v)
            except (TypeError, ValueError):
                raise MapperParsingError(
                    f"failed to parse feature [{feat}] of field "
                    f"[{self.name}]")
            if fv <= 0:
                raise MapperParsingError(
                    f"[rank_features] fields must have positive "
                    f"values, got [{fv}] for feature [{feat}]")
            out[feat] = fv
        return out


class AggregateMetricDoubleFieldType(MappedFieldType):
    """Pre-aggregated metric document (``aggregate_metric_double``,
    ``x-pack mapper: AggregateDoubleMetricFieldMapper.java``): each doc
    carries min/max/sum/value_count sub-metrics, one numeric column per
    metric; queries and sorts on the bare name resolve to
    ``default_metric``'s column."""

    type_name = "aggregate_metric_double"
    has_doc_values = True

    VALID_METRICS = ("min", "max", "sum", "value_count")

    def __init__(self, name, metrics, default_metric, params=None):
        super().__init__(name, params)
        if not metrics:
            raise MapperParsingError(
                f"Property [metrics] is required for field [{name}]")
        for m in metrics:
            if m not in self.VALID_METRICS:
                raise MapperParsingError(
                    f"Metric [{m}] is not supported for field [{name}]; "
                    f"supported metrics are "
                    f"{list(self.VALID_METRICS)}")
        if default_metric is None:
            raise MapperParsingError(
                f"Property [default_metric] is required for field "
                f"[{name}]")
        if default_metric not in metrics:
            raise MapperParsingError(
                f"Default metric [{default_metric}] is not defined in "
                f"the metrics of field [{name}]")
        self.metrics = list(metrics)
        self.default_metric = default_metric

    def parse_value(self, value):
        if not isinstance(value, dict):
            raise MapperParsingError(
                f"Failed to parse object: expecting an object for "
                f"field [{self.name}]")
        out = {}
        for m in self.metrics:
            if m not in value:
                raise MapperParsingError(
                    f"Aggregate metric field [{self.name}] must "
                    f"contain all metrics {self.metrics}")
            try:
                out[m] = float(value[m])
            except (TypeError, ValueError):
                raise MapperParsingError(
                    f"failed to parse metric [{m}] of field "
                    f"[{self.name}]")
        if "value_count" in out and out["value_count"] < 0:
            raise MapperParsingError(
                f"Aggregate metric [value_count] of field "
                f"[{self.name}] cannot be a negative number")
        return out


class GeoShapeFieldType(MappedFieldType):
    """Arbitrary geometries (``geo_shape``; reference:
    ``x-pack/plugin/spatial/`` + ``GeoShapeFieldMapper.java``).
    The geometry is validated at parse time and kept in _source; the
    geo_shape query evaluates relations against parsed geometries with
    a per-segment cache (search/geometry.py), and the indexed bbox
    columns (``._minx`` …) give exists/pre-filter columns — vs the
    reference's triangulated BKD encoding."""

    type_name = "geo_shape"
    has_doc_values = True

    def parse_value(self, value):
        from ..search.geometry import parse_geometry
        try:
            geom = parse_geometry(value)
        except Exception as e:
            raise MapperParsingError(
                f"failed to parse field [{self.name}] of type "
                f"[geo_shape]: {e}")
        if geom.empty:
            raise MapperParsingError(
                f"failed to parse field [{self.name}] of type "
                f"[geo_shape]: empty geometry")
        return geom


class IpFieldType(MappedFieldType):
    """IP addresses (reference: ``index/mapper/IpFieldMapper.java``).
    Stored dual: the numeric value (for range/CIDR masks on device) and
    the normalized string as a keyword term (exact term matches). IPv4 is
    exact; IPv6 numeric comparisons carry f64 (2^53) precision — range
    endpoints beyond that resolve to the nearest representable value
    (documented deviation; the reference compares 128-bit points)."""

    type_name = "ip"
    has_doc_values = True

    def parse_value(self, value):
        import ipaddress
        try:
            ip = ipaddress.ip_address(str(value))
        except ValueError as e:
            raise MapperParsingError(f"'{value}' is not an IP string "
                                     f"literal.") from e
        return str(ip), float(int(ip))

    @staticmethod
    def cidr_bounds(value: str):
        """'a.b.c.d/n' → (lo_int, hi_int) or None when not a CIDR."""
        import ipaddress
        if "/" not in str(value):
            return None
        net = ipaddress.ip_network(str(value), strict=False)
        return float(int(net.network_address)), \
            float(int(net.broadcast_address))


RANGE_TYPES = {"integer_range", "long_range", "float_range",
               "double_range", "date_range", "ip_range"}


class RangeFieldType(MappedFieldType):
    """Range fields (reference: ``index/mapper/RangeFieldMapper.java``):
    each value is an interval stored as two numeric columns
    ``<field>._gte`` / ``<field>._lte`` (bounds normalized to closed);
    queries compare interval endpoints under a relation
    (intersects/contains/within)."""

    type_name = "range"

    def __init__(self, name: str, range_kind: str, params: dict):
        super().__init__(name, params)
        self.range_kind = range_kind
        self.type_name = range_kind

    def _point(self, v, round_up: bool = False):
        try:
            if self.range_kind == "date_range":
                return float(parse_date_millis(v, round_up=round_up))
            if self.range_kind == "ip_range":
                import ipaddress
                return float(int(ipaddress.ip_address(str(v))))
            return float(v)
        except (ValueError, TypeError) as e:
            raise MapperParsingError(
                f"failed to parse [{self.range_kind}] bound [{v}] for "
                f"field [{self.name}]") from e

    def parse_value(self, value):
        if not isinstance(value, dict):
            raise MapperParsingError(
                f"range field [{self.name}] expects an object with "
                f"gte/gt/lte/lt bounds")
        integral = self.range_kind in ("integer_range", "long_range",
                                       "date_range", "ip_range")
        lo = value.get("gte")
        if lo is None and value.get("gt") is not None:
            p = self._point(value["gt"])
            lo = p + 1 if integral else float(np.nextafter(p, np.inf))
        elif lo is not None:
            lo = self._point(lo)
        hi = value.get("lte")
        if hi is None and value.get("lt") is not None:
            p = self._point(value["lt"])
            hi = p - 1 if integral else float(np.nextafter(p, -np.inf))
        elif hi is not None:
            hi = self._point(hi)
        if lo is None:
            lo = -1.7e308
        if hi is None:
            hi = 1.7e308
        return float(lo), float(hi)


class SearchAsYouTypeFieldType(TextFieldType):
    """search_as_you_type: the base text field plus an ``._index_prefix``
    sibling holding edge n-grams (2..max_prefix_chars) of every analyzed
    term, so as-you-type prefixes match postings without wildcard scans
    (the reference adds shingle subfields too; prefix covers the hot
    match_bool_prefix path)."""

    type_name = "search_as_you_type"
    MAX_PREFIX = 10

    def __init__(self, name, analyzer, params):
        super().__init__(name, analyzer, None, params)


class PrefixSubFieldType(TextFieldType):
    """The ``._index_prefix`` sibling of a search_as_you_type field —
    queryable like text, but its postings are written by the parent's
    prefix-gram branch, never by the generic multi-field loop."""

    type_name = "text"


class RuntimeFieldType(MappedFieldType):
    """Runtime fields (reference: ``index/mapper/RuntimeField.java`` —
    script-computed at query time, no index structures). The script is a
    restricted expression (``utils/expressions.py``) over the document's
    numeric doc-value columns; the column materializes lazily per segment
    as one vectorized evaluation and caches — usable in sort, range
    queries, and numeric aggregations."""

    type_name = "runtime"
    has_doc_values = True

    def __init__(self, name: str, runtime_kind: str, script_source: str,
                 params: dict):
        super().__init__(name, params)
        self.runtime_kind = runtime_kind
        self.script_source = script_source

    def column(self, seg) -> np.ndarray:
        """float64[n_pad] computed column (NaN where any input is
        missing), cached on the segment."""
        key = f"__rt__{self.name}"
        col = seg._fv_columns.get(key)
        if col is None:
            import ast as _ast
            from ..utils.expressions import (compile_expression,
                                             evaluate_expression_vec)
            tree = compile_expression(self.script_source)
            names = {n.id for n in _ast.walk(tree)
                     if isinstance(n, _ast.Name)}
            env = {}
            for nm in names:
                try:
                    env[nm] = seg.numeric_first_value_column(nm)
                except Exception:       # noqa: BLE001 — math fn names etc.
                    continue
            col = np.asarray(
                evaluate_expression_vec(self.script_source, env),
                dtype=np.float64)
            if col.shape == ():          # constant expression
                col = np.full(seg.n_pad, float(col))
            seg._fv_columns[key] = col
        return col


class CompletionFieldType(MappedFieldType):
    """Auto-complete inputs (reference:
    ``search/suggest/completion/CompletionFieldMapper.java``). Inputs are
    stored as keyword terms on the field itself and the per-doc suggestion
    weight as a hidden ``<field>._weight`` numeric column — the FST the
    reference builds is replaced by prefix scans of the keyword ordinal
    table (``search/suggest.py``). Weight is per document (the reference
    allows per-input weights; documented simplification)."""

    type_name = "completion"

    def __init__(self, name: str, params: Optional[dict] = None):
        super().__init__(name, params)
        ctxs = (params or {}).get("contexts") or []
        if isinstance(ctxs, dict):
            ctxs = [ctxs]
        self.contexts = ctxs        # [{name, type, path?, precision?}]

    def parse_value(self, value):
        """→ (inputs, weight, contexts_dict)."""
        if isinstance(value, str):
            inputs, weight, ctxs = [value], 1, {}
        elif isinstance(value, list) and any(
                isinstance(v, dict) for v in value):
            # array of {input, weight} entries — inputs merge; the
            # per-doc weight column keeps the FIRST entry's weight
            # (per-input weights are a documented simplification)
            inputs, weight, ctxs = [], None, {}
            for v in value:
                i2, w2, c2 = self.parse_value(v)
                inputs.extend(i2)
                if weight is None:
                    weight = w2
                for ck, cv in c2.items():
                    ctxs.setdefault(ck, cv)
            weight = 1 if weight is None else weight
        elif isinstance(value, list):
            inputs, weight, ctxs = [str(v) for v in value], 1, {}
        elif isinstance(value, dict):
            inputs = value.get("input", [])
            if isinstance(inputs, str):
                inputs = [inputs]
            inputs = [str(v) for v in inputs]
            weight = int(value.get("weight", 1))
            ctxs = value.get("contexts") or {}
        else:
            raise MapperParsingError(
                f"failed to parse completion input [{value}]")
        if self.contexts and not ctxs and not any(
                c.get("path") for c in self.contexts):
            raise MapperParsingError(
                f"Contexts are mandatory in context enabled "
                f"completion field [{self.name}]")
        return inputs, weight, ctxs

    def context_tokens(self, ctxs: dict, source: dict) -> dict:
        """context name → list of stored tokens (geo → geohash12)."""
        out = {}
        for cdef in self.contexts:
            cname = cdef.get("name")
            ctype = cdef.get("type", "category")
            vals = ctxs.get(cname)
            if vals is None and cdef.get("path"):
                cur = source
                for part in str(cdef["path"]).split("."):
                    cur = cur.get(part) if isinstance(cur, dict) else None
                vals = cur
            if vals is None:
                continue
            if not isinstance(vals, list):
                vals = [vals]
            toks = []
            for v in vals:
                if ctype == "geo":
                    lat, lon = GeoPointFieldType(cname).parse_value(v)
                    toks.append(geohash_encode_12(lat, lon))
                else:
                    toks.append(str(v))
            out[cname] = toks
        return out


def geohash_encode(lat: float, lon: float, precision: int) -> str:
    """Geohash encoding (Geohash.java bit interleaving)."""
    lat_lo, lat_hi, lon_lo, lon_hi = -90.0, 90.0, -180.0, 180.0
    out, bits, n, even = [], 0, 0, True
    while len(out) < precision:
        if even:
            mid = (lon_lo + lon_hi) / 2
            if lon >= mid:
                bits = (bits << 1) | 1
                lon_lo = mid
            else:
                bits <<= 1
                lon_hi = mid
        else:
            mid = (lat_lo + lat_hi) / 2
            if lat >= mid:
                bits = (bits << 1) | 1
                lat_lo = mid
            else:
                bits <<= 1
                lat_hi = mid
        even = not even
        n += 1
        if n == 5:
            out.append(_GEOHASH_B32[bits])
            bits = n = 0
    return "".join(out)


def geohash_encode_12(lat: float, lon: float) -> str:
    """12-char geohash (max context precision; queries prefix-match)."""
    return geohash_encode(lat, lon, 12)


class JoinFieldType(MappedFieldType):
    """Parent/child relations inside one index (reference:
    ``modules/parent-join/.../ParentJoinFieldMapper.java``). A doc's
    value is ``"parent"`` or ``{"name": "child", "parent": "<id>"}``;
    storage is the reference's own trick: the relation NAME is a keyword
    at the field, and the parent id a keyword at ``<field>#<parent>`` —
    parents store their OWN id there, so has_parent/has_child/children
    all work off one column."""

    type_name = "join"

    def __init__(self, name: str, relations: dict, params: dict):
        super().__init__(name, params)
        self.relations_raw = dict(relations or {})
        self.relations: Dict[str, List[str]] = {}
        for parent, kids in self.relations_raw.items():
            self.relations[parent] = [kids] if isinstance(kids, str) \
                else list(kids)

    def parent_rel_of(self, name: str) -> Optional[str]:
        """The parent relation a NAME belongs under (None for roots)."""
        for parent, kids in self.relations.items():
            if name in kids:
                return parent
        return None

    def all_names(self) -> List[str]:
        out = list(self.relations)
        for kids in self.relations.values():
            out.extend(kids)
        return out

    def id_field_for(self, rel_name: str) -> str:
        """Column carrying the family id for docs of ``rel_name``."""
        parent = self.parent_rel_of(rel_name) or rel_name
        return f"{self.name}#{parent}"

    def to_mapping(self) -> dict:
        return {"type": "join", "eager_global_ordinals": True,
                "relations": self.relations_raw}


class PercolatorFieldType(MappedFieldType):
    """Stored-query field (reference:
    ``modules/percolator/PercolatorFieldMapper.java:93``). The query
    spec lives in _source; match-time the percolate query runs each
    stored query against an in-memory segment built from the candidate
    document. (The reference extracts candidate terms to prune which
    stored queries run; this build evaluates all of them — exact, and
    the per-query cost is one tiny-segment execution.)"""

    type_name = "percolator"

    def to_mapping(self) -> dict:
        return {"type": "percolator"}


class BinaryFieldType(MappedFieldType):
    """Base64 blobs (reference: ``BinaryFieldMapper``): stored in _source,
    neither indexed nor doc-valued — exists queries consult the source."""

    type_name = "binary"
    is_searchable = False

    def parse_value(self, value):
        import base64
        try:
            base64.b64decode(str(value), validate=True)
        except Exception as e:
            raise MapperParsingError(
                f"failed to parse field [{self.name}] of type [binary]"
            ) from e
        return str(value)


class AliasFieldType(MappedFieldType):
    """Field alias (reference: ``FieldAliasMapper``): queries and aggs on
    the alias resolve to the target path; documents never write to it."""

    type_name = "alias"

    def __init__(self, name: str, path: str, params: dict):
        super().__init__(name, params)
        self.path = path


class ObjectFieldType(MappedFieldType):
    type_name = "object"
    is_searchable = False


class NestedFieldType(ObjectFieldType):
    """Nested objects as block-joined hidden child documents (reference:
    ``index/mapper/NestedObjectMapper.java`` + Lucene block join): each
    nested value becomes its own document indexed immediately BEFORE its
    parent, carrying the ``path.field`` leaf values; the segment stores a
    parent bitmask and child→parent pointers, and ``nested`` queries join
    child matches back to parents (``search/query_dsl.py NestedQuery``).
    Cross-object match leakage — the flattened v1 gap — is gone: each
    child matches independently."""

    type_name = "nested"


# ---------------------------------------------------------------------------
# Parsed document
# ---------------------------------------------------------------------------


@dataclass
class ParsedDocument:
    """Output of document parsing, consumed by the segment writer
    (analogue of ``ParsedDocument.java`` wrapping LuceneDocument)."""

    doc_id: str
    source: dict
    routing: Optional[str] = None
    # field name -> list of analyzed tokens (text fields)
    text_tokens: Dict[str, List[Token]] = dc_field(default_factory=dict)
    # field name -> list of exact terms (keyword fields)
    keyword_terms: Dict[str, List[str]] = dc_field(default_factory=dict)
    # field name -> list of float64 values (numeric/date/boolean)
    numeric_values: Dict[str, List[float]] = dc_field(default_factory=dict)
    # field name -> exact epoch-nanos longs (date_nanos only: float64
    # cannot hold ns-resolution epochs)
    int64_values: Dict[str, List[int]] = dc_field(default_factory=dict)
    # field name -> float32 vector
    vectors: Dict[str, np.ndarray] = dc_field(default_factory=dict)
    # field name -> list of (lat, lon)
    geo_points: Dict[str, List[Tuple[float, float]]] = dc_field(default_factory=dict)
    # dynamic mapping updates discovered while parsing (to merge into mapping)
    dynamic_updates: Dict[str, dict] = dc_field(default_factory=dict)
    # (nested path, child ParsedDocument) — block-joined hidden children,
    # indexed immediately before this parent (NestedFieldType)
    nested_docs: List[Tuple[str, "ParsedDocument"]] = \
        dc_field(default_factory=list)

    def field_names(self) -> List[str]:
        names = set()
        for d in (self.text_tokens, self.keyword_terms, self.numeric_values,
                  self.vectors, self.geo_points):
            names.update(k for k, v in d.items() if len(v) > 0)
        return sorted(names)


# ---------------------------------------------------------------------------
# MapperService
# ---------------------------------------------------------------------------


def resolve_field_patterns(mapper, pattern: str,
                           types: Optional[tuple] = None) -> List[str]:
    """Expand a ``*``-pattern over a mapper's concrete fields (the
    reference's ``QueryParserHelper.resolveMappingFields``); ``types``
    optionally restricts to specific MappedFieldType classes."""
    import fnmatch
    out = []
    for name, ft in getattr(mapper, "_fields", {}).items():
        if not fnmatch.fnmatchcase(name, pattern):
            continue
        if types is not None and not isinstance(ft, types):
            continue
        out.append(name)
    return out


class MapperService:
    """Holds the resolved mapping for one index and parses documents
    (reference: ``MapperService.java`` + ``DocumentParser.java:52``).

    ``mappings`` is the ES JSON shape: ``{"properties": {...}}``, optional
    ``"dynamic"``: true (default) / false / "strict", optional ``"_source"``:
    ``{"enabled": bool}``.
    """

    def __init__(self, mappings: Optional[dict] = None,
                 analysis_registry: Optional[AnalysisRegistry] = None):
        self.analysis = analysis_registry or AnalysisRegistry()
        self._fields: Dict[str, MappedFieldType] = {}
        #: fields whose column data a sort/agg has materialized — the
        #: fielddata stats accounting (lazily loaded, like Lucene)
        self.fielddata_loaded: set = set()
        #: index.mapping.nested_objects.limit (set by the index service)
        self.nested_limit = 10000
        self._mapping_def: dict = {"properties": {}}
        self.dynamic: Any = True
        self.source_enabled = True
        self.runtime_defs: Dict[str, dict] = {}
        if mappings:
            self.merge(mappings)

    # -- mapping management --------------------------------------------------

    def merge(self, mappings: dict) -> None:
        if not isinstance(mappings, dict):
            raise MapperParsingError("mapping must be an object")
        if "_doc" in mappings:
            raise IllegalArgumentError(
                "Types cannot be provided in put mapping requests")
        if "dynamic" in mappings:
            self.dynamic = mappings["dynamic"]
        if "_source" in mappings:
            self.source_enabled = bool(mappings["_source"].get("enabled", True))
        for name, spec in (mappings.get("runtime") or {}).items():
            script = (spec.get("script") or {})
            src = script.get("source") if isinstance(script, dict) \
                else str(script)
            if not src:
                raise MapperParsingError(
                    f"runtime field [{name}] requires a script")
            self._fields[name] = RuntimeFieldType(
                name, spec.get("type", "double"), src, {})
            self.runtime_defs[name] = spec
        props = mappings.get("properties", {})
        self._merge_properties("", props)
        self._rebuild_mapping_def()

    def _merge_properties(self, prefix: str, props: dict) -> None:
        for name, spec in props.items():
            if name == "":
                # reference: ObjectMapper.TypeParser rejects empty names
                # with an IllegalArgumentException
                raise IllegalArgumentError(
                    "field name cannot be an empty string")
            if not isinstance(spec, dict):
                raise MapperParsingError(f"invalid mapping for field [{name}]")
            full = f"{prefix}{name}"
            ftype = spec.get("type")
            if ftype is None and "properties" in spec:
                ftype = "object"
            if ftype is None:
                raise MapperParsingError(f"no type specified for field [{full}]")
            existing = self._fields.get(full)
            if existing is not None and existing.type_name != ftype and not (
                    ftype == "object" and
                    existing.type_name in ("object", "nested")):
                raise IllegalArgumentError(
                    f"mapper [{full}] cannot be changed from type "
                    f"[{existing.type_name}] to [{ftype}]")
            if ftype == "object" or ftype == "nested":
                if ftype == "nested" or not isinstance(
                        existing, NestedFieldType):
                    # dynamic "object" updates never demote a nested
                    # field; nested params (include_in_parent/root)
                    # survive into the rendered mapping
                    extra = {k: v for k, v in spec.items()
                             if k not in ("type", "properties")}
                    self._fields[full] = (
                        NestedFieldType(full, extra)
                        if ftype == "nested"
                        else ObjectFieldType(full, {"type": ftype}))
                self._merge_properties(f"{full}.", spec.get("properties", {}))
                continue
            self._fields[full] = self._build_field(full, ftype, spec)
            # multi-fields: "fields": {"raw": {"type": "keyword"}}
            for sub, subspec in (spec.get("fields") or {}).items():
                subfull = f"{full}.{sub}"
                self._fields[subfull] = self._build_field(
                    subfull, subspec.get("type", "keyword"), subspec)

    def _build_field(self, name: str, ftype: str, spec: dict) -> MappedFieldType:
        params = {k: v for k, v in spec.items()
                  if k not in ("type", "properties", "fields")}
        if ftype == "text":
            analyzer = self.analysis.get(spec.get("analyzer", "standard"))
            search_analyzer = (self.analysis.get(spec["search_analyzer"])
                               if "search_analyzer" in spec else None)
            return TextFieldType(name, analyzer, search_analyzer, params)
        if ftype == "keyword":
            return KeywordFieldType(
                name, int(spec.get("ignore_above", 2 ** 31 - 1)),
                spec.get("normalizer") == "lowercase", params)
        if ftype == "constant_keyword":
            return ConstantKeywordFieldType(name, params)
        if ftype == "wildcard":
            return WildcardFieldType(name, params)
        if ftype == "version":
            return VersionFieldType(name, params)
        if ftype == "flattened":
            return FlattenedFieldType(name, params)
        if ftype in NUMERIC_TYPES:
            return NumberFieldType(name, ftype, params)
        if ftype in ("date", "date_nanos"):
            # date_nanos maps onto the millisecond date column with the
            # sub-ms remainder kept in the float fraction (the reference
            # stores nanos in a long)
            return DateFieldType(
                name, spec.get("format", "strict_date_optional_time||epoch_millis"),
                params, nanos=(ftype == "date_nanos"))
        if ftype == "token_count":
            an = self.analysis.get(spec.get("analyzer", "standard"))
            return TokenCountFieldType(name, an, params)
        if ftype == "boolean":
            return BooleanFieldType(name, params)
        if ftype == "dense_vector":
            if "dims" not in spec:
                raise MapperParsingError(
                    f"Missing required parameter [dims] for field [{name}]")
            return DenseVectorFieldType(name, spec["dims"],
                                        spec.get("similarity", "cosine"), params)
        if ftype == "geo_point":
            return GeoPointFieldType(name, params)
        if ftype == "geo_shape":
            return GeoShapeFieldType(name, params)
        if ftype == "rank_feature":
            return RankFeatureFieldType(
                name, params,
                positive_score_impact=spec.get(
                    "positive_score_impact", True))
        if ftype == "rank_features":
            return RankFeaturesFieldType(
                name, params,
                positive_score_impact=spec.get(
                    "positive_score_impact", True))
        if ftype == "aggregate_metric_double":
            return AggregateMetricDoubleFieldType(
                name, spec.get("metrics"), spec.get("default_metric"),
                params)
        if ftype == "completion":
            return CompletionFieldType(name, params)
        if ftype == "ip":
            return IpFieldType(name, params)
        if ftype == "binary":
            return BinaryFieldType(name, params)
        if ftype == "alias":
            if "path" not in spec:
                raise MapperParsingError(
                    f"Field [{name}] of type [alias] must have a [path]")
            return AliasFieldType(name, spec["path"], params)
        if ftype == "join":
            jf = JoinFieldType(name, spec.get("relations") or {}, params)
            # the family-id columns exist per parent relation
            for parent in jf.relations:
                self._fields[f"{name}#{parent}"] = KeywordFieldType(
                    f"{name}#{parent}", 2 ** 31 - 1, False, {})
            return jf
        if ftype == "percolator":
            return PercolatorFieldType(name, params)
        if ftype in RANGE_TYPES:
            return RangeFieldType(name, ftype, params)
        if ftype == "search_as_you_type":
            # reference: SearchAsYouTypeFieldMapper — a text field plus
            # prefix-acceleration subfields; here the main field is text
            # and ._index_prefix stores edge n-grams of every term so
            # prefix/bool-prefix matches hit the postings directly
            analyzer = self.analysis.get(spec.get("analyzer", "standard"))
            self._fields[f"{name}._index_prefix"] = PrefixSubFieldType(
                f"{name}._index_prefix", analyzer, None, {})
            return SearchAsYouTypeFieldType(name, analyzer, params)
        raise MapperParsingError(f"No handler for type [{ftype}] declared on field [{name}]")

    def _rebuild_mapping_def(self) -> None:
        root: dict = {}
        for name in sorted(self._fields):
            ft = self._fields[name]
            if isinstance(ft, RuntimeFieldType):
                continue                 # rendered under "runtime"
            if "#" in name:
                continue                 # join-family id columns: internal
            parts = name.split(".")
            # Place under parent's "fields" if parent exists and is a leaf
            # (multi-field), else nest via "properties".
            parent = ".".join(parts[:-1])
            if parent and parent in self._fields and \
                    not isinstance(self._fields[parent], ObjectFieldType):
                continue  # rendered inline below as multi-field
            node = root
            for p in parts[:-1]:
                node = node.setdefault(p, {"type": "object", "properties": {}})
                node = node.setdefault("properties", {})
            entry = ft.to_mapping()
            subfields = {
                n.split(".")[-1]: self._fields[n].to_mapping()
                for n in self._fields
                if n.startswith(name + ".") and "." not in n[len(name) + 1:]
                and not isinstance(ft, ObjectFieldType)
                # synthetic siblings re-register from the parent's type on
                # merge; rendering them as multi-fields would round-trip
                # them into plain text fields (double indexing)
                and not isinstance(self._fields[n], PrefixSubFieldType)}
            if subfields:
                entry["fields"] = subfields
            node[parts[-1]] = entry
        mapping_def: dict = {"properties": root}
        if self.runtime_defs:
            mapping_def["runtime"] = dict(self.runtime_defs)
        if self.dynamic is not True:
            mapping_def["dynamic"] = self.dynamic
        if not self.source_enabled:
            mapping_def["_source"] = {"enabled": False}
        self._mapping_def = mapping_def

    def mapping_dict(self) -> dict:
        if not self._mapping_def.get("properties") and \
                len(self._mapping_def) == 1:
            return {}               # a bare empty mapping serializes as {}
        return self._mapping_def

    def field_type(self, name: str) -> Optional[MappedFieldType]:
        ft = self._field_type_raw(name)
        if isinstance(ft, AliasFieldType):
            return self._field_type_raw(ft.path)
        if ft is None and "." in name:
            # flattened sub-paths resolve to synthetic keyword types
            # (FlattenedFieldMapper.KeyedFlattenedFieldType)
            parts = name.split(".")
            for i in range(len(parts) - 1, 0, -1):
                anc = self._field_type_raw(".".join(parts[:i]))
                if isinstance(anc, FlattenedFieldType):
                    return KeywordFieldType(name, 2 ** 31 - 1, False, {})
                if anc is not None:
                    break
        return ft

    def _field_type_raw(self, name: str) -> Optional[MappedFieldType]:
        return self._fields.get(name)

    def field_names(self) -> List[str]:
        return sorted(self._fields)

    def fields_of_type(self, *type_names: str) -> List[MappedFieldType]:
        return [f for f in self._fields.values() if f.type_name in type_names]

    # -- document parsing ----------------------------------------------------

    def parse_document(self, doc_id: str, source: dict,
                       routing: Optional[str] = None) -> ParsedDocument:
        if not isinstance(source, dict):
            raise MapperParsingError("document source must be a JSON object")
        parsed = ParsedDocument(doc_id=doc_id, source=source, routing=routing)
        if routing is not None:
            # _routing indexes as a metadata keyword (RoutingFieldMapper)
            parsed.keyword_terms.setdefault("_routing", []).append(routing)
        dc = source.get("_doc_count")
        if dc is not None:
            if not isinstance(dc, int) or isinstance(dc, bool) or dc <= 0:
                raise MapperParsingError(
                    f"[_doc_count] field value must be a positive integer,"
                    f" got [{dc}]")
            parsed.numeric_values.setdefault("_doc_count",
                                             []).append(float(dc))
        self._parse_object("", source, parsed)
        # constant_keyword: every doc of the index carries the constant
        # (term queries must match docs that omitted the field)
        for fname, ft0 in self._fields.items():
            if isinstance(ft0, ConstantKeywordFieldType) and \
                    ft0.value is not None:
                if getattr(ft0, "_pinned_dirty", False):
                    # a first-doc pin changes the rendered mapping
                    ft0._pinned_dirty = False
                    self._rebuild_mapping_def()
                if fname not in parsed.keyword_terms:
                    parsed.keyword_terms[fname] = [ft0.value]
        if len(parsed.nested_docs) > self.nested_limit:
            raise IllegalArgumentError(
                f"The number of nested documents has exceeded the allowed "
                f"limit of [{self.nested_limit}]. This limit can be set "
                f"by changing the [index.mapping.nested_objects.limit] "
                f"index level setting.")
        if parsed.dynamic_updates:
            self.merge({"properties": parsed.dynamic_updates})
        return parsed

    def _parse_object(self, prefix: str, obj: dict, parsed: ParsedDocument) -> None:
        for key, value in obj.items():
            full = f"{prefix}{key}"
            if value is None:
                continue
            if full == "_doc_count":
                continue          # meta field, handled in parse_document
            ft = self._fields.get(full)
            if isinstance(ft, NestedFieldType):
                children = value if isinstance(value, list) else [value]
                for ci, child in enumerate(children):
                    if not isinstance(child, dict):
                        raise MapperParsingError(
                            f"object mapping for [{full}] tried to parse "
                            f"field as object, but got a non-object value")
                    child_parsed = ParsedDocument(
                        doc_id=f"{parsed.doc_id}#{full}#{ci}", source=child)
                    child_parsed.dynamic_updates = parsed.dynamic_updates
                    self._parse_object(f"{full}.", child, child_parsed)
                    parsed.nested_docs.append((full, child_parsed))
                continue
            if isinstance(value, dict) and (ft is None or isinstance(ft, ObjectFieldType)):
                if ft is None:
                    if self._check_dynamic(full):
                        self._parse_object(f"{full}.", value, parsed)
                else:
                    self._parse_object(f"{full}.", value, parsed)
                continue
            if ft is None:
                ft = self._dynamic_map(full, value, parsed)
                if ft is None:
                    continue
            if isinstance(value, list) and not isinstance(ft, DenseVectorFieldType) \
                    and not (isinstance(ft, GeoPointFieldType) and value
                             and isinstance(value[0], numbers.Number)):
                values = value
            else:
                values = [value]
            for v in values:
                if v is None:
                    continue
                if isinstance(ft, AliasFieldType):
                    raise MapperParsingError(
                        f"Cannot write to a field alias [{full}].")
                try:
                    self._index_leaf(ft, full, v, parsed)
                except MapperParsingError:
                    # ignore_malformed drops the bad VALUE, keeps the doc
                    # and records the field in the _ignored meta field
                    if not ft.params.get("ignore_malformed"):
                        raise
                    parsed.keyword_terms.setdefault("_ignored",
                                                    []).append(full)

    def _maybe_geo(self, full: str, value: dict, parsed: ParsedDocument) -> bool:
        return False  # dynamic geo detection is off, like the reference default

    def _check_dynamic(self, field: str) -> bool:
        if self.dynamic == "strict":
            raise MapperParsingError(
                f"mapping set to strict, dynamic introduction of [{field}] "
                f"within [_doc] is not allowed", )
        return self.dynamic is not False and self.dynamic != "false"

    def _dynamic_map(self, full: str, value: Any,
                     parsed: ParsedDocument) -> Optional[MappedFieldType]:
        if not self._check_dynamic(full):
            return None
        sample = value[0] if isinstance(value, list) and value else value
        if sample is None:
            return None
        if isinstance(sample, bool):
            spec = {"type": "boolean"}
        elif isinstance(sample, int):
            spec = {"type": "long"}
        elif isinstance(sample, float):
            spec = {"type": "double"}
        elif isinstance(sample, str):
            # date detection (DynamicFieldsBuilder: date_detection default
            # true for strict_date_optional_time-shaped strings)
            if _looks_date(sample.strip()):
                spec = {"type": "date"}
            else:
                spec = {"type": "text", "fields": {"keyword": {
                    "type": "keyword", "ignore_above": 256}}}
        elif isinstance(sample, list):
            return None  # empty/odd nested list
        else:
            return None
        # record for merge into the mapping (nested path → nested spec)
        parts = full.split(".")
        node = parsed.dynamic_updates
        for p in parts[:-1]:
            node = node.setdefault(p, {"type": "object", "properties": {}})
            node = node.setdefault("properties", {})
        node[parts[-1]] = spec
        ft = self._build_field(full, spec["type"], spec)
        self._fields[full] = ft
        if "fields" in spec:
            for sub, subspec in spec["fields"].items():
                self._fields[f"{full}.{sub}"] = self._build_field(
                    f"{full}.{sub}", subspec["type"], subspec)
        return ft

    def _index_leaf(self, ft: MappedFieldType, full: str, value: Any,
                    parsed: ParsedDocument) -> None:
        if isinstance(ft, ObjectFieldType):
            return
        if isinstance(ft, TextFieldType):
            text = ft.parse_value(value)
            toks = parsed.text_tokens.setdefault(full, [])
            # Lucene places the first token of value N+1 at
            # last_position + position_increment_gap(100) + 1
            base_pos = (toks[-1].position + 101) if toks else 0
            new = ft.analyzer.analyze(text)
            for t in new:
                toks.append(Token(t.term, t.position + base_pos,
                                  t.start_offset, t.end_offset))
            if isinstance(ft, SearchAsYouTypeFieldType):
                pref = parsed.text_tokens.setdefault(
                    f"{full}._index_prefix", [])
                for t in new:
                    for n in range(2, min(len(t.term),
                                          ft.MAX_PREFIX) + 1):
                        pref.append(Token(t.term[:n],
                                          t.position + base_pos,
                                          t.start_offset, t.end_offset))
        elif isinstance(ft, IpFieldType):
            s, num = ft.parse_value(value)
            parsed.keyword_terms.setdefault(full, []).append(s)
            parsed.numeric_values.setdefault(full, []).append(num)
        elif isinstance(ft, RangeFieldType):
            lo, hi = ft.parse_value(value)
            parsed.numeric_values.setdefault(f"{full}._gte", []).append(lo)
            parsed.numeric_values.setdefault(f"{full}._lte", []).append(hi)
        elif isinstance(ft, BinaryFieldType):
            ft.parse_value(value)            # validate; stored in _source
            # presence for exists queries via the _field_names meta field
            # (the reference's FieldNamesFieldMapper)
            parsed.keyword_terms.setdefault("_field_names",
                                            []).append(full)
        elif isinstance(ft, JoinFieldType):
            if isinstance(value, str):
                rel, parent_id = value, None
            elif isinstance(value, dict):
                rel = value.get("name")
                parent_id = value.get("parent")
            else:
                raise MapperParsingError(
                    f"failed to parse join field [{full}]")
            if rel not in ft.all_names():
                raise MapperParsingError(
                    f"unknown join name [{rel}] for field [{full}]")
            parsed.keyword_terms.setdefault(full, []).append(rel)
            if ft.parent_rel_of(rel) is not None:
                if parent_id is None:
                    raise MapperParsingError(
                        f"[parent] is missing for join field [{full}]")
                parsed.keyword_terms.setdefault(
                    ft.id_field_for(rel), []).append(str(parent_id))
            if rel in ft.relations:
                # a doc whose relation has children of its own stores
                # its OWN id in that relation's family column (multi-
                # level joins: parent -> child -> grand_child)
                parsed.keyword_terms.setdefault(
                    f"{full}#{rel}", []).append(parsed.doc_id)
        elif isinstance(ft, PercolatorFieldType):
            from ..search.query_dsl import parse_query
            try:
                parse_query(value)       # the stored query must parse
            except Exception as e:
                raise MapperParsingError(
                    f"failed to parse query for field [{full}]: {e}")
            parsed.keyword_terms.setdefault("_field_names",
                                            []).append(full)
        elif isinstance(ft, ConstantKeywordFieldType):
            v = ft.index_value(value)
            if v is not None:
                parsed.keyword_terms.setdefault(full, []).append(v)
        elif isinstance(ft, VersionFieldType):
            v = ft.parse_value(value)
            if v is not None:
                parsed.keyword_terms.setdefault(full, []).append(v)
                k = ft.sort_key(v)
                if k is not None:
                    # paired numeric order key → semver-correct sorting
                    parsed.numeric_values.setdefault(full, []).append(k)
        elif isinstance(ft, FlattenedFieldType):
            if not isinstance(value, (dict, list)):
                raise MapperParsingError(
                    f"Failed to parse object: expecting an object but "
                    f"got [{type(value).__name__}] for field [{full}]")
            for path, leaf in ft.leaves(value):
                parsed.keyword_terms.setdefault(full, []).append(leaf)
                if path:
                    parsed.keyword_terms.setdefault(
                        f"{full}.{path}", []).append(leaf)
        elif isinstance(ft, KeywordFieldType):
            v = ft.parse_value(value)
            if v is not None:
                parsed.keyword_terms.setdefault(full, []).append(v)
        elif isinstance(ft, CompletionFieldType):
            inputs, weight, cvals = ft.parse_value(value)
            parsed.keyword_terms.setdefault(full, []).extend(inputs)
            parsed.numeric_values.setdefault(f"{full}._weight",
                                             []).append(float(weight))
            for cname, toks in ft.context_tokens(cvals,
                                                 parsed.source).items():
                parsed.keyword_terms.setdefault(
                    f"{full}._ctx_{cname}", []).extend(toks)
        elif isinstance(ft, DenseVectorFieldType):
            parsed.vectors[full] = ft.parse_value(value)
        elif isinstance(ft, GeoPointFieldType):
            lat, lon = ft.parse_value(value)
            parsed.geo_points.setdefault(full, []).append((lat, lon))
            # paired positional columns (lockstep append, like range fields'
            # _gte/_lte) so distance/grid queries and aggs read doc values
            parsed.numeric_values.setdefault(f"{full}._lat", []).append(lat)
            parsed.numeric_values.setdefault(f"{full}._lon", []).append(lon)
        elif isinstance(ft, GeoShapeFieldType):
            geom = ft.parse_value(value)
            x1, y1, x2, y2 = geom.bbox()
            # bbox columns: presence (exists) + coarse pre-filter
            parsed.numeric_values.setdefault(full, []).append(0.0)
            for key, v in (("_minx", x1), ("_miny", y1),
                           ("_maxx", x2), ("_maxy", y2)):
                parsed.numeric_values.setdefault(
                    f"{full}.{key}", []).append(v)
        elif isinstance(ft, RankFeatureFieldType):
            parsed.numeric_values.setdefault(full, []).append(
                ft.parse_value(value))
        elif isinstance(ft, RankFeaturesFieldType):
            feats = ft.parse_value(value)
            parsed.numeric_values.setdefault(full, []).append(0.0)
            for feat, fv in feats.items():
                parsed.numeric_values.setdefault(
                    f"{full}.{feat}", []).append(fv)
        elif isinstance(ft, AggregateMetricDoubleFieldType):
            metrics = ft.parse_value(value)
            # the bare name carries default_metric so term/range/sort
            # resolve like the reference's default_metric delegation
            parsed.numeric_values.setdefault(full, []).append(
                metrics[ft.default_metric])
            for m, v in metrics.items():
                parsed.numeric_values.setdefault(
                    f"{full}.{m}", []).append(v)
        elif isinstance(ft, (NumberFieldType, DateFieldType, BooleanFieldType,
                             TokenCountFieldType)):
            parsed.numeric_values.setdefault(full, []).append(ft.parse_value(value))
            if isinstance(ft, DateFieldType) and ft.nanos:
                parsed.int64_values.setdefault(full, []).append(
                    parse_date_nanos(value, ft.format, ft.locale))
        # index multi-fields too
        for sub_name in list(self._fields):
            if sub_name.startswith(full + ".") and "." not in sub_name[len(full) + 1:]:
                sub = self._fields[sub_name]
                if isinstance(sub, (ObjectFieldType, PrefixSubFieldType)) \
                        or sub_name == full:
                    continue
                if not isinstance(ft, ObjectFieldType) and not isinstance(
                        sub, (ObjectFieldType,)):
                    # only leaf multi-fields of leaf parents
                    if isinstance(sub, CompletionFieldType):
                        inputs, weight, cvals = sub.parse_value(value)
                        parsed.keyword_terms.setdefault(
                            sub_name, []).extend(inputs)
                        parsed.numeric_values.setdefault(
                            f"{sub_name}._weight", []).append(float(weight))
                        for cname, toks in sub.context_tokens(
                                cvals, parsed.source).items():
                            parsed.keyword_terms.setdefault(
                                f"{sub_name}._ctx_{cname}",
                                []).extend(toks)
                    elif isinstance(sub, KeywordFieldType):
                        v = sub.parse_value(value)
                        if v is not None:
                            parsed.keyword_terms.setdefault(sub_name, []).append(v)
                    elif isinstance(sub, (NumberFieldType, DateFieldType,
                                          BooleanFieldType,
                                          TokenCountFieldType)):
                        try:
                            parsed.numeric_values.setdefault(
                                sub_name, []).append(sub.parse_value(value))
                        except MapperParsingError:
                            if not (sub.params or {}).get(
                                    "ignore_malformed"):
                                raise
                    elif isinstance(sub, TextFieldType):
                        toks = parsed.text_tokens.setdefault(sub_name, [])
                        base_pos = (toks[-1].position + 101) if toks else 0
                        for t in sub.analyzer.analyze(str(value)):
                            toks.append(Token(t.term, t.position + base_pos,
                                              t.start_offset, t.end_offset))
