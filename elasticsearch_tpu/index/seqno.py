"""Sequence numbers and replication checkpoints.

Re-design of the reference's seq-no subsystem
(``index/seqno/LocalCheckpointTracker.java``, ``ReplicationTracker.java``,
``RetentionLease*.java``): every engine operation gets a monotonically
increasing sequence number; the *local checkpoint* is the highest seq-no
below which every op has been processed; the *global checkpoint* is the
minimum local checkpoint across the in-sync replication group and is the
durable resume point for replica recovery.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Set

NO_OPS_PERFORMED = -1
UNASSIGNED_SEQ_NO = -2


class LocalCheckpointTracker:
    """Tracks processed seq-nos; checkpoint advances over contiguous runs."""

    def __init__(self, max_seq_no: int = NO_OPS_PERFORMED,
                 local_checkpoint: int = NO_OPS_PERFORMED):
        self._max_seq_no = max_seq_no
        self._checkpoint = local_checkpoint
        self._pending: Set[int] = set()

    def generate_seq_no(self) -> int:
        self._max_seq_no += 1
        return self._max_seq_no

    def advance_max_seq_no(self, seq_no: int) -> None:
        """Note a seq-no assigned elsewhere (replica path)."""
        self._max_seq_no = max(self._max_seq_no, seq_no)

    def mark_processed(self, seq_no: int) -> None:
        if seq_no <= self._checkpoint:
            return
        self._pending.add(seq_no)
        while self._checkpoint + 1 in self._pending:
            self._checkpoint += 1
            self._pending.discard(self._checkpoint)

    def fast_forward(self, seq_no: int) -> None:
        """Restore a persisted checkpoint: everything <= seq_no is known
        processed (used on recovery; reference: the local checkpoint handed
        to ``LocalCheckpointTracker``'s constructor from the safe commit)."""
        if seq_no <= self._checkpoint:
            return
        self._checkpoint = seq_no
        self._max_seq_no = max(self._max_seq_no, seq_no)
        self._pending = {s for s in self._pending if s > seq_no}
        while self._checkpoint + 1 in self._pending:
            self._checkpoint += 1
            self._pending.discard(self._checkpoint)

    @property
    def checkpoint(self) -> int:
        return self._checkpoint

    @property
    def max_seq_no(self) -> int:
        return self._max_seq_no

    def pending_count(self) -> int:
        return len(self._pending)


@dataclass
class RetentionLease:
    """History retention marker (reference: ``RetentionLease.java``): ops at
    or above ``retaining_seq_no`` must be kept for the lease holder (a
    recovering replica / CCR follower)."""

    lease_id: str
    retaining_seq_no: int
    timestamp_millis: float
    source: str


@dataclass
class CheckpointState:
    local_checkpoint: int = UNASSIGNED_SEQ_NO
    global_checkpoint: int = UNASSIGNED_SEQ_NO
    in_sync: bool = False
    tracked: bool = False


class ReplicationTracker:
    """Primary-side view of the replication group
    (reference: ``ReplicationTracker.java``, ~1.5k LoC): which allocations
    are in-sync, their local checkpoints, the computed global checkpoint,
    and retention leases for history."""

    def __init__(self, allocation_id: str, local_tracker: LocalCheckpointTracker,
                 lease_expiry_millis: float = 12 * 3600 * 1000):
        self.allocation_id = allocation_id
        self.local_tracker = local_tracker
        self.primary_mode = False
        self.checkpoints: Dict[str, CheckpointState] = {
            allocation_id: CheckpointState(in_sync=True, tracked=True)}
        self.leases: Dict[str, RetentionLease] = {}
        self.lease_expiry_millis = lease_expiry_millis
        self._global_checkpoint = UNASSIGNED_SEQ_NO

    # -- mode ----------------------------------------------------------------

    def activate_primary_mode(self, local_checkpoint: int) -> None:
        self.primary_mode = True
        st = self.checkpoints[self.allocation_id]
        st.local_checkpoint = local_checkpoint
        st.in_sync = True
        st.tracked = True
        self._recompute_global_checkpoint()

    # -- replication group management ---------------------------------------

    def init_tracking(self, allocation_id: str) -> None:
        self.checkpoints.setdefault(allocation_id, CheckpointState(tracked=True))
        self.checkpoints[allocation_id].tracked = True

    def mark_in_sync(self, allocation_id: str, local_checkpoint: int) -> None:
        st = self.checkpoints.setdefault(allocation_id, CheckpointState())
        st.local_checkpoint = max(st.local_checkpoint, local_checkpoint)
        st.in_sync = True
        st.tracked = True
        self._recompute_global_checkpoint()

    def remove_allocation(self, allocation_id: str) -> None:
        if allocation_id != self.allocation_id:
            self.checkpoints.pop(allocation_id, None)
            self._recompute_global_checkpoint()

    def update_local_checkpoint(self, allocation_id: str,
                                local_checkpoint: int) -> None:
        st = self.checkpoints.get(allocation_id)
        if st is None:
            return
        st.local_checkpoint = max(st.local_checkpoint, local_checkpoint)
        self._recompute_global_checkpoint()

    def update_global_checkpoint_on_replica(self, global_checkpoint: int) -> None:
        self._global_checkpoint = max(self._global_checkpoint, global_checkpoint)

    def _recompute_global_checkpoint(self) -> None:
        in_sync = [st.local_checkpoint for st in self.checkpoints.values()
                   if st.in_sync]
        if in_sync and all(cp != UNASSIGNED_SEQ_NO for cp in in_sync):
            gcp = min(in_sync)
            self._global_checkpoint = max(self._global_checkpoint, gcp)

    @property
    def global_checkpoint(self) -> int:
        return self._global_checkpoint

    def in_sync_allocation_ids(self) -> Set[str]:
        return {aid for aid, st in self.checkpoints.items() if st.in_sync}

    # -- retention leases ----------------------------------------------------

    def add_lease(self, lease_id: str, retaining_seq_no: int,
                  source: str) -> RetentionLease:
        lease = RetentionLease(lease_id, retaining_seq_no,
                               time.time() * 1000, source)
        self.leases[lease_id] = lease
        return lease

    def renew_lease(self, lease_id: str, retaining_seq_no: int) -> None:
        lease = self.leases.get(lease_id)
        if lease is not None:
            lease.retaining_seq_no = max(lease.retaining_seq_no, retaining_seq_no)
            lease.timestamp_millis = time.time() * 1000

    def remove_lease(self, lease_id: str) -> None:
        self.leases.pop(lease_id, None)

    def expire_leases(self, now_millis: Optional[float] = None) -> None:
        now = now_millis if now_millis is not None else time.time() * 1000
        expired = [lid for lid, l in self.leases.items()
                   if now - l.timestamp_millis > self.lease_expiry_millis]
        for lid in expired:
            del self.leases[lid]

    def min_retained_seq_no(self) -> int:
        """Ops at/above this must be retained for lease holders; with no
        leases, retain above the global checkpoint."""
        floor = self._global_checkpoint + 1
        if self.leases:
            floor = min(floor, min(l.retaining_seq_no for l in self.leases.values()))
        return floor
