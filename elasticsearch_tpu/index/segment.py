"""Immutable index segments with device-resident postings and doc values.

Re-design of the Lucene segment (the reference's storage unit under
``index/engine/InternalEngine.java`` — Lucene is a dependency there, see
SURVEY.md §2.9.1) as TPU-friendly dense arrays:

- text fields   → flat CSR postings ``(doc_ids int32[P], tf float32[P])``
  with host-side term dictionary / offsets / doc freqs, plus per-doc token
  counts ``doc_len float32[N]``. Scored eagerly by ``ops/bm25.py``.
- keyword/numeric/date/boolean fields → (value, doc) pair columns on device
  for range masks and ``segment_sum`` aggregations; numeric values are stored
  as float32 *offsets from a per-segment float64 base* so large magnitudes
  (epoch millis, longs) keep precision on TPU (f64 is not TPU-resident);
  exact float64 copies stay on the host for sort keys and fetch.
- dense_vector fields → ``float32[N, D]`` matrices for einsum kNN.
- term positions stay host-side (numpy CSR) for phrase verification; the
  candidate set is computed on device first.

A segment is immutable once built; deletes are a host-side liveness bitmask
(device mask materialized lazily), mirroring Lucene's liveDocs.

All device arrays are padded to power-of-two buckets (``utils/shapes.py``) so
XLA programs are reused across segments of similar size. Padded doc slots are
inert: postings never reference them and scatter uses OOB-drop semantics.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils.shapes import round_up_pow2
from .mapping import ParsedDocument

# Deliberately late/lazy jax import so host-only paths (translog replay, etc.)
# work without touching the device.
import jax
import jax.numpy as jnp


# ---------------------------------------------------------------------------
# Per-field data
# ---------------------------------------------------------------------------


@dataclass
class TextFieldData:
    """CSR postings for one text field."""

    term_ids: Dict[str, int]                 # term -> tid
    df: np.ndarray                           # int32[V] doc freq per term
    offsets: np.ndarray                      # int64[V+1] into flat postings
    docs_host: np.ndarray                    # int32[P]
    tf_host: np.ndarray                      # float32[P]
    doc_len_host: np.ndarray                 # float32[N]
    sum_dl: float                            # total tokens in field
    field_doc_count: int                     # docs that have this field
    total_term_freq: np.ndarray              # int64[V] sum tf per term
    pos_offsets: np.ndarray                  # int64[P+1] into pos_flat
    pos_flat: np.ndarray                     # int32[total positions]
    docs_dev: jnp.ndarray = None             # int32[P_pad]
    tf_dev: jnp.ndarray = None               # float32[P_pad]
    doc_len_dev: jnp.ndarray = None          # float32[N_pad]

    def term_run(self, term: str) -> Tuple[int, int, int]:
        """(start, length, df) of a term's postings run; absent → (P, 0, 0)."""
        tid = self.term_ids.get(term)
        if tid is None:
            return int(self.docs_host.shape[0]), 0, 0
        return (int(self.offsets[tid]), int(self.offsets[tid + 1] - self.offsets[tid]),
                int(self.df[tid]))

    def positions_for(self, term: str, doc: int) -> np.ndarray:
        """Host-side positions of ``term`` in local doc ``doc`` (for phrase)."""
        start, length, _ = self.term_run(term)
        if length == 0:
            return np.empty(0, np.int32)
        run = self.docs_host[start:start + length]
        i = np.searchsorted(run, doc)
        if i >= length or run[i] != doc:
            return np.empty(0, np.int32)
        p = start + i
        return self.pos_flat[self.pos_offsets[p]:self.pos_offsets[p + 1]]


@dataclass
class KeywordFieldData:
    """Postings + ordinal doc-values pairs for one keyword field."""

    ord_terms: List[str]                     # ord -> term (sorted)
    term_ords: Dict[str, int]                # term -> ord
    df: np.ndarray                           # int32[V]
    offsets: np.ndarray                      # int64[V+1]
    docs_host: np.ndarray                    # int32[P] postings doc ids
    dv_ords_host: np.ndarray                 # int32[M] value ordinal per pair
    dv_docs_host: np.ndarray                 # int32[M] owning doc per pair
    docs_dev: jnp.ndarray = None
    dv_ords_dev: jnp.ndarray = None
    dv_docs_dev: jnp.ndarray = None

    def term_run(self, term: str) -> Tuple[int, int, int]:
        o = self.term_ords.get(term)
        if o is None:
            return int(self.docs_host.shape[0]), 0, 0
        return (int(self.offsets[o]), int(self.offsets[o + 1] - self.offsets[o]),
                int(self.df[o]))


@dataclass
class NumericFieldData:
    """(value, doc) pair column.

    The device column stores each pair's int32 RANK among the segment's
    sorted distinct values, not the value itself: range bounds are
    binary-searched into rank space on the host (exact f64 compares) and
    the device compares integers — exact at ANY value span, where a
    float32 value/offset column would overflow or collapse neighboring
    values (the round-2 ±inf corruption on wide-span longs/doubles)."""

    base: float                              # float64 min value (store manifest)
    vals_host: np.ndarray                    # float64[M] exact values
    docs_host: np.ndarray                    # int32[M]
    uniq_vals: np.ndarray = None             # float64[U] sorted distinct values
    ranks_dev: jnp.ndarray = None            # int32[M_pad] rank per pair
    docs_dev: jnp.ndarray = None             # int32[M_pad]


@dataclass
class VectorFieldData:
    matrix_host: np.ndarray                  # float32[N, D]
    exists: np.ndarray                       # bool[N]
    matrix_dev: jnp.ndarray = None           # float32[N_pad, D]
    # segment-lifetime corpus invariant, built once on first use and
    # reused by every cosine query against this column (segments are
    # immutable, so it can never go stale)
    unit_dev: jnp.ndarray = None             # row-normalized matrix_dev

    def unit_matrix_dev(self) -> jnp.ndarray:
        """Unit-normalized rows — computed ONCE per segment column, not
        per query (the old cosine path re-normalized the whole segment on
        every knn clause / script_score call)."""
        if self.unit_dev is None:
            m = self.matrix_dev
            self.unit_dev = m / jnp.maximum(
                jnp.linalg.norm(m, axis=-1, keepdims=True), 1e-12)
        return self.unit_dev


# ---------------------------------------------------------------------------
# Segment
# ---------------------------------------------------------------------------


class Segment:
    """One immutable generation of indexed docs, device arrays attached."""

    def __init__(self, seg_id: str, n_docs: int, doc_uids: List[str],
                 sources: List[Optional[dict]], seq_nos: np.ndarray,
                 text_fields: Dict[str, TextFieldData],
                 keyword_fields: Dict[str, KeywordFieldData],
                 numeric_fields: Dict[str, NumericFieldData],
                 vector_fields: Dict[str, VectorFieldData],
                 parent_of: Optional[np.ndarray] = None,
                 nested_paths: Optional[Dict[str, np.ndarray]] = None):
        self.seg_id = seg_id
        self.n_docs = n_docs
        self.n_pad = round_up_pow2(max(n_docs, 1))
        self.doc_uids = doc_uids
        self.sources = sources
        self.seq_nos = seq_nos                      # int64[N]
        self.text_fields = text_fields
        self.keyword_fields = keyword_fields
        self.numeric_fields = numeric_fields
        self.vector_fields = vector_fields
        # block join: child -> parent pointers (self for top-level docs)
        # and per-nested-path child marks; parent_mask excludes hidden
        # children from every top-level query/agg/fetch
        self.parent_of = (parent_of if parent_of is not None
                          else np.arange(n_docs, dtype=np.int32))
        self.nested_paths = nested_paths or {}
        self.parent_mask = self.parent_of == np.arange(n_docs,
                                                       dtype=np.int32)
        self._parent_mask_dev: Optional[jnp.ndarray] = None
        self._children_of: Optional[Dict[int, List[int]]] = None
        self.live = np.ones(n_docs, dtype=bool)     # host liveness (deletes)
        self._live_dev: Optional[jnp.ndarray] = None
        self._fv_columns: Dict[str, np.ndarray] = {}
        # hidden nested children never resolve by uid: a user doc whose id
        # happens to collide with a synthetic child uid must win
        self._uid_to_doc: Dict[str, int] = {
            u: i for i, u in enumerate(doc_uids) if self.parent_mask[i]}
        self._upload()

    # -- device upload -------------------------------------------------------

    def _upload(self) -> None:
        n_pad = self.n_pad
        for f in self.text_fields.values():
            p_pad = round_up_pow2(max(f.docs_host.shape[0], 1))
            f.docs_dev = jnp.asarray(_pad_to(f.docs_host, p_pad, n_pad), jnp.int32)
            f.tf_dev = jnp.asarray(_pad_to(f.tf_host, p_pad, 0.0), jnp.float32)
            f.doc_len_dev = jnp.asarray(_pad_to(f.doc_len_host, n_pad, 0.0),
                                        jnp.float32)
        for f in self.keyword_fields.values():
            p_pad = round_up_pow2(max(f.docs_host.shape[0], 1))
            m_pad = round_up_pow2(max(f.dv_docs_host.shape[0], 1))
            f.docs_dev = jnp.asarray(_pad_to(f.docs_host, p_pad, n_pad), jnp.int32)
            f.dv_ords_dev = jnp.asarray(_pad_to(f.dv_ords_host, m_pad, 0), jnp.int32)
            f.dv_docs_dev = jnp.asarray(_pad_to(f.dv_docs_host, m_pad, n_pad),
                                        jnp.int32)
        for f in self.numeric_fields.values():
            m_pad = round_up_pow2(max(f.docs_host.shape[0], 1))
            f.uniq_vals, inv = np.unique(f.vals_host, return_inverse=True)
            f.ranks_dev = jnp.asarray(_pad_to(inv.astype(np.int32), m_pad, 0),
                                      jnp.int32)
            f.docs_dev = jnp.asarray(_pad_to(f.docs_host, m_pad, n_pad), jnp.int32)
        for f in self.vector_fields.values():
            d = f.matrix_host.shape[1] if f.matrix_host.size else 0
            mat = np.zeros((n_pad, d), np.float32)
            mat[: f.matrix_host.shape[0]] = f.matrix_host
            f.matrix_dev = jnp.asarray(mat)

    # -- liveness ------------------------------------------------------------

    def delete_doc(self, local_doc: int) -> None:
        self.live[local_doc] = False
        # cascade: a doc's hidden nested descendants die with it
        # (recursive — multi-level nesting chains parent pointers)
        if len(self.nested_paths):
            if self._children_of is None:
                cmap: Dict[int, List[int]] = {}
                for c in np.flatnonzero(~self.parent_mask):
                    cmap.setdefault(int(self.parent_of[c]), []).append(int(c))
                self._children_of = cmap
            stack = list(self._children_of.get(local_doc, ()))
            while stack:
                c = stack.pop()
                self.live[c] = False
                stack.extend(self._children_of.get(c, ()))
        self._live_dev = None

    @property
    def live_dev(self) -> jnp.ndarray:
        if self._live_dev is None:
            padded = np.zeros(self.n_pad, dtype=bool)
            padded[: self.n_docs] = self.live
            self._live_dev = jnp.asarray(padded)
        return self._live_dev

    @property
    def parent_mask_dev(self) -> jnp.ndarray:
        if self._parent_mask_dev is None:
            padded = np.zeros(self.n_pad, dtype=bool)
            padded[: self.n_docs] = self.parent_mask
            self._parent_mask_dev = jnp.asarray(padded)
        return self._parent_mask_dev

    @property
    def has_nested(self) -> bool:
        return bool(self.nested_paths)

    @property
    def live_count(self) -> int:
        return int(self.live.sum())

    @property
    def live_parent_count(self) -> int:
        """User-visible doc count: hidden nested children excluded (the
        reference's _count likewise only sees top-level docs)."""
        if not self.nested_paths:
            return int(self.live.sum())
        return int((self.live & self.parent_mask).sum())

    def find_doc(self, uid: str) -> Optional[int]:
        d = self._uid_to_doc.get(uid)
        if d is not None and self.live[d]:
            return d
        return None

    # -- doc-values columns --------------------------------------------------

    def numeric_first_value_column(self, field: str) -> np.ndarray:
        """Dense float64[n_pad] column of the field's first value per doc
        (NaN where absent); cached. Sort keys, script doc access and
        function_score all read this."""
        col = self._fv_columns.get(field)
        if col is None:
            col = np.full(self.n_pad, np.nan)
            f = self.numeric_fields.get(field)
            if f is not None:
                # reverse fill keeps the first (lowest-index) pair per doc
                col[f.docs_host[::-1]] = f.vals_host[::-1]
            self._fv_columns[field] = col
        return col

    # -- stats for idf -------------------------------------------------------

    def field_stats(self, field: str) -> Tuple[float, int]:
        """(sum_dl, field_doc_count) for avgdl computation."""
        f = self.text_fields.get(field)
        if f is None:
            return 0.0, 0
        return f.sum_dl, f.field_doc_count

    def term_df(self, field: str, term: str) -> int:
        f = self.text_fields.get(field)
        if f is not None:
            return f.term_run(term)[2]
        kf = self.keyword_fields.get(field)
        if kf is not None:
            return kf.term_run(term)[2]
        return 0


def _pad_to(arr: np.ndarray, size: int, fill) -> np.ndarray:
    if arr.shape[0] == size:
        return arr
    out = np.full(size, fill, dtype=arr.dtype)
    out[: arr.shape[0]] = arr
    return out


# ---------------------------------------------------------------------------
# Builder
# ---------------------------------------------------------------------------


class SegmentBuilder:
    """Accumulates parsed documents (the in-memory indexing buffer —
    analogue of Lucene's IndexWriter RAM buffer inside
    ``index/engine/InternalEngine.java:123``) and freezes them into a
    :class:`Segment` on refresh."""

    def __init__(self, seg_id: str):
        self.seg_id = seg_id
        self.doc_uids: List[str] = []
        self.sources: List[Optional[dict]] = []
        self.seq_nos: List[int] = []
        # local ids deleted before the segment is frozen (doc updated or
        # removed while still in the buffer); applied to `live` at build()
        self.deleted: set = set()
        # block-join bookkeeping: child local id -> parent local id / path
        self.parent_of: Dict[int, int] = {}
        self.nested_path_of: Dict[int, str] = {}
        # field -> term -> list[(doc, tf)] built doc-ascending
        self._text_postings: Dict[str, Dict[str, List[Tuple[int, int]]]] = {}
        # field -> term -> doc -> positions
        self._text_positions: Dict[str, Dict[str, Dict[int, List[int]]]] = {}
        self._doc_len: Dict[str, Dict[int, int]] = {}
        self._keyword_postings: Dict[str, Dict[str, List[int]]] = {}
        self._keyword_values: Dict[str, List[Tuple[int, str]]] = {}  # (doc, term)
        self._numeric_values: Dict[str, List[Tuple[int, float]]] = {}
        # exact int64 doc values (date_nanos): host-side, never floats
        self._int64_values: Dict[str, List[Tuple[int, int]]] = {}
        self._vectors: Dict[str, Dict[int, np.ndarray]] = {}

    def __len__(self) -> int:
        return len(self.doc_uids)

    @property
    def n_docs(self) -> int:
        return len(self.doc_uids)

    def add(self, parsed: ParsedDocument, seq_no: int,
            store_source: bool = True) -> int:
        """Index one parsed document (plus its block-joined nested
        children, Lucene block order: children first, RECURSIVELY — a
        grandchild's parent pointer targets its immediate nested parent,
        so multi-level paths join level by level like the reference's
        stacked ToParentBlockJoin); returns the top local doc id."""
        return self._add_block(parsed, seq_no, store_source)

    def _add_block(self, parsed: ParsedDocument, seq_no: int,
                   store_source: bool) -> int:
        child_ids = []
        for path, child in parsed.nested_docs:
            cid = self._add_block(child, seq_no, store_source=False)
            self.nested_path_of[cid] = path
            child_ids.append(cid)
        doc = self._add_single(parsed, seq_no, store_source)
        for cid in child_ids:
            self.parent_of[cid] = doc
        return doc

    def _add_single(self, parsed: ParsedDocument, seq_no: int,
                    store_source: bool = True) -> int:
        doc = len(self.doc_uids)
        self.doc_uids.append(parsed.doc_id)
        self.sources.append(parsed.source if store_source else None)
        self.seq_nos.append(seq_no)

        for field, tokens in parsed.text_tokens.items():
            postings = self._text_postings.setdefault(field, {})
            positions = self._text_positions.setdefault(field, {})
            per_term_pos: Dict[str, List[int]] = {}
            for t in tokens:
                per_term_pos.setdefault(t.term, []).append(t.position)
            for term, plist in per_term_pos.items():
                postings.setdefault(term, []).append((doc, len(plist)))
                positions.setdefault(term, {})[doc] = plist
            if tokens:
                self._doc_len.setdefault(field, {})[doc] = len(tokens)

        for field, terms in parsed.keyword_terms.items():
            postings = self._keyword_postings.setdefault(field, {})
            values = self._keyword_values.setdefault(field, [])
            for term in set(terms):
                postings.setdefault(term, []).append(doc)
            for term in terms:
                values.append((doc, term))

        for field, vals in parsed.numeric_values.items():
            lst = self._numeric_values.setdefault(field, [])
            for v in vals:
                lst.append((doc, float(v)))

        for field, ivals in parsed.int64_values.items():
            ilst = self._int64_values.setdefault(field, [])
            for v in ivals:
                ilst.append((doc, int(v)))

        for field, vec in parsed.vectors.items():
            self._vectors.setdefault(field, {})[doc] = vec

        return doc

    def build(self) -> Segment:
        n = len(self.doc_uids)

        text_fields: Dict[str, TextFieldData] = {}
        for field, postings in self._text_postings.items():
            terms_sorted = sorted(postings)
            term_ids = {t: i for i, t in enumerate(terms_sorted)}
            v = len(terms_sorted)
            df = np.zeros(v, np.int32)
            ttf = np.zeros(v, np.int64)
            offsets = np.zeros(v + 1, np.int64)
            total = sum(len(postings[t]) for t in terms_sorted)
            docs = np.zeros(total, np.int32)
            tf = np.zeros(total, np.float32)
            pos_offsets = np.zeros(total + 1, np.int64)
            pos_chunks: List[List[int]] = []
            p = 0
            positions = self._text_positions[field]
            for i, term in enumerate(terms_sorted):
                run = postings[term]
                df[i] = len(run)
                offsets[i] = p
                for d, f_ in run:
                    docs[p] = d
                    tf[p] = f_
                    ttf[i] += f_
                    pos_chunks.append(positions[term][d])
                    pos_offsets[p + 1] = pos_offsets[p] + f_
                    p += 1
                offsets[i + 1] = p
            pos_flat = (np.concatenate([np.asarray(c, np.int32) for c in pos_chunks])
                        if pos_chunks else np.empty(0, np.int32))
            dl_map = self._doc_len.get(field, {})
            doc_len = np.zeros(n, np.float32)
            for d, l in dl_map.items():
                doc_len[d] = l
            text_fields[field] = TextFieldData(
                term_ids=term_ids, df=df, offsets=offsets, docs_host=docs,
                tf_host=tf, doc_len_host=doc_len, sum_dl=float(doc_len.sum()),
                field_doc_count=len(dl_map), total_term_freq=ttf,
                pos_offsets=pos_offsets, pos_flat=pos_flat)

        keyword_fields: Dict[str, KeywordFieldData] = {}
        for field, postings in self._keyword_postings.items():
            terms_sorted = sorted(postings)
            term_ords = {t: i for i, t in enumerate(terms_sorted)}
            v = len(terms_sorted)
            df = np.zeros(v, np.int32)
            offsets = np.zeros(v + 1, np.int64)
            total = sum(len(postings[t]) for t in terms_sorted)
            docs = np.zeros(total, np.int32)
            p = 0
            for i, term in enumerate(terms_sorted):
                run = postings[term]
                df[i] = len(run)
                offsets[i] = p
                docs[p: p + len(run)] = run
                p += len(run)
                offsets[i + 1] = p
            pairs = self._keyword_values.get(field, [])
            dv_docs = np.asarray([d for d, _ in pairs], np.int32)
            dv_ords = np.asarray([term_ords[t] for _, t in pairs], np.int32)
            keyword_fields[field] = KeywordFieldData(
                ord_terms=terms_sorted, term_ords=term_ords, df=df,
                offsets=offsets, docs_host=docs, dv_ords_host=dv_ords,
                dv_docs_host=dv_docs)

        numeric_fields: Dict[str, NumericFieldData] = {}
        for field, pairs in self._numeric_values.items():
            docs = np.asarray([d for d, _ in pairs], np.int32)
            vals = np.asarray([v for _, v in pairs], np.float64)
            base = float(vals.min()) if vals.size else 0.0
            numeric_fields[field] = NumericFieldData(
                base=base, vals_host=vals, docs_host=docs)

        vector_fields: Dict[str, VectorFieldData] = {}
        for field, rows in self._vectors.items():
            dim = next(iter(rows.values())).shape[0]
            mat = np.zeros((n, dim), np.float32)
            exists = np.zeros(n, bool)
            for d, vec in rows.items():
                mat[d] = vec
                exists[d] = True
            vector_fields[field] = VectorFieldData(matrix_host=mat, exists=exists)

        parent_of = np.arange(n, dtype=np.int32)
        for c, p in self.parent_of.items():
            parent_of[c] = p
        nested_paths: Dict[str, np.ndarray] = {}
        for c, path in self.nested_path_of.items():
            m = nested_paths.get(path)
            if m is None:
                m = nested_paths[path] = np.zeros(n, bool)
            m[c] = True
        seg = Segment(self.seg_id, n, list(self.doc_uids), list(self.sources),
                      np.asarray(self.seq_nos, np.int64), text_fields,
                      keyword_fields, numeric_fields, vector_fields,
                      parent_of=parent_of, nested_paths=nested_paths)
        # exact int64 doc values (date_nanos) ride as a host-side extra:
        # {field: (docs int32[], vals int64[])}
        seg.int64_fields = {
            f: (np.asarray([d for d, _ in pairs], np.int32),
                np.asarray([v for _, v in pairs], np.int64))
            for f, pairs in self._int64_values.items()}
        for local in self.deleted:
            seg.delete_doc(local)
        return seg
